"""Distributed serving: worker registry, cross-worker replies, lease replay.

Reference mapping (``continuous/HTTPSourceV2.scala``):

- ``DriverServiceUtils.createDriverService`` (:133-194) — the driver-side
  HTTP registry workers report to → :class:`DriverRegistry`.
- ``WorkerClient.reportServerToDriver`` (:460-468) →
  :class:`RegistryClient.register`.
- ``WorkerServer.replyTo`` incl. cross-machine forwarding (:535+) —
  request ids embed the owning worker (``<worker_id>/<uuid>``) and a reply
  raised on any process is routed to the owner's internal ``__reply__``
  endpoint → :meth:`DistributedServingServer.reply_to`.
- epoch-tagged ``historyQueues``/``recoveredPartitions`` replay on task
  retry (:488-517) → work *leases*: peers pull batches through the
  internal ``__lease__`` endpoint; a lease that is not answered before its
  deadline (worker crash) bumps the epoch and requeues the requests on
  the owner, so the client-held connection is answered by a surviving
  worker with no client-visible error.

The data plane stays HTTP (like the reference's worker mesh); the
model-compute plane inside each worker is the jitted pipeline.
"""

from __future__ import annotations

import base64
import dataclasses
import http.client
import json
import queue
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler

import numpy as np

from ..core import DataFrame
from ..io.http.schema import HTTPRequestData, HTTPResponseData
from ..obs import registry as _obs
from ..obs.export import flight_recorder as _flight
from ..obs.fleet import (fleet_aggregator as _fleet_agg, own_worker_samples,
                         local_fleet_snapshot, straggler_workers)
from ..obs.profile import process_label
from ..obs.propagation import TraceContext
from ..obs.tracing import tracer as _tracer
from ..resilience import breaker_for, drop_breaker
from ..resilience.faults import WorkerKilled, injector as _faults
from .native_front import NativeServingServer
from .server import (CachedRequest, LowLatencyHandlerMixin,
                     QuietHTTPServer, ServingServer, _LOG, _SERVICES)

# per-worker execute timing lands in the SAME family the StepProfiler
# fills (profile_step_seconds), labelled worker=<id> — the straggler
# detector reads per-rank/per-worker means off one family
_h_worker_step = _obs.histogram(
    "profile_step_seconds",
    "per-stage wall seconds, split host-dispatch vs device")

# mesh-internal traffic series (obs subsystem): every lease/reply hop
# counts calls and payload bytes, so a scrape shows where cross-worker
# bandwidth and replay churn go
_m_mesh_calls = _obs.counter(
    "serving_mesh_calls_total",
    "mesh-internal endpoint hits, by service/endpoint")
_m_mesh_bytes = _obs.counter(
    "serving_mesh_bytes_total",
    "mesh-internal payload bytes, by service/endpoint/direction")
_m_mesh_reply_seconds = _obs.histogram(
    "serving_mesh_reply_seconds",
    "cross-worker reply forwarding wall seconds")
_m_lease_replays = _obs.counter(
    "serving_lease_replays_total",
    "requests replayed because their lease expired (worker death)")
# failure-detection series (resilience subsystem)
_m_worker_deaths = _obs.counter(
    "resilience_worker_deaths_total",
    "workers marked dead by registry heartbeat liveness, by service")
_m_registry_workers = _obs.gauge(
    "serving_registry_workers", "live registered workers, by service")

# registry suffix under which compute workers (remote_worker_loop)
# heartbeat their liveness — the ingest servers' failure detector reads
# this table to requeue a dead worker's leases without waiting for the
# full lease deadline
COMPUTE_SUFFIX = "#compute"


@dataclasses.dataclass
class ServiceInfo:
    """Reference ``ServiceInfo`` — one worker's public coordinates,
    plus its load signal (queue depth and EWMA request latency) so
    registry clients can route to the least-loaded worker instead of
    blindly. Defaults keep old registry payloads parseable."""
    name: str
    worker_id: str
    host: str
    port: int
    api_path: str = "/"
    queue_depth: int = 0
    ewma_latency_ms: float = 0.0


def pick_least_loaded(infos: list[ServiceInfo],
                      avoid=None) -> ServiceInfo | None:
    """Least-loaded routing: order by queue depth first (requests
    already committed to a worker), then EWMA latency (how fast it
    drains them). Ties break on worker_id for determinism. Workers the
    fleet health plane flags as stragglers (``avoid``; defaults to the
    live ``fleet_straggler`` flag set) sort behind every healthy worker
    — still pickable when they are all that's left."""
    if not infos:
        return None
    if avoid is None:
        avoid = straggler_workers()
    return min(infos, key=lambda i: (1 if i.worker_id in avoid else 0,
                                     i.queue_depth, i.ewma_latency_ms,
                                     i.worker_id))


def _req_to_json(r: HTTPRequestData) -> dict:
    return {"url": r.url, "method": r.method, "headers": dict(r.headers),
            "entity_b64": base64.b64encode(r.entity or b"").decode()}


def _req_from_json(d: dict) -> HTTPRequestData:
    return HTTPRequestData(
        url=d["url"], method=d["method"], headers=d["headers"],
        entity=base64.b64decode(d["entity_b64"]) or None)


def _resp_to_json(r: HTTPResponseData) -> dict:
    return {"status_code": r.status_code, "reason": r.reason,
            "headers": dict(r.headers),
            "entity_b64": base64.b64encode(r.entity or b"").decode()}


def _resp_from_json(d: dict) -> HTTPResponseData:
    return HTTPResponseData(
        status_code=d["status_code"], reason=d.get("reason", ""),
        headers=d.get("headers", {}),
        entity=base64.b64decode(d["entity_b64"]) or None)


def _post(host: str, port: int, path: str, payload: dict | bytes,
          timeout: float = 10.0) -> tuple[int, bytes]:
    body = payload if isinstance(payload, bytes) \
        else json.dumps(payload).encode()
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


# ----------------------------------------------------------------- registry
class DriverRegistry:
    """Driver-side worker registry (reference ``DriverServiceUtils``
    service, ``HTTPSourceV2.scala:133-194``), now with heartbeat
    liveness: every registration stamps ``last_seen``, and a monitor
    thread marks workers dead — deregistering them and counting
    ``resilience_worker_deaths_total`` — once they miss beats for
    ``heartbeat_timeout`` seconds. Registered workers already
    re-register on a heartbeat (``DistributedServingServer`` every
    ``load_report_interval``, ``remote_worker_loop`` every poll beat),
    so a crashed worker disappears from the table instead of routing
    traffic forever. ``heartbeat_timeout=0`` disables pruning."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 heartbeat_timeout: float = 15.0):
        self._services: dict[str, dict[str, ServiceInfo]] = {}
        self._last_seen: dict[tuple[str, str], float] = {}
        self.heartbeat_timeout = float(heartbeat_timeout)
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        registry = self

        class Handler(LowLatencyHandlerMixin,
                      BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if self.path == "/register":
                    info = ServiceInfo(**body)
                    with registry._lock:
                        registry._services.setdefault(
                            info.name, {})[info.worker_id] = info
                        registry._last_seen[(info.name, info.worker_id)] \
                            = time.monotonic()
                        registry._set_workers_gauge_locked(info.name)
                    out = registry._table_json(info.name)
                elif self.path == "/unregister":
                    with registry._lock:
                        registry._services.get(body["name"], {}).pop(
                            body["worker_id"], None)
                        registry._last_seen.pop(
                            (body["name"], body["worker_id"]), None)
                        registry._set_workers_gauge_locked(body["name"])
                    out = b"[]"
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def do_GET(self):
                if self.path.startswith("/services/"):
                    name = self.path.split("/services/", 1)[1]
                    out = registry._table_json(name)
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(out)))
                    self.end_headers()
                    self.wfile.write(out)
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()



        self._httpd = QuietHTTPServer((host, port), Handler)
        self.address = self._httpd.server_address
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._liveness = threading.Thread(target=self._monitor_liveness,
                                          daemon=True)

    def _table_json(self, name: str) -> bytes:
        with self._lock:
            infos = list(self._services.get(name, {}).values())
        return json.dumps([dataclasses.asdict(i) for i in infos]).encode()

    def workers(self, name: str) -> list[ServiceInfo]:
        with self._lock:
            return list(self._services.get(name, {}).values())

    def _set_workers_gauge_locked(self, name: str) -> None:
        _m_registry_workers.set(len(self._services.get(name, {})),
                                service=name)

    def _monitor_liveness(self):
        """Mark-dead + deregister on missed heartbeats: the mesh's
        failure detector. Everything routing on the table (lease pulls,
        least-loaded picks, reply forwarding) stops seeing a worker
        within one heartbeat_timeout of its last beat."""
        poll = max(self.heartbeat_timeout / 4.0, 0.05)
        while not self._stopping.wait(poll):
            cutoff = time.monotonic() - self.heartbeat_timeout
            with self._lock:
                dead = [(n, w) for (n, w), seen in self._last_seen.items()
                        if seen < cutoff]
                for name, worker_id in dead:
                    self._services.get(name, {}).pop(worker_id, None)
                    self._last_seen.pop((name, worker_id), None)
                    self._set_workers_gauge_locked(name)
            for name, worker_id in dead:
                _m_worker_deaths.inc(1, service=name)
                _LOG.warning("registry: worker %s/%s missed heartbeats "
                             "for %.1fs — marked dead", name, worker_id,
                             self.heartbeat_timeout)

    def start(self):
        self._thread.start()
        if self.heartbeat_timeout > 0:
            self._liveness.start()
        return self

    def stop(self):
        self._stopping.set()
        self._httpd.shutdown()
        self._httpd.server_close()


class RegistryClient:
    """Worker-side registry access (reference ``WorkerClient``)."""

    def __init__(self, driver_address):
        if isinstance(driver_address, str):
            host, port = driver_address.rsplit(":", 1)
            driver_address = (host, int(port))
        self.driver_address = tuple(driver_address)

    def register(self, info: ServiceInfo) -> list[ServiceInfo]:
        status, body = _post(*self.driver_address, "/register",
                             dataclasses.asdict(info))
        if status != 200:
            raise IOError(f"driver registry refused registration: {status}")
        return [ServiceInfo(**d) for d in json.loads(body)]

    def unregister(self, name: str, worker_id: str) -> None:
        _post(*self.driver_address, "/unregister",
              {"name": name, "worker_id": worker_id})

    def workers(self, name: str) -> list[ServiceInfo]:
        conn = http.client.HTTPConnection(*self.driver_address, timeout=10)
        try:
            conn.request("GET", f"/services/{name}")
            resp = conn.getresponse()
            return [ServiceInfo(**d) for d in json.loads(resp.read())]
        finally:
            conn.close()

    def least_loaded(self, name: str) -> ServiceInfo | None:
        """The worker a load-aware client should talk to: each
        ``ServiceInfo`` carries the queue depth / EWMA latency its
        owner last reported (``DistributedServingServer`` re-registers
        on a heartbeat), and :func:`pick_least_loaded` orders them."""
        return pick_least_loaded(self.workers(name))


# ------------------------------------------------------------------- worker
class DistributedServingServer(ServingServer):
    """A ServingServer that participates in a worker mesh.

    Adds: registration with the driver registry; internal ``__reply__``
    (cross-worker reply delivery) and ``__lease__`` (peer work pulling)
    endpoints; and a lease monitor that replays expired leases with an
    epoch bump — the reference's recovered-partition replay, with worker
    death detected by deadline instead of task re-registration.
    """

    def __init__(self, name: str, driver_address, *,
                 worker_id: str | None = None, host: str = "127.0.0.1",
                 port: int = 0, lease_timeout: float = 5.0,
                 mesh_secret: str = "", load_report_interval: float = 1.0,
                 **kwargs):
        super().__init__(name, host=host, port=port, **kwargs)
        # heartbeat cadence for re-registering this worker's load signal
        # (queue depth + EWMA latency) with the driver registry
        self.load_report_interval = float(load_report_interval)
        self.worker_id = worker_id or uuid.uuid4().hex[:12]
        self.lease_timeout = lease_timeout
        # the internal endpoints share the public listener; when the
        # server binds beyond localhost, set a mesh_secret so untrusted
        # clients cannot lease (read!) other clients' queued requests —
        # every internal payload must then carry {"secret": <value>}
        self.mesh_secret = mesh_secret
        # replay-wave counter (observability; dedup itself is carried by
        # CachedRequest's reply-exactly-once latch, so a late reply from a
        # presumed-dead worker can still win if nobody answered yet)
        self.epoch = 0
        # lease entries are (deadline, cached[, lessee_worker_id]);
        # 2-tuples stay accepted (tests and old callers poke them in)
        self._leases: dict[str, tuple] = {}
        self.registry = RegistryClient(driver_address)
        self._peers: dict[str, ServiceInfo] = {}
        base = "" if self.api_path == "/" else self.api_path
        self._routes[f"{base}/__reply__"] = self._handle_reply
        self._routes[f"{base}/__lease__"] = self._handle_lease
        # fleet telemetry ingest (obs.fleet): compute workers push
        # their registry samples + pending spans here on the heartbeat
        # cadence, next to __lease__/__reply__ on the same listener
        self._routes[f"{base}/__fleet__"] = self._handle_fleet
        # pod xprof fanout (obs.xprof, ISSUE 20): on a mesh worker, one
        # capture POST also captures every registered peer through
        # their __fleet__ endpoint — override the shared-state handler
        # under BOTH keys the base class registered
        self._query_routes["/debug/xprof"] = self._fanout_xprof_route
        if base:
            self._query_routes[f"{base}/debug/xprof"] = \
                self._fanout_xprof_route
        self._monitor = threading.Thread(target=self._monitor_leases,
                                         daemon=True)
        self._load_reporter = threading.Thread(target=self._report_load,
                                               daemon=True)
        self._stopping = threading.Event()

    def _new_id(self) -> str:
        # the owning worker rides inside the id, so any process can route
        # a reply home (reference: machine ip inside the id triple)
        return f"{self.worker_id}/{uuid.uuid4()}"

    @property
    def service_info(self) -> ServiceInfo:
        return ServiceInfo(name=self.name, worker_id=self.worker_id,
                           host=self.address[0], port=self.address[1],
                           api_path=self.api_path,
                           queue_depth=int(self.queue.qsize()),
                           ewma_latency_ms=float(
                               getattr(self, "_lat_ewma", 0.0)) * 1e3)

    def start(self):
        super().start()
        infos = self.registry.register(self.service_info)
        with self._lock:
            for info in infos:
                self._peers[info.worker_id] = info
        self._monitor.start()
        self._load_reporter.start()
        return self

    def stop(self):
        self._stopping.set()
        try:
            self.registry.unregister(self.name, self.worker_id)
        except Exception:
            pass
        super().stop()

    def _check_secret(self, d: dict) -> bool:
        import hmac
        return (not self.mesh_secret
                or hmac.compare_digest(str(d.get("secret", "")),
                                       self.mesh_secret))

    # -- internal endpoints -------------------------------------------------
    def _handle_reply(self, body: bytes) -> tuple[int, bytes]:
        # named injection point for the reply hop: an injected error
        # status is returned to the posting worker (whose retry/replay
        # machinery must absorb it); a drop aborts the connection the
        # way a dying ingest server would
        act = _faults.apply("mesh.reply", key=self.worker_id)
        if act is not None:
            return act.status, b'{"error": "injected fault"}'
        d = json.loads(body)
        if not self._check_secret(d):
            return 403, b'{"error": "bad mesh secret"}'
        # counted only past the secret check: the series measures real
        # cross-worker traffic, not junk sprayed at the public port
        _m_mesh_calls.inc(1, service=self.name, endpoint="__reply__")
        _m_mesh_bytes.inc(len(body), service=self.name,
                          endpoint="__reply__", direction="in")
        # the worker's spans ride home in the reply payload: fold them
        # into this process's flight recorder BEFORE the reply latch
        # fires, so note_request (triggered by the waiting handler) sees
        # the complete cross-process tree
        if d.get("spans"):
            _flight.ingest(d["spans"])
        # history read and lease drop in ONE critical section: the lease
        # monitor (its own thread) and handler threads race on _leases —
        # graftcheck's lock-discipline pass gates this (docs/analysis.md)
        with self._lock:
            cached = self.history.get(d["id"])
            self._leases.pop(d["id"], None)
        if cached is None:
            return 404, b'{"delivered": false}'
        ok = cached.reply(_resp_from_json(d["response"]))
        return 200, json.dumps({"delivered": bool(ok)}).encode()

    def _handle_fleet(self, body: bytes) -> tuple[int, bytes]:
        """Worker telemetry push: ``{"worker", "process", "snapshot",
        "spans", "secret"}``. The snapshot merges into the process-wide
        FleetAggregator (worker/process labels stamped there); pending
        spans flushed from the worker's flight recorder fold into the
        ingest-side recorder so a tree that dies on the worker can
        still be closed or marked incomplete here."""
        d = json.loads(body or b"{}")
        if not self._check_secret(d):
            return 403, b'{"error": "bad mesh secret"}'
        _m_mesh_calls.inc(1, service=self.name, endpoint="__fleet__")
        _m_mesh_bytes.inc(len(body), service=self.name,
                          endpoint="__fleet__", direction="in")
        if d.get("spans"):
            _flight.ingest(d["spans"])
        snap = d.get("snapshot")
        if isinstance(snap, dict):
            _fleet_agg.ingest_snapshot(
                snap, process=d.get("process"), worker=d.get("worker"),
                channel="heartbeat")
        xp = d.get("xprof")
        if isinstance(xp, dict):
            # xprof fanout leg (obs.xprof): a peer's capture request
            # rides the fleet channel — run a LOCAL capture and answer
            # with its result so the fanning-out worker can aggregate
            # per-rank outcomes
            from ..obs.xprof import xprof_captures
            import urllib.parse as _up
            q = _up.urlencode({k: xp[k] for k in ("duration_ms", "tag")
                               if xp.get(k) not in (None, "")})
            return xprof_captures.handle_query(q, b"")
        return 200, b'{"ok": true}'

    def _handle_lease(self, body: bytes) -> tuple[int, bytes]:
        # named injection point for the lease hop (the worker absorbs
        # an injected error by skipping this ingest for a round)
        act = _faults.apply("mesh.lease", key=self.worker_id)
        if act is not None:
            return act.status, b'{"error": "injected fault"}'
        d = json.loads(body or b"{}")
        if not self._check_secret(d):
            return 403, b'{"error": "bad mesh secret"}'
        n = int(d.get("max", 64))
        # lessee id (when the puller identifies itself): lets the lease
        # monitor requeue this batch the moment the registry marks the
        # lessee dead, instead of waiting out the full lease deadline
        lessee = str(d.get("worker", "")) or None
        batch: list[CachedRequest] = []
        while len(batch) < n:
            try:
                c = self.queue.get_nowait()
            except queue.Empty:
                break
            # same expiry contract as the local execution path: a
            # request whose deadline lapsed while queued is answered
            # 429 here, not serialized and shipped to a remote worker
            # that would spend device time on a reply nobody awaits
            if self.scheduler.shed_if_expired(c):
                continue
            batch.append(c)
        deadline = time.monotonic() + self.lease_timeout
        with self._lock:
            for c in batch:
                self._leases[c.id] = (deadline, c, lessee)
        # the lease drain bypasses next_batch, so the queue-wait spans
        # are annotated here (outside _lock — span emission does
        # registry/sink work)
        self.scheduler.annotate_queue_spans(batch)
        out = []
        for c in batch:
            entry = {"id": c.id, "request": _req_to_json(c.request)}
            tenant = getattr(c, "tenant", "")
            if tenant:
                # the tenant rides the lease: compute workers label
                # their telemetry (and any per-tenant batching they
                # grow) with the quota bucket the ingest side resolved
                entry["tenant"] = tenant
            sp = getattr(c, "span", None)
            if sp is not None:
                # trace context rides the lease: the compute worker
                # parents its execute/device spans into THIS request's
                # tree instead of starting a fresh root
                entry["trace"] = {"trace_id": sp.trace_id,
                                  "span_id": sp.span_id}
            out.append(entry)
        payload = json.dumps(out).encode()
        _m_mesh_calls.inc(1, service=self.name, endpoint="__lease__")
        _m_mesh_bytes.inc(len(payload), service=self.name,
                          endpoint="__lease__", direction="out")
        return 200, payload

    def _report_load(self):
        # load heartbeat: re-registering refreshes this worker's
        # queue_depth / ewma_latency_ms in the driver table, the signal
        # least_loaded routing reads. It runs on its OWN thread because
        # register() blocks up to its HTTP timeout when the driver is
        # slow or partitioned — inline on the lease monitor that stall
        # would delay the expiry replay clients depend on. Best-effort:
        # an unreachable driver just means a stale load table.
        while not self._stopping.wait(self.load_report_interval):
            try:
                # injection point: a dropped heartbeat simulates a
                # partitioned ingest server (the registry will mark it
                # dead after heartbeat_timeout)
                _faults.apply("worker.heartbeat", key=self.worker_id)
                table = {info.worker_id: info
                         for info in self.registry.register(
                             self.service_info)}
                # the registry table is the truth: evict departed peers
                # and their breakers — worker ids are per-process
                # identities, so without eviction a mesh with churn
                # retains a breaker + gauge series per worker forever
                with self._lock:
                    gone = set(self._peers) - set(table)
                    self._peers = table
                for wid in gone:
                    drop_breaker(f"mesh:{self.name}:{wid}")
                    # departed peer: its fleet source (and any
                    # fleet_* series keyed by it) go too — bounded
                    # eviction on death, not just staleness
                    _fleet_agg.evict_worker(wid)
            except WorkerKilled:
                return  # injected death: stop beating, keep the body
            except Exception:
                pass

    def _live_lessees(self) -> set[str] | None:
        """Live compute workers from the registry's heartbeat table
        (``<name>#compute``); None when the driver is unreachable —
        detection then falls back to deadline-only expiry rather than
        declaring everyone dead on a registry blip."""
        try:
            infos = self.registry.workers(self.name + COMPUTE_SUFFIX)
        except Exception:
            return None
        return {i.worker_id for i in infos}

    def _monitor_leases(self):
        while not self._stopping.wait(
                min(self.lease_timeout / 4.0, 0.25)):
            now = time.monotonic()
            # snapshot under the lock; the registry round trip and the
            # expiry scan run on the copy (holding _lock across an HTTP
            # call would stall every handler thread's lease/reply)
            with self._lock:
                entries = list(self._leases.items())
            # the registry round trip is only worth taking when an
            # identified lessee actually holds a lease — an idle ingest
            # must not generate 4 control-plane requests per second
            live = self._live_lessees() if any(
                len(e) > 2 and e[2] for _, e in entries) else None
            expired = []
            for i, entry in entries:
                lessee = entry[2] if len(entry) > 2 else None
                if entry[0] < now:
                    expired.append(i)
                elif (live is not None and lessee is not None
                        and lessee not in live):
                    # failure DETECTION beat the deadline: an identified
                    # lessee always registers its heartbeat BEFORE its
                    # first lease pull (remote_worker_loop's loop
                    # order), so absence from the live table means the
                    # registry marked it dead — requeue now. Anonymous
                    # pullers (no worker id in the lease request) keep
                    # the deadline-only contract. A false positive (a
                    # stalled-but-alive worker) only risks a duplicate
                    # reply, which CachedRequest's reply-exactly-once
                    # latch absorbs.
                    expired.append(i)
            if not expired:
                continue
            self.epoch += 1  # a worker died mid-lease: new replay wave
            _LOG.warning("service %s: %d leases expired, replaying at "
                         "epoch %d", self.name, len(expired), self.epoch)
            to_replay = []
            dead_lessees = set()
            with self._lock:
                for i in expired:
                    # a reply may land concurrently and pop the lease
                    # first — that request is answered, nothing to replay
                    entry = self._leases.pop(i, None)
                    if entry is not None and not entry[1]._event.is_set():
                        to_replay.append(entry[1])
                        if len(entry) > 2 and entry[2]:
                            dead_lessees.add(entry[2])
            # replays re-enter the scheduler (its own condition variable)
            # outside _lock: lock order stays one-directional
            for cached in to_replay:
                _m_lease_replays.inc(1, service=self.name)
                # the dead worker's spans (whatever its heartbeat
                # flushed home) become a closed, incomplete-flagged
                # tree instead of rotting orphaned in pending; if the
                # replay completes elsewhere, note_request fills in
                # the real outcome and the flag stays
                sp = getattr(cached, "span", None)
                if sp is not None:
                    _flight.mark_incomplete(
                        sp.trace_id, reason="lease expired: worker lost")
                self.replay(cached)
            for wid in dead_lessees:
                # dead lessee: drop its fleet source + keyed series
                _fleet_agg.evict_worker(wid)

    def _fanout_xprof_route(self, query: str,
                            body: bytes) -> tuple[int, bytes]:
        """``/debug/xprof`` with pod fanout: list/fetch stay local, but
        a capture request (``duration_ms=``) also POSTs an ``xprof``
        payload to every registered peer's ``__fleet__`` endpoint —
        concurrently, while the local capture blocks for its duration —
        so ONE request captures every rank into its own rank-suffixed
        directory. Peer failures are itemized, never fatal: the local
        capture's status decides the response code."""
        import urllib.parse as _up
        from ..obs.xprof import xprof_captures
        q = _up.parse_qs(query or "")
        if "duration_ms" not in q:
            return xprof_captures.handle_query(query, body)
        try:
            duration_s = float(q["duration_ms"][0]) / 1e3
        except (TypeError, ValueError, IndexError):
            duration_s = 0.0
        with self._lock:
            peers = [i for wid, i in self._peers.items()
                     if wid != self.worker_id]
        payload = {"xprof": {"duration_ms": (q["duration_ms"] or [""])[0],
                             "tag": (q.get("tag") or [""])[0]},
                   "secret": self.mesh_secret}
        results: dict[str, dict] = {}
        lock = threading.Lock()

        def _one(info: ServiceInfo) -> None:
            base = "" if info.api_path == "/" else info.api_path
            try:
                status, resp = _post(info.host, info.port,
                                     f"{base}/__fleet__", payload,
                                     timeout=duration_s + 10.0)
                try:
                    parsed = json.loads(resp or b"{}")
                except ValueError:
                    parsed = {"raw": len(resp)}
                entry = {"status": status, "result": parsed}
            except Exception as e:
                entry = {"status": 0, "error": repr(e)}
            with lock:
                results[info.worker_id] = entry

        threads = [threading.Thread(target=_one, args=(i,), daemon=True)
                   for i in peers]
        for t in threads:
            t.start()
        status, local_body = xprof_captures.handle_query(query, body)
        for t in threads:
            t.join(timeout=duration_s + 15.0)
        try:
            local = json.loads(local_body)
        except ValueError:
            local = {"raw": len(local_body)}
        out = {"worker": self.worker_id, "local_status": status,
               "local": local, "peers": results}
        return status, json.dumps(out, indent=1).encode()

    # -- cross-worker reply routing ----------------------------------------
    def reply_to(self, request_id: str, response: HTTPResponseData) -> bool:
        """Deliver a reply wherever the request was ingested (reference
        ``WorkerServer.replyTo`` cross-machine branch)."""
        owner = request_id.split("/", 1)[0]
        if owner == self.worker_id:
            with self._lock:
                cached = self.history.get(request_id)
                self._leases.pop(request_id, None)
            return cached is not None and cached.reply(response)
        with self._lock:
            info = self._peers.get(owner)
        if info is None:
            # registry refresh happens OUTSIDE the lock (HTTP round
            # trip); only the table merge is a critical section
            fresh = {i.worker_id: i for i in
                     self.registry.workers(self.name)}
            with self._lock:
                self._peers.update(fresh)
                info = self._peers.get(owner)
        if info is None:
            return False
        # per-peer breaker (resilience subsystem): a dead owner fails
        # this forward in microseconds instead of a socket timeout per
        # reply, and half-open probes re-learn the peer when it returns
        breaker = breaker_for(f"mesh:{self.name}:{owner}")
        if not breaker.allow():
            return False
        base = "" if info.api_path == "/" else info.api_path
        # serialized once, measured as actually sent on the wire (json
        # envelope, base64'd entity) — the same measure the receiving
        # _handle_reply takes, so in/out for one hop agree
        payload = json.dumps(
            {"id": request_id,
             "response": _resp_to_json(response),
             "secret": self.mesh_secret}).encode()
        sent = len(payload)
        t0 = time.perf_counter()
        try:
            status, body = _post(info.host, info.port,
                                 f"{base}/__reply__", payload)
        except OSError:
            breaker.record_failure()
            return False  # owner unreachable (crashed); bool contract
        breaker.record_success()
        # observed only for completed round trips: a crashed owner's
        # instant connection-refused (or timeout) sample would misstate
        # healthy forwarding latency
        _m_mesh_reply_seconds.observe(time.perf_counter() - t0,
                                      service=self.name)
        _m_mesh_bytes.inc(sent, service=self.name,
                          endpoint="__reply__", direction="out")
        return status == 200 and json.loads(body).get("delivered", False)


def _worker_fleet_payload(wid: str, secret: str,
                          own_process: bool) -> dict:
    """What a compute worker pushes over ``__fleet__`` each heartbeat.

    A worker that owns its process (a pod rank, or a standalone worker
    process with no in-process ingest) ships its full prefix-filtered
    registry snapshot and DRAINS its local flight recorder's pending
    spans — that flush is what lets the ingest-side recorder close or
    mark-incomplete a tree whose worker later dies. A thread-pool
    worker SHARES the ingest's registry and recorder, so it ships only
    the series already labelled ``worker="<id>"`` and never drains
    (draining would strip the ingest's own in-flight traces).
    ``own_process`` is decided ONCE at worker-loop start: a thread
    worker must never flip to draining just because the servers it
    shares a process with stopped first — that window would strip
    other traces still pending in the shared recorder."""
    pl = process_label()
    if own_process:
        snap = local_fleet_snapshot()
        spans = _flight.pending_spans(drain=True)
    else:
        snap = own_worker_samples(wid)
        spans = []
    return {"worker": wid, "process": pl, "snapshot": snap,
            "spans": spans, "secret": secret}


def _worker_spans(items: list, wid: str, service: str, execute_s: float,
                  out) -> dict[str, list[dict]]:
    """Per-request trace annotation on the compute-worker side: for
    every leased item that carried trace context, emit a
    ``worker.execute`` span (the batch's transform wall time — what
    each rider paid) with a ``worker.device`` child measured by the
    block_until_ready delta on whatever the transform returned. Returns
    ``request id → [span wire dicts]`` for the reply payloads; the
    spans ALSO emit through this process's tracer (local telemetry)."""
    traced = [i for i in items if i.get("trace")]
    if not traced:
        return {}
    t0 = time.perf_counter()
    synced = False
    if out is not None:
        from ..obs.profile import _block_on
        for col in (getattr(out, "columns", None) or ()):
            try:
                if _block_on(out[col]):
                    synced = True
            except Exception:
                pass
    device_s = time.perf_counter() - t0
    spans_by_id: dict[str, list[dict]] = {}
    for i in traced:
        tr = i["trace"]
        parent = TraceContext(trace_id=str(tr.get("trace_id", "")),
                              span_id=str(tr.get("span_id", "")))
        wspan = _tracer.emit_span(
            "worker.execute", parent=parent, seconds=execute_s,
            worker=wid, service=service, rows=len(items))
        dspan = _tracer.emit_span(
            "worker.device", parent=wspan, seconds=device_s,
            worker=wid, synced=synced)
        spans_by_id[str(i["id"])] = [wspan.to_dict(), dspan.to_dict()]
    return spans_by_id


# ---------------------------------------------------------------- pull loop
class _PeerConnections:
    """Persistent keep-alive connections, one per ingest server — the
    reference's ``WorkerClient`` reuses a pooled HttpClient for the same
    reason (``HTTPSourceV2.scala:446-458``)."""

    def __init__(self, timeout: float = 10.0):
        self._conns: dict[tuple[str, int], http.client.HTTPConnection] = {}
        self.timeout = timeout

    def post(self, host: str, port: int, path: str,
             payload: dict) -> tuple[int, bytes]:
        key = (host, port)
        body = json.dumps(payload).encode()
        for attempt in (0, 1):  # one reconnect on a stale keep-alive
            conn = self._conns.get(key)
            if conn is None:
                conn = http.client.HTTPConnection(host, port,
                                                 timeout=self.timeout)
                self._conns[key] = conn
            try:
                conn.request("POST", path, body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                return resp.status, resp.read()
            except (OSError, http.client.HTTPException):
                # stale keep-alive raises HTTPException subclasses
                # (CannotSendRequest/BadStatusLine), not just OSError —
                # either way the connection must be evicted, not reused
                conn.close()
                self._conns.pop(key, None)
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def close(self):
        for c in self._conns.values():
            c.close()
        self._conns.clear()


def remote_worker_loop(driver_address, service_name: str, transform_fn,
                       *, poll_interval: float = 0.01,
                       max_idle_interval: float = 0.2,
                       stop_event: threading.Event | None = None,
                       max_batch: int = 64, mesh_secret: str = "",
                       worker_id: str | None = None,
                       heartbeat_interval: float = 0.25) -> None:
    """A compute worker with no public ingress: leases request batches from
    every registered ingest server, runs the pipeline, and posts replies
    back to each request's owner. Run one per process for model-compute
    scale-out behind fixed ingest endpoints.

    ``transform_fn`` has the ServingQuery contract: DataFrame(id, request)
    → DataFrame(id, reply). Connections to ingest servers are persistent
    keep-alive, and the idle poll backs off from ``poll_interval`` to
    ``max_idle_interval``.

    Failure detection (resilience subsystem): the worker heartbeats its
    liveness to the driver registry under ``<service>#compute`` every
    ``heartbeat_interval`` seconds, and identifies itself on every lease
    pull — an ingest server requeues this worker's leases the moment the
    registry marks it dead, instead of waiting out the lease deadline.
    Lease pulls to each ingest run behind a per-ingest circuit breaker,
    so a dead ingest server costs one socket timeout, not one per poll.
    The loop carries the ``worker.heartbeat`` and ``worker.death``
    injection points (a ``kill`` exits as if SIGKILLed, stranding any
    leased batch — exactly what the replay machinery must absorb).
    """
    client = RegistryClient(driver_address)
    stop_event = stop_event or threading.Event()
    conns = _PeerConnections()
    wid = worker_id or uuid.uuid4().hex[:12]
    # collect this worker's spans locally (idempotent when an ingest in
    # this process already installed): the heartbeat flushes pending
    # spans home so a trace that dies here is not orphaned. Whether
    # this loop OWNS its process (may drain the recorder on flush) is
    # fixed now — _SERVICES can empty out later when co-resident
    # servers stop, and a thread worker that flipped to draining then
    # would strip traces other servers in this process still own.
    own_process = process_label() is not None or not _SERVICES
    _flight.install()
    # AOT warm boot BEFORE the first lease pull: a worker the
    # autoscaler just added loads its fused-segment executables from
    # the on-disk store (core/aot.py) instead of paying a compile storm
    # on first traffic — the scale-up acceptance's mechanism
    from ..core import aot
    aot.maybe_warm(transform_fn, service=service_name)
    liveness = ServiceInfo(name=service_name + COMPUTE_SUFFIX,
                           worker_id=wid, host="0.0.0.0", port=0)
    idle = poll_interval
    last_beat = 0.0
    last_fleet = 0.0
    killed = False
    known_ingests: set[str] = set()
    try:
        while not stop_event.is_set():
            if time.monotonic() - last_beat >= heartbeat_interval:
                try:
                    # injection point: a dropped beat simulates a
                    # partition; a kill raises out of the loop below
                    _faults.apply("worker.heartbeat", key=wid)
                    client.register(liveness)
                    last_beat = time.monotonic()
                except WorkerKilled:
                    killed = True
                    return  # injected death: vanish without unregister
                except Exception:
                    pass  # missed beat; the detector tolerates a few
            if last_beat == 0.0:
                # never successfully registered: do NOT pull leases yet.
                # Identified lease pulls promise "the lessee is in the
                # heartbeat table" — leasing before the first register
                # lands would make the ingest's failure detector requeue
                # work this live worker is actively processing.
                time.sleep(min(heartbeat_interval, max_idle_interval))
                continue
            try:
                infos = client.workers(service_name)
            except Exception:
                time.sleep(max_idle_interval)
                continue
            # evict breakers for ingest servers that left the table —
            # their ids are per-process identities, so a mesh with
            # ingest churn would otherwise accrete breakers forever
            current = {i.worker_id for i in infos}
            for gone in known_ingests - current:
                drop_breaker(f"mesh:{service_name}:ingest:{gone}")
            known_ingests = current
            # fleet telemetry push, heartbeat cadence: this worker's
            # samples + pending-span flush to every ingest server's
            # aggregator. Best-effort — a missed push only means one
            # staler source on that ingest's fleet view.
            if time.monotonic() - last_fleet >= heartbeat_interval:
                fleet_payload = _worker_fleet_payload(
                    wid, mesh_secret, own_process)
                for info in infos:
                    base = "" if info.api_path == "/" else info.api_path
                    try:
                        conns.post(info.host, info.port,
                                   f"{base}/__fleet__", fleet_payload)
                    except Exception:
                        pass
                last_fleet = time.monotonic()
            got = False
            # drain the most-backlogged ingest first (the registry table
            # carries each server's last-reported queue depth)
            infos.sort(key=lambda i: -i.queue_depth)
            for info in infos:
                base = "" if info.api_path == "/" else info.api_path
                breaker = breaker_for(
                    f"mesh:{service_name}:ingest:{info.worker_id}")
                if not breaker.allow():
                    continue  # ingest known-dead; probe after reset
                try:
                    status, body = conns.post(info.host, info.port,
                                              f"{base}/__lease__",
                                              {"max": max_batch,
                                               "secret": mesh_secret,
                                               "worker": wid})
                except Exception:
                    breaker.record_failure()
                    continue  # ingest server died; registry will catch up
                breaker.record_success()
                if status != 200:
                    continue
                items = json.loads(body)
                if not items:
                    continue
                got = True
                # the lease is acknowledged into each request's trace
                # BEFORE the death injection point: if this worker dies
                # mid-batch, these spans are what its last heartbeat
                # flushed home — the ingest's recorder closes the tree
                # as incomplete instead of orphaning it
                for it in items:
                    tr = it.get("trace")
                    if tr:
                        _tracer.emit_span(
                            "worker.lease",
                            parent=TraceContext(
                                trace_id=str(tr.get("trace_id", "")),
                                span_id=str(tr.get("span_id", ""))),
                            seconds=0.0, worker=wid,
                            service=service_name, rows=len(items))
                # injection point AFTER the lease is held: a kill here
                # is the mid-batch worker death the lease replay (and
                # its chaos test) exists for; a "slow" rule here arms a
                # persistent per-worker degradation instead (the
                # sick-but-alive worker load-aware routing must route
                # around)
                _faults.apply("worker.death", key=wid)
                _faults.apply("worker.slow", key=wid)
                ids = np.empty(len(items), object)
                reqs = np.empty(len(items), object)
                ids[:] = [i["id"] for i in items]
                reqs[:] = [_req_from_json(i["request"]) for i in items]
                t0 = time.perf_counter()
                try:
                    out = transform_fn(
                        DataFrame({"id": ids, "request": reqs}))
                    slow = _faults.degradation(wid)
                    if slow > 1.0:
                        # stretch this worker's service time by the
                        # injected factor: latency the mesh observes
                        # (EWMA, lease pacing), not a one-shot spike
                        time.sleep((time.perf_counter() - t0)
                                   * (slow - 1.0))
                    # ServingQuery contract: a transform may reply itself
                    # (send_reply_udf) and return None / no "reply" column
                    pairs = (list(zip(out["id"], out["reply"]))
                             if out is not None and "reply" in getattr(
                                 out, "columns", []) else [])
                except Exception:
                    continue  # lease expiry will replay the batch
                # per-worker execute time (slow-factor inclusive) into
                # the step family — the straggler detector's signal
                _h_worker_step.observe(
                    time.perf_counter() - t0, stage="worker_execute",
                    phase="execute", worker=wid)
                spans_by_id = _worker_spans(
                    items, wid, service_name,
                    time.perf_counter() - t0, out)
                for rid, reply in pairs:
                    try:
                        conns.post(info.host, info.port,
                                   f"{base}/__reply__",
                                   {"id": rid,
                                    "response": _resp_to_json(reply),
                                    "secret": mesh_secret,
                                    # this worker's spans for THIS
                                    # request ride home with the reply,
                                    # completing the ingest server's
                                    # cross-process tree
                                    "spans": spans_by_id.get(rid, [])})
                    except Exception:
                        pass
            if got:
                idle = poll_interval
            else:
                time.sleep(idle)
                idle = min(idle * 2, max_idle_interval)
    except WorkerKilled:
        killed = True
        return  # injected mid-batch death: leased work is stranded
    finally:
        conns.close()
        if not killed:  # a dead worker never says goodbye — the
            try:        # detector, not the socket, reports it
                client.unregister(liveness.name, wid)
            except Exception:
                pass


class NativeDistributedServingServer(DistributedServingServer,
                                     NativeServingServer):
    """Distributed worker whose public ingress is the native epoll front
    (``httpfront.cpp``): the low-tail-latency reactor serves client
    traffic AND the mesh-internal ``__reply__``/``__lease__`` endpoints —
    both fronts share ``_init_shared_state``'s route table, so every
    piece of the distributed logic (registration, cross-worker reply
    routing, lease replay) is inherited unchanged; the MRO routes
    ``DistributedServingServer``'s ``super()`` calls to the native
    front. Raises at construction when the native toolchain is
    unavailable (mirroring ``serving_query(backend="native")``)."""
