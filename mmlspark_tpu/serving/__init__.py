"""Spark-Serving equivalent: pipelines as low-latency web services.

Reference L9 (SURVEY §2.7): HTTP sources/sinks over structured streaming —
``HTTPSource``/``HTTPSink`` (head node), ``DistributedHTTPSource``,
continuous mode with epoch replay (``continuous/HTTPSourceV2.scala``), and
``ServingUDFs.makeReplyUDF/sendReplyUDF``.

TPU-native shape: one process = one host = one server; requests flow
through a dynamic batcher into the (device-resident, pre-compiled)
pipeline; replies are routed back by request id. Fault tolerance keeps the
reference's semantics: in-flight requests are replayed if a batch fails
(the epoch/history-queue mechanism of ``HTTPSourceV2.scala:488-517``).
"""

from .autoscale import (AutoscaleConfig, AutoscaleSignals, Autoscaler,
                        ComputeWorkerPool)
from .deploy import (ModelRegistry, ModelVersion, RolloutConfig,
                     RolloutController, VersionRouter)
from .distributed import (DistributedServingServer, DriverRegistry,
                          NativeDistributedServingServer,
                          RegistryClient, ServiceInfo, pick_least_loaded,
                          remote_worker_loop)
from .llm import (DecodeExecutor, HandoffQueue, LLMEngine,
                  PrefillExecutor, pack_handoff, unpack_handoff)
from .server import ServingServer, bucket_pad, serving_query
from .udfs import make_reply_udf, send_reply_udf
from .dsl import read_stream

__all__ = ["bucket_pad",
           "LLMEngine", "PrefillExecutor", "DecodeExecutor",
           "HandoffQueue", "pack_handoff", "unpack_handoff",
           "Autoscaler", "AutoscaleConfig", "AutoscaleSignals",
           "ComputeWorkerPool",
           "ModelRegistry", "ModelVersion", "VersionRouter",
           "RolloutConfig", "RolloutController",
           "DistributedServingServer", "NativeDistributedServingServer",
           "DriverRegistry", "RegistryClient",
           "ServiceInfo", "ServingServer", "pick_least_loaded",
           "remote_worker_loop",
           "serving_query", "make_reply_udf", "send_reply_udf",
           "read_stream"]
