"""Native serving front: the epoll HTTP server (httpfront.cpp) behind
the same ServingServer interface.

The Python front (``server.py``) spends a thread per connection and
several GIL hand-offs per request — that is the serving p99. Here one
C++ reactor thread owns all sockets; a single Python poller thread
converts ready requests into :class:`CachedRequest`s on the shared
queue, so :class:`ServingQuery`, replay, routing, and the distributed
worker mesh all work unchanged. Replies go straight to the reactor via
``hf_reply`` from whichever thread calls ``CachedRequest.reply``.

Opt in with ``serving_query(..., backend="native")``; falls back to the
Python front when the toolchain is unavailable.

Everything registered in ``ServingServer._init_shared_state`` rides
along unchanged — including the AOT executable-store surfaces
(``GET /debug/aot``, the ``aot_*`` metric family on ``/metrics``), and
the warm boot itself: ``ServingQuery.start`` loads store executables
before this front's poller delivers its first request, so a native
scale-up worker boots hot exactly like the threaded one
(``core/aot.py``, ``docs/aot.md``).
"""

from __future__ import annotations

import ctypes
import logging
import threading
import time
import traceback
from collections import deque

from ..io.http.schema import HTTPRequestData, HTTPResponseData
from ..native.loader import get_httpfront
from ..sched import Shed
from .server import _SERVICES, CachedRequest, ServingServer

_LOG = logging.getLogger("mmlspark_tpu.serving")

_POLL_BATCH = 256


class _NativeCachedRequest(CachedRequest):
    """Replies by id straight into the C++ reactor (exactly once)."""

    def __init__(self, id: str, request: HTTPRequestData, server,
                 native_id: int):
        super().__init__(id=id, request=request)
        self._server = server
        self._native_id = native_id

    def reply(self, response: HTTPResponseData) -> bool:
        # Build the wire bytes BEFORE marking the request answered: a
        # bad header value must fail while the 504 sweep can still take
        # over, not after the exactly-once latch is burned.
        srv = self._server
        body = response.entity or b""
        # deploy plane: echo the serving version before the header
        # blob is built (the threaded front stamps at its own write
        # site — same shared helper, so the fronts cannot drift)
        srv._stamp_version(self, response)
        # every pipeline-set header rides through (Content-Length and
        # Connection are owned by the reactor). CR/LF are stripped from
        # names and values — embedded newlines would otherwise let a
        # header-echoing pipeline be used for response splitting.
        hdrs = dict(response.headers or {})
        hdrs.setdefault("Content-Type", "application/octet-stream")

        def clean(t):
            return str(t).replace("\r", "").replace("\n", "")

        blob = "".join(
            f"{clean(k)}: {clean(v)}\r\n" for k, v in hdrs.items()
            if k.lower() not in ("content-length", "connection")
        ).encode("latin-1", errors="replace")
        if not super().reply(response):
            return False
        srv._lib.hf_reply(srv._handle, self._native_id,
                          int(response.status_code or 500),
                          blob, body, len(body))
        srv.history.pop(self.id, None)
        # same per-route series the threaded front records (obs
        # subsystem); latency runs intake → reply. The request span
        # closes here too — reply() is this front's single exit, on
        # whichever thread delivered the answer (executor, mesh reply
        # hop, or the poller's 504 sweep).
        srv._observe_request(srv.api_path,
                             int(response.status_code or 500),
                             time.perf_counter() - self.created)
        srv._finish_request(self, int(response.status_code or 500))
        return True


class NativeServingServer(ServingServer):
    """ServingServer whose HTTP front is the native epoll reactor."""

    def __init__(self, name: str, host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", reply_timeout: float = 30.0,
                 max_retries: int = 2, max_queue: int = 0,
                 deadline: float = 0.0, max_inflight: int = 0,
                 tenancy=None):
        lib = get_httpfront()
        if lib is None:
            raise RuntimeError(
                "native http front unavailable (no toolchain or "
                "MMLSPARK_TPU_DISABLE_NATIVE=1)")
        self._lib = lib
        out_port = ctypes.c_int(0)
        handle = lib.hf_start(host.encode(), port,
                              ctypes.byref(out_port))
        if handle <= 0:
            raise OSError(-handle, "hf_start failed")
        self._handle = handle
        self._init_shared_state(name, api_path, reply_timeout,
                                max_retries, max_queue, deadline=deadline,
                                max_inflight=max_inflight,
                                tenancy=tenancy)
        self.address = (host, out_port.value)
        self._stop = threading.Event()
        self._poller = threading.Thread(target=self._poll_loop,
                                        daemon=True)
        # (deadline, CachedRequest) for 504s, scanned by the poller
        self._deadlines: deque[tuple[float, CachedRequest]] = deque()
        _SERVICES[name] = self

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._poller.start()
        return self

    def stop(self):
        self.scheduler.close()
        self._stop.set()
        self._poller.join(timeout=5)
        self._lib.hf_stop(self._handle)
        _SERVICES.pop(self.name, None)

    # -- intake ------------------------------------------------------------
    def _poll_loop(self):
        lib, h = self._lib, self._handle
        ids = (ctypes.c_uint64 * _POLL_BATCH)()
        meth = ctypes.create_string_buffer(16)
        path_buf = ctypes.create_string_buffer(4096)
        blen = ctypes.c_int64(0)
        hlen = ctypes.c_int64(0)
        while not self._stop.is_set():
            try:
                self._poll_once(lib, h, ids, meth, path_buf, blen, hlen)
            except Exception:
                # one bad request (or route handler) must not kill the
                # single poller — that would brick the whole server,
                # where the threaded front loses only one connection
                _LOG.warning("native poll loop error: %s",
                             traceback.format_exc())

    def _poll_once(self, lib, h, ids, meth, path_buf, blen, hlen):
        n = lib.hf_poll(h, ids, _POLL_BATCH, 50)
        now = time.monotonic()
        # expire overdue requests (replaces the per-request wait()
        # timeout of the threaded front); also shed already-answered
        # entries from the front so the deque tracks in-flight work,
        # not reply_timeout's worth of history
        while self._deadlines and (
                self._deadlines[0][0] <= now
                or self._deadlines[0][1]._event.is_set()):
            _, cached = self._deadlines.popleft()
            cached.reply(HTTPResponseData(
                status_code=504, reason="pipeline timeout"))
        if len(self._deadlines) > 16384:
            # out-of-order completions behind one slow request:
            # compact answered entries wherever they sit
            self._deadlines = deque(
                e for e in self._deadlines
                if not e[1]._event.is_set())
        for i in range(max(int(n), 0)):
            try:
                self._handle_request(lib, h, ids[i], meth, path_buf,
                                     blen, hlen, now)
            except Exception:
                # contain failures per request (the threaded front loses
                # one connection; we answer 500 and keep polling)
                _LOG.warning("native request handling failed: %s",
                             traceback.format_exc())
                lib.hf_reply(h, ids[i], 500, b"", b"", 0)

    def _handle_request(self, lib, h, nid, meth, path_buf, blen, hlen,
                        now):
        if lib.hf_req_info(h, nid, meth, 16, path_buf, 4096,
                           ctypes.byref(blen), ctypes.byref(hlen)) != 0:
            return
        t0 = time.perf_counter()
        body = b""
        if blen.value:
            buf = ctypes.create_string_buffer(blen.value)
            lib.hf_req_body(h, nid, buf)
            body = buf.raw
        headers: dict = {}
        if hlen.value:
            hbuf = ctypes.create_string_buffer(hlen.value)
            lib.hf_req_headers(h, nid, hbuf)
            for line in hbuf.raw.decode("latin-1").split("\r\n"):
                k, sep, v = line.partition(":")
                if sep:
                    headers[k.strip()] = v.strip()
        raw_path = path_buf.value.decode(errors="replace")
        path = raw_path.split("?", 1)[0].rstrip("/") or "/"
        # query-scoped routes first ("/metrics?scope=fleet" is a
        # literal key — same order as the threaded front), then the
        # query-stripped path, then the query-route table (variable
        # query values — /debug/timeline?series=&window=)
        route = None
        query = ""
        if "?" in raw_path:
            query = raw_path.split("?", 1)[1]
            route = self._routes.get(f"{path}?{query}")
        if route is None:
            route = self._routes.get(path)
        if route is None:
            qroute = self._query_routes.get(path)
            if qroute is not None:
                def route(b, _q=query, _h=qroute):
                    return _h(_q, b)
        default_ct = b"Content-Type: application/octet-stream\r\n"
        if route is not None:
            status, out = route(body)
            lib.hf_reply(h, nid, status, default_ct, out, len(out))
            self._observe_request(path, status, time.perf_counter() - t0)
            return
        if path != self.api_path:
            lib.hf_reply(h, nid, 404, default_ct, b"", 0)
            # measured like every other exit — the threaded front records
            # real elapsed time for 404s, and the two series must agree
            self._observe_request(path, 404, time.perf_counter() - t0)
            return
        req = HTTPRequestData(
            url=raw_path, method=meth.value.decode(), headers=headers,
            entity=body or None)
        cached = _NativeCachedRequest(
            id=self._new_id(), request=req, server=self, native_id=nid)
        # span opens before admission (same ordering as the threaded
        # front); reply() closes it on every exit path
        self._start_request_span(cached, path)
        with self._lock:
            self.history[cached.id] = cached
            self._deadlines.append((now + self.reply_timeout, cached))
        try:
            self._admit(cached, path)
        except Shed as s:
            # same contract as the threaded front: 503 on hard queue
            # overflow, 429 + Retry-After on policy sheds
            cached.reply(HTTPResponseData(
                status_code=s.status, reason=f"shed: {s.reason}",
                headers={"Retry-After": str(s.retry_after)}))
