"""The serving engine: HTTP front, dynamic batcher, pipeline executor.

Reference mapping:
- ``WorkerServer`` (``continuous/HTTPSourceV2.scala:475+``): per-process
  HTTP server enqueueing ``CachedRequest``s → :class:`ServingServer`.
- micro-batch/continuous readers (:259-326): the executor thread pulling
  batches from the queue and running the pipeline.
- ``HTTPSourceStateHolder`` (:337-428): the module-level ``_SERVICES``
  registry, keyed by service name (used by reply UDFs).
- epoch replay on task retry (:488-517): failed batches are re-enqueued
  with a bounded retry count.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

import logging

from ..core import DataFrame
from ..io.http.schema import HTTPRequestData, HTTPResponseData
from ..obs import registry as _obs
from ..obs.tracing import tracer as _tracer

_LOG = logging.getLogger("mmlspark_tpu.serving")

_SERVICES: dict[str, "ServingServer"] = {}


class LowLatencyHandlerMixin:
    """Shared handler posture for every serving-plane HTTP handler:
    HTTP/1.1 keep-alive, responses coalesced into one TCP segment
    (buffered wfile) with Nagle off — the unbuffered default interacts
    with delayed ACK for ~40 ms stalls per request — and quiet logs."""

    protocol_version = "HTTP/1.1"
    wbufsize = -1
    disable_nagle_algorithm = True

    def log_message(self, *args):
        pass


class QuietHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that treats dead-client disconnects as routine.

    With a buffered response stream (``wbufsize = -1``) a client that
    hangs up early raises BrokenPipeError at the post-handler flush —
    outside any in-handler guard — and stock socketserver would dump a
    traceback per flaky client. The reference tolerates these silently
    (``HTTPv2Suite`` flaky-connection test); so do we."""

    # socketserver's default listen backlog is 5: a 16-way client burst
    # overflows it, dropped SYNs retransmit after ~1 s, and the loaded
    # tail grows a 1000 ms outlier. The native front listens at 1024.
    request_queue_size = 128

    def handle_error(self, request, client_address):
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError,
                            TimeoutError)):
            return  # routine client disconnect
        super().handle_error(request, client_address)


def get_service(name: str) -> "ServingServer":
    """Reference ``HTTPSourceStateHolder.getServer``."""
    return _SERVICES[name]


def bucket_pad(xs: np.ndarray) -> tuple[np.ndarray, int]:
    """Pad a serving batch's leading dim UP to the next power of two;
    returns ``(padded, real_count)`` — score the padded array, slice
    results to ``real_count``.

    Why this exists: under ``jax.jit`` every distinct batch shape
    compiles a separate program, and a dynamic-batching front produces
    every batch size up to the in-flight count — so each NOVEL size
    pays a multi-ms (CPU) to multi-100 ms (TPU) compile at request
    latency. Measured here: a 16-way loaded p99 of ~96 ms collapses to
    ~5 ms once shapes stop being novel. Buckets bound the program count
    to log2(max_batch)."""
    n = len(xs)
    b = 1 << max(n - 1, 0).bit_length()
    if b == n:
        return xs, n
    pad = np.zeros((b - n,) + xs.shape[1:], xs.dtype)
    return np.concatenate([xs, pad]), n


@dataclass
class CachedRequest:
    """An in-flight request (reference ``CachedRequest``): body + the
    machinery to reply exactly once."""
    id: str
    request: HTTPRequestData
    _event: threading.Event = field(default_factory=threading.Event)
    _response: HTTPResponseData | None = None
    retries: int = 0
    # intake timestamp (perf_counter) — the native front measures
    # request latency from here at reply time; the threaded front times
    # in-handler instead (same series either way)
    created: float = field(default_factory=time.perf_counter)

    def reply(self, response: HTTPResponseData) -> bool:
        if self._event.is_set():
            return False
        self._response = response
        self._event.set()
        return True

    def wait(self, timeout: float) -> HTTPResponseData:
        if not self._event.wait(timeout):
            return HTTPResponseData(status_code=504,
                                    reason="pipeline timeout")
        return self._response


class ServingServer:
    """HTTP server + request queue for one named service."""

    def _init_shared_state(self, name: str, api_path: str,
                           reply_timeout: float, max_retries: int,
                           max_queue: int) -> None:
        """State shared by every front (threaded Python and native epoll —
        ``native_front.NativeServingServer`` calls this too, so the two
        cannot drift): the queue, replay bookkeeping, and route table
        that ``next_batch``/``replay``/``_new_id`` operate on."""
        self.name = name
        self.api_path = api_path.rstrip("/") or "/"
        self.reply_timeout = reply_timeout
        self.max_retries = max_retries
        # bounded intake = backpressure: a full queue answers 503
        # immediately instead of buffering unboundedly (VERDICT r1 weak #7)
        self.queue: queue.Queue[CachedRequest] = queue.Queue(
            maxsize=max_queue or 0)
        self.history: dict[str, CachedRequest] = {}
        self._lock = threading.Lock()
        # internal sub-path handlers (distributed mode registers
        # __reply__/__lease__ here): path -> fn(body) -> (status, bytes)
        self._routes: dict[str, callable] = {}
        # -- observability (process-wide registry: obs subsystem) ----------
        # per-route request/error/latency series + a Prometheus text
        # exposition endpoint. Registered in shared state so BOTH fronts
        # (threaded python and native epoll) and distributed mode serve
        # and record identically.
        self._m_requests = _obs.counter(
            "serving_requests_total",
            "requests answered, by service/route/status code")
        self._m_errors = _obs.counter(
            "serving_errors_total",
            "requests answered with status >= 400, by service/route")
        self._m_latency = _obs.histogram(
            "serving_request_seconds",
            "request wall seconds from intake to reply, by service/route")
        self._m_queue = _obs.gauge(
            "serving_queue_depth", "queued requests awaiting the executor")
        self._routes["/metrics"] = self._metrics_route
        if self.api_path != "/":
            self._routes[f"{self.api_path}/metrics"] = self._metrics_route

    def _metrics_route(self, body: bytes) -> tuple[int, bytes]:
        """``GET /metrics``: Prometheus text exposition of the
        process-wide registry (every subsystem's series, not just this
        server's — one scrape surface per process)."""
        return 200, _obs.exposition().encode()

    def _observe_request(self, route: str, status: int,
                         seconds: float) -> None:
        """ONE recording site for both fronts: count + latency, by route.

        Only known routes become label values — anything else collapses
        to ``<unmatched>`` so a client spraying distinct paths cannot
        grow the registry (and the /metrics exposition) without bound.
        """
        if route != self.api_path and route not in self._routes:
            route = "<unmatched>"
        self._m_requests.inc(1, service=self.name, route=route,
                             code=str(status))
        if status >= 400:
            self._m_errors.inc(1, service=self.name, route=route)
        self._m_latency.observe(seconds, service=self.name, route=route)

    def __init__(self, name: str, host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", reply_timeout: float = 30.0,
                 max_retries: int = 2, max_queue: int = 0):
        self._init_shared_state(name, api_path, reply_timeout,
                                max_retries, max_queue)

        serving = self

        class Handler(LowLatencyHandlerMixin,
                      BaseHTTPRequestHandler):
            def _serve(self):
                # every exit records into the shared per-route series
                # (requests/errors/latency) — same recording site the
                # native front uses, so the two fronts cannot drift
                t0 = time.perf_counter()
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                status = self._serve_inner(path)
                serving._observe_request(path, status,
                                         time.perf_counter() - t0)

            def _serve_inner(self, path: str) -> int:
                # route on the service path like the reference WorkerServer
                # (continuous/HTTPSourceV2.scala PublicHandler): anything
                # not addressed to this service's api_path is 404, never
                # queued.
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else None
                route = serving._routes.get(path)
                if route is not None:
                    status, out = route(body or b"")
                    self.send_response(status)
                    self.send_header("Content-Length", str(len(out)))
                    self.end_headers()
                    self.wfile.write(out)
                    return status
                if path != serving.api_path:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return 404
                req = HTTPRequestData(
                    url=self.path, method=self.command,
                    headers=dict(self.headers.items()), entity=body)
                cached = CachedRequest(id=serving._new_id(), request=req)
                with serving._lock:
                    serving.history[cached.id] = cached
                try:
                    serving.queue.put_nowait(cached)
                except queue.Full:
                    with serving._lock:
                        serving.history.pop(cached.id, None)
                    self.send_response(503)
                    self.send_header("Retry-After", "1")
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return 503
                resp = cached.wait(serving.reply_timeout)
                with serving._lock:
                    serving.history.pop(cached.id, None)
                try:
                    self.send_response(resp.status_code or 500)
                    body = resp.entity or b""
                    for k, v in resp.headers.items():
                        if k.lower() != "content-length":
                            self.send_header(k, v)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # flaky client; reference tolerates these too
                return resp.status_code or 500

            do_GET = do_POST = do_PUT = _serve

        self._httpd = QuietHTTPServer((host, port), Handler)
        self.address = self._httpd.server_address
        self._server_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        _SERVICES[name] = self

    def _new_id(self) -> str:
        """Request id; distributed mode embeds the owning worker."""
        return str(uuid.uuid4())

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._server_thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        _SERVICES.pop(self.name, None)

    # -- batch intake (called by the query loop) ---------------------------
    def next_batch(self, max_wait: float = 0.005,
                   max_batch: int = 1024,
                   linger: float = 0.0) -> list[CachedRequest]:
        """Dynamic batching: whatever accumulated, like the reference's
        ``DynamicBufferedBatcher`` — small batches under light load (low
        latency), large under heavy load. ``max_wait`` is only the idle
        poll timeout (an arriving request is picked up immediately);
        ``linger`` optionally waits after the first request to grow the
        batch (micro-batch throughput mode); ``max_batch=1`` is strict
        record-at-a-time (continuous mode)."""
        batch: list[CachedRequest] = []
        try:
            batch.append(self.queue.get(timeout=max_wait))
        except queue.Empty:
            return batch
        deadline = time.monotonic() + linger if linger > 0 else None
        while len(batch) < max_batch:
            try:
                if deadline is None:
                    batch.append(self.queue.get_nowait())
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    batch.append(self.queue.get(timeout=remaining))
            except queue.Empty:
                break
        # depth AFTER the drain = standing backlog the executor can't
        # keep up with (qsize is approximate under concurrency; a gauge
        # tolerates that)
        self._m_queue.set(self.queue.qsize(), service=self.name)
        return batch

    def replay(self, cached: CachedRequest) -> None:
        """Reference epoch replay (``recoveredPartitions``,
        ``HTTPSourceV2.scala:488-517``): requeue an in-flight request whose
        processing failed."""
        cached.retries += 1
        if cached.retries > self.max_retries:
            cached.reply(HTTPResponseData(
                status_code=500, reason="pipeline failed after retries"))
            return
        try:
            # non-blocking: with a bounded queue a blocking put here could
            # deadlock the very consumer that would drain it
            self.queue.put_nowait(cached)
        except queue.Full:
            cached.reply(HTTPResponseData(
                status_code=503, reason="replay rejected: queue full"))


class ServingQuery:
    """The 'streaming query': a thread that pulls request batches through
    the pipeline and replies. ``transform_fn`` receives a DataFrame with
    ``id`` and ``request`` (HTTPRequestData) columns and must either call
    ``send_reply_udf`` itself or return a DataFrame with ``id`` and
    ``reply`` (HTTPResponseData) columns."""

    def __init__(self, server: ServingServer, transform_fn,
                 name: str | None = None, *, max_batch: int = 1024,
                 linger: float = 0.0):
        self.server = server
        self.transform_fn = transform_fn
        self.name = name or server.name
        # max_batch=1 = record-at-a-time (reference continuous mode);
        # linger > 0 = micro-batch throughput mode (wait to grow batches)
        self.max_batch = max_batch
        self.linger = linger
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self.exception: Exception | None = None

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self.server.stop()

    def await_termination(self, timeout: float | None = None):
        self._thread.join(timeout)

    def _run(self):
        batch_rows = _obs.histogram(
            "serving_batch_rows", "requests per executor batch",
            buckets=tuple(float(1 << k) for k in range(11)))
        batch_seconds = _obs.histogram(
            "serving_batch_seconds", "transform wall seconds per batch")
        batch_failures = _obs.counter(
            "serving_batch_failures_total",
            "executor batches that raised and were replayed")
        while not self._stop.is_set():
            batch = self.server.next_batch(max_batch=self.max_batch,
                                           linger=self.linger)
            if not batch:
                continue
            batch_rows.observe(len(batch), service=self.name)
            ids = np.empty(len(batch), object)
            reqs = np.empty(len(batch), object)
            ids[:] = [c.id for c in batch]
            reqs[:] = [c.request for c in batch]
            df = DataFrame({"id": ids, "request": reqs})
            try:
                # the span roots here (the executor thread has no ambient
                # context); batch latency also lands in the registry
                with batch_seconds.time(service=self.name), \
                        _tracer.span("serving.batch", parent=None,
                                     service=self.name, rows=len(batch)):
                    out = self.transform_fn(df)
                if out is not None and "reply" in getattr(
                        out, "columns", []):
                    by_id = {c.id: c for c in batch}
                    for rid, reply in zip(out["id"], out["reply"]):
                        c = by_id.get(rid)
                        if c is not None:
                            c.reply(reply)
            except Exception as e:  # replay the whole failed batch
                self.exception = e
                batch_failures.inc(1, service=self.name)
                _LOG.warning("serving batch failed, replaying: %s",
                             traceback.format_exc())
                for c in batch:
                    self.server.replay(c)


def serving_query(name: str, transform_fn, host: str = "127.0.0.1",
                  port: int = 0, reply_timeout: float = 30.0,
                  backend: str = "auto") -> ServingQuery:
    """One-call setup: server + query, started.

    ``backend``: ``"auto"`` (the DEFAULT: native when the toolchain
    allows, else python), ``"native"`` (C++ epoll reactor,
    ``native_front.py``), or ``"python"`` (threaded http.server front).
    Native is the serving answer under load: request parsing and
    socket writes stay out of the GIL, so at 16-way closed-loop
    saturation its p99 measures ~5.8 ms vs the python front's ~8.4 ms
    (and it sustains ~35% more throughput); single-connection p99s are
    equal (~1 ms, the reference's continuous-mode figure). Saturated
    closed-loop latency is conc/throughput by Little's law — sub-ms
    tails under load need either moderate load or more than one
    transform executor."""
    cls = ServingServer
    if backend in ("native", "auto"):
        try:
            from .native_front import NativeServingServer
            from ..native.loader import get_httpfront
            if get_httpfront() is None:
                raise RuntimeError("native http front unavailable")
            cls = NativeServingServer
        except Exception:
            if backend == "native":
                raise
    server = cls(name, host=host, port=port,
                 reply_timeout=reply_timeout).start()
    return ServingQuery(server, transform_fn).start()
