"""The serving engine: HTTP front, dynamic batcher, pipeline executor.

Reference mapping:
- ``WorkerServer`` (``continuous/HTTPSourceV2.scala:475+``): per-process
  HTTP server enqueueing ``CachedRequest``s → :class:`ServingServer`.
- micro-batch/continuous readers (:259-326): the executor thread pulling
  batches from the queue and running the pipeline.
- ``HTTPSourceStateHolder`` (:337-428): the module-level ``_SERVICES``
  registry, keyed by service name (used by reply UDFs).
- epoch replay on task retry (:488-517): failed batches are re-enqueued
  with a bounded retry count.
"""

from __future__ import annotations

import math
import queue
import threading
import time
import traceback
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

import logging

from ..core import DataFrame
from ..io.http.schema import HTTPRequestData, HTTPResponseData
from ..obs import registry as _obs
from ..obs.attribution import cost_attribution as _cost_attribution
from ..obs.export import debug_trace_payload, flight_recorder as _flight
from ..obs.fleet import (fleet_aggregator as _fleet_agg,
                         fleet_health as _fleet_health)
from ..obs.memory import memory_profiler as _memory
from ..obs.profile import feature_log as _features
from ..obs.propagation import extract as _extract
from ..obs.timeseries import (recorder as _recorder,
                              timeline_payload as _timeline)
from ..obs.tracing import tracer as _tracer
from ..resilience.faults import injector as _inj
from ..sched import RequestScheduler, Shed
from ..sched.policy import bucket_of
from ..sched.tenancy import clean_tenant

_LOG = logging.getLogger("mmlspark_tpu.serving")

_SERVICES: dict[str, "ServingServer"] = {}


class LowLatencyHandlerMixin:
    """Shared handler posture for every serving-plane HTTP handler:
    HTTP/1.1 keep-alive, responses coalesced into one TCP segment
    (buffered wfile) with Nagle off — the unbuffered default interacts
    with delayed ACK for ~40 ms stalls per request — and quiet logs."""

    protocol_version = "HTTP/1.1"
    wbufsize = -1
    disable_nagle_algorithm = True

    def log_message(self, *args):
        pass


class QuietHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that treats dead-client disconnects as routine.

    With a buffered response stream (``wbufsize = -1``) a client that
    hangs up early raises BrokenPipeError at the post-handler flush —
    outside any in-handler guard — and stock socketserver would dump a
    traceback per flaky client. The reference tolerates these silently
    (``HTTPv2Suite`` flaky-connection test); so do we."""

    # socketserver's default listen backlog is 5: a 16-way client burst
    # overflows it, dropped SYNs retransmit after ~1 s, and the loaded
    # tail grows a 1000 ms outlier. The native front listens at 1024.
    request_queue_size = 128

    def handle_error(self, request, client_address):
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError,
                            TimeoutError)):
            return  # routine client disconnect
        super().handle_error(request, client_address)


def get_service(name: str) -> "ServingServer":
    """Reference ``HTTPSourceStateHolder.getServer``."""
    return _SERVICES[name]


def bucket_pad(xs: np.ndarray) -> tuple[np.ndarray, int]:
    """Pad a serving batch's leading dim UP to the next power of two;
    returns ``(padded, real_count)`` — score the padded array, slice
    results to ``real_count``.

    Why this exists: under ``jax.jit`` every distinct batch shape
    compiles a separate program, and a dynamic-batching front produces
    every batch size up to the in-flight count — so each NOVEL size
    pays a multi-ms (CPU) to multi-100 ms (TPU) compile at request
    latency. Measured here: a 16-way loaded p99 of ~96 ms collapses to
    ~5 ms once shapes stop being novel. Buckets bound the program count
    to log2(max_batch)."""
    n = len(xs)
    b = 1 << max(n - 1, 0).bit_length()
    if b == n:
        return xs, n
    pad = np.zeros((b - n,) + xs.shape[1:], xs.dtype)
    return np.concatenate([xs, pad]), n


@dataclass
class CachedRequest:
    """An in-flight request (reference ``CachedRequest``): body + the
    machinery to reply exactly once.

    The reply latch is now an atomic check-and-set under a per-request
    lock, with a second terminal transition — :meth:`abandon` — taken
    when the waiting client gives up (handler timeout): a later
    pipeline ``reply`` then returns False and is dropped cleanly
    instead of racing the latch, and ``on_done`` (the scheduler's
    in-flight release) fires exactly once on whichever transition wins.
    """
    id: str
    request: HTTPRequestData
    _event: threading.Event = field(default_factory=threading.Event)
    _response: HTTPResponseData | None = None
    retries: int = 0
    # intake timestamp (perf_counter) — the native front measures
    # request latency from here at reply time; the threaded front times
    # in-handler instead (same series either way)
    created: float = field(default_factory=time.perf_counter)
    # absolute deadline on the scheduler's monotonic clock (None = no
    # deadline) and the route label — set at admission (sched subsystem)
    deadline: float | None = None
    route: str = "/"
    # quota/tier bucket from the X-Tenant header (sched.tenancy); ""
    # when the service runs without a tenancy policy
    tenant: str = ""
    # fired exactly once when the request reaches ANY terminal state
    # (reply or abandon); the serving layer hangs the scheduler's
    # in-flight release here
    on_done: object = None
    abandoned: bool = False
    # the request's span in the cross-process trace (obs subsystem) and
    # the queue wait the scheduler stamped at pop — both None until set
    span: object = None
    queue_wait: float | None = None
    # deploy plane (serving.deploy): the model version that admitted
    # this request — it completes on that version even across a flip;
    # the released latch makes the router's inflight release one-shot
    # (the Shed path and _finish_request can both reach it)
    model_version: str = ""
    _version_released: bool = False
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def reply(self, response: HTTPResponseData) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self._response = response
            self._event.set()
        self._fire_done()
        return True

    def abandon(self, response: HTTPResponseData | None = None) -> bool:
        """Terminal no-client-listening state (handler wait timed out):
        marks the slot dead so a later ``reply`` is dropped cleanly.
        Returns False when a real reply won the race."""
        with self._lock:
            if self._event.is_set():
                return False
            self.abandoned = True
            self._response = response or HTTPResponseData(
                status_code=504, reason="pipeline timeout")
            self._event.set()
        self._fire_done()
        return True

    def wait(self, timeout: float) -> HTTPResponseData:
        if not self._event.wait(timeout):
            # mark abandoned; on a lost race the landed reply stands
            self.abandon()
        return self._response

    def _fire_done(self) -> None:
        cb, self.on_done = self.on_done, None
        if cb is not None:
            try:
                cb()
            except Exception:
                _LOG.warning("request done-callback failed: %s",
                             traceback.format_exc())


class ServingServer:
    """HTTP server + request queue for one named service."""

    def _init_shared_state(self, name: str, api_path: str,
                           reply_timeout: float, max_retries: int,
                           max_queue: int, deadline: float = 0.0,
                           max_inflight: int = 0,
                           tenancy=None) -> None:
        """State shared by every front (threaded Python and native epoll —
        ``native_front.NativeServingServer`` calls this too, so the two
        cannot drift): the scheduler, replay bookkeeping, and route table
        that ``next_batch``/``replay``/``_new_id`` operate on."""
        self.name = name
        self.api_path = api_path.rstrip("/") or "/"
        self.reply_timeout = reply_timeout
        self.max_retries = max_retries
        # the admission-controlled scheduler (sched subsystem) replaces
        # the plain FIFO: bounded intake still answers 503 on hard
        # overflow (VERDICT r1 weak #7), and the deadline budget adds
        # predictive load shedding (429 + Retry-After) plus expiry sheds
        # before execution. Queue-compatible, so the mesh lease drain,
        # replay, and queue-poking tests work unchanged.
        self.scheduler = RequestScheduler(
            name, max_queue=max_queue or 0, max_inflight=max_inflight,
            deadline=deadline, on_shed=self._shed_reply,
            tenancy=tenancy)
        self.queue = self.scheduler
        self.history: dict[str, CachedRequest] = {}
        self._lock = threading.Lock()
        # internal sub-path handlers (distributed mode registers
        # __reply__/__lease__ here): path -> fn(body) -> (status, bytes)
        self._routes: dict[str, callable] = {}
        # -- observability (process-wide registry: obs subsystem) ----------
        # per-route request/error/latency series + a Prometheus text
        # exposition endpoint. Registered in shared state so BOTH fronts
        # (threaded python and native epoll) and distributed mode serve
        # and record identically.
        self._m_requests = _obs.counter(
            "serving_requests_total",
            "requests answered, by service/route/status code")
        self._m_errors = _obs.counter(
            "serving_errors_total",
            "requests answered with status >= 400, by service/route")
        self._m_latency = _obs.histogram(
            "serving_request_seconds",
            "request wall seconds from intake to reply, by service/route")
        self._m_queue = _obs.gauge(
            "serving_queue_depth", "queued requests awaiting the executor")
        self._m_lat_ewma = _obs.gauge(
            "serving_request_seconds_ewma",
            "EWMA request latency, by service (load-aware routing input)")
        # per-tenant outcome series (sched.tenancy): label cardinality
        # is bounded by the tenancy policy's idle-tenant eviction
        self._m_tenant_requests = _obs.counter(
            "serving_tenant_requests_total",
            "requests answered, by service/tenant/status code")
        self._lat_ewma = 0.0
        self._lat_seen = False
        self._routes["/metrics"] = self._metrics_route
        if self.api_path != "/":
            self._routes[f"{self.api_path}/metrics"] = self._metrics_route
        # flight recorder + trace debug surface (obs subsystem): the
        # recorder collects every span once installed; requests report
        # their outcome through _finish_request so the N slowest /
        # errored keep their full cross-process trees, served at
        # GET /debug/trace by BOTH fronts (shared route table)
        _flight.install()
        self._routes["/debug/trace"] = self._debug_trace_route
        if self.api_path != "/":
            self._routes[f"{self.api_path}/debug/trace"] = \
                self._debug_trace_route
        # AOT store introspection (core/aot.py): what the process's
        # executable store holds vs what compiled at runtime — served
        # by BOTH fronts (shared route table), like /metrics
        self._routes["/debug/aot"] = self._debug_aot_route
        if self.api_path != "/":
            self._routes[f"{self.api_path}/debug/aot"] = \
                self._debug_aot_route
        # fleet telemetry plane (obs.fleet, ISSUE 15): the fleet-scoped
        # exposition ("?scope=fleet" is a LITERAL route key — both
        # fronts try the query-preserving key before the stripped
        # path), the per-source debug view, and the SLO-burn /healthz
        # verdict. Shared route table → identical on both fronts.
        self._routes["/metrics?scope=fleet"] = self._fleet_metrics_route
        self._routes["/debug/fleet"] = self._debug_fleet_route
        self._routes["/healthz"] = self._healthz_route
        if self.api_path != "/":
            for suffix in ("/metrics?scope=fleet", "/debug/fleet",
                           "/healthz"):
                self._routes[f"{self.api_path}{suffix}"] = \
                    self._routes[suffix]
        # telemetry history plane (obs.timeseries, ISSUE 16): the
        # timeline query surface. Its query VALUES vary per request
        # (series=<patterns>&window=<seconds>), so it cannot be a
        # literal ``path?query`` key — query routes are a second table
        # (path -> fn(query, body)) both fronts consult after the
        # literal lookups, keeping the existing routes byte-identical.
        self._query_routes: dict[str, callable] = {}
        self._query_routes["/debug/timeline"] = self._debug_timeline_route
        if self.api_path != "/":
            self._query_routes[f"{self.api_path}/debug/timeline"] = \
                self._debug_timeline_route
        # deploy plane (serving.deploy, ISSUE 19): no router until an
        # operator attaches one — versionless serving stays the exact
        # pre-deploy-plane path. The debug surface is shared-state so
        # both fronts serve it.
        self.version_router = None
        self._routes["/debug/deploy"] = self._debug_deploy_route
        if self.api_path != "/":
            self._routes[f"{self.api_path}/debug/deploy"] = \
                self._debug_deploy_route
        # cost-attribution plane (obs.attribution/goodput/xprof, ISSUE
        # 20): the goodput ledger report is a literal route; /debug/
        # xprof is a QUERY route (list on empty query, capture on
        # ``duration_ms=``, download on ``fetch=``) so one path serves
        # the whole capture workflow on BOTH fronts. The distributed
        # server overrides the xprof handler with the pod-fanout
        # variant.
        self._routes["/debug/goodput"] = self._debug_goodput_route
        self._query_routes["/debug/xprof"] = self._debug_xprof_route
        if self.api_path != "/":
            self._routes[f"{self.api_path}/debug/goodput"] = \
                self._debug_goodput_route
            self._query_routes[f"{self.api_path}/debug/xprof"] = \
                self._debug_xprof_route
        if tenancy is not None:
            _fleet_health.attach_tenancy(tenancy)

    def _debug_aot_route(self, body: bytes) -> tuple[int, bytes]:
        """``GET /debug/aot``: active store stats + the CompileTracker
        steady-state view (runtime compiles since mark_steady — the
        functions an operator must add to the AOT build)."""
        import json as _json

        from ..core import aot
        from ..obs.profile import compile_tracker
        store = aot.active_store()
        payload = {
            "store": store.stats() if store is not None else None,
            "steady": compile_tracker.steady,
            "runtime_compiles": compile_tracker.runtime_compiled(),
        }
        return 200, _json.dumps(payload, indent=1).encode()

    def _debug_deploy_route(self, body: bytes) -> tuple[int, bytes]:
        """``GET /debug/deploy``: the version router's live state —
        active/candidate/prior pointers, canary config, per-version
        inflight, and the registry's version table."""
        import json as _json
        router = self.version_router
        payload = router.describe() if router is not None \
            else {"router": None}
        return 200, _json.dumps(payload, indent=1).encode()

    def _debug_goodput_route(self, body: bytes) -> tuple[int, bytes]:
        """``GET /debug/goodput``: tick the fleet goodput ledger
        against the live registry and report the ratio plus the
        itemized waste taxonomy (obs.goodput)."""
        from ..obs.goodput import goodput_payload
        return 200, goodput_payload()

    def _debug_xprof_route(self, query: str,
                           body: bytes) -> tuple[int, bytes]:
        """``GET/POST /debug/xprof``: list captures (empty query),
        run a bounded device-profiler capture (``?duration_ms=``), or
        download one (``?fetch=``) — obs.xprof; degrades to 503 with a
        reason when jax is absent rather than importing it."""
        from ..obs.xprof import xprof_captures
        return xprof_captures.handle_query(query, body)

    def attach_router(self, router) -> "ServingServer":
        """Attach a :class:`~mmlspark_tpu.serving.deploy.VersionRouter`:
        every subsequently admitted request is stamped with (and
        completes on) the version the router assigns, and replies
        carry ``X-Model-Version``. Works on both fronts — admission,
        the terminal release, and the executor all run through shared
        state."""
        self.version_router = router
        return self

    def _stamp_version(self, cached: "CachedRequest",
                       response: HTTPResponseData) -> None:
        """Echo the serving version on a response (deploy satellite:
        the flip must be visible client-side). setdefault — the
        executor's per-group stamp is authoritative when present."""
        router = self.version_router
        if router is None or response is None:
            return
        ver = cached.model_version or router.active or ""
        if ver and isinstance(response.headers, dict):
            response.headers.setdefault("X-Model-Version", ver)

    def _release_version(self, cached: "CachedRequest") -> None:
        """One-shot release of the admitted version's inflight slot
        (drain accounting): reachable from BOTH the Shed-at-admission
        path and _finish_request, so the latch keeps it exact."""
        router = self.version_router
        if router is None or not cached.model_version \
                or cached._version_released:
            return
        cached._version_released = True
        router.release(cached.model_version)

    def _metrics_route(self, body: bytes) -> tuple[int, bytes]:
        """``GET /metrics``: Prometheus text exposition of the
        process-wide registry (every subsystem's series, not just this
        server's — one scrape surface per process)."""
        return 200, _obs.exposition().encode()

    def _debug_trace_route(self, body: bytes) -> tuple[int, bytes]:
        """``GET /debug/trace``: the flight recorder's retained span
        trees (slowest + errored requests) as Chrome-trace/Perfetto
        JSON with per-trace summaries — save as ``.json``, open in
        Perfetto, find the trace_id the load generator printed."""
        return 200, debug_trace_payload()

    def _fleet_metrics_route(self, body: bytes) -> tuple[int, bytes]:
        """``GET /metrics?scope=fleet``: the local exposition plus
        every merged remote source's samples (pod ranks, heartbeating
        mesh workers, pulled peers) — one scrape for the whole fleet.
        Memory gauges refresh on scrape so they are never staler than
        the reading."""
        _memory.update()
        return 200, _fleet_agg.exposition().encode()

    def _debug_fleet_route(self, body: bytes) -> tuple[int, bytes]:
        """``GET /debug/fleet``: verdict + per-source staleness/size,
        flagged stragglers, and per-tenant burn rates as JSON."""
        return 200, _fleet_health.debug_payload()

    def _healthz_route(self, body: bytes) -> tuple[int, bytes]:
        """``GET /healthz``: the fleet health verdict. 200 for
        ok/degraded (a slow fleet must not be drained by its load
        balancer), 503 only when critical (SLO burn is paging)."""
        return _fleet_health.healthz_payload()

    def _debug_timeline_route(self, query: str,
                              body: bytes) -> tuple[int, bytes]:
        """``GET /debug/timeline?series=&window=``: the history
        store's recorded series as JSON — ``series`` is a
        comma-separated name/prefix list, ``window`` trailing seconds
        (default 300); without ``series`` an index of recorded series.
        Served by BOTH fronts via the shared query-route table."""
        return _timeline(query)

    def _start_request_span(self, cached: "CachedRequest",
                            route: str) -> None:
        """Open the request's span: parented into the CLIENT's trace
        when the request carries a traceparent header (the HTTP client
        stack injects one), a fresh root otherwise. ``current=False``:
        handler/poller threads serve many requests concurrently, so the
        ambient context must stay untouched — children name this span
        explicitly (scheduler queue spans, executor execute spans)."""
        ctx = _extract(cached.request.headers)
        cached.span = _tracer.start_span(
            "serving.request", parent=ctx, current=False,
            service=self.name, route=route, worker=self._worker_label())

    def _worker_label(self) -> str:
        """Distributed mode overrides identity via worker_id; the
        single-process server labels spans with its service name."""
        return getattr(self, "worker_id", "") or self.name

    def _finish_request(self, cached: "CachedRequest",
                        status: int) -> None:
        """Close the request span and report the outcome to the flight
        recorder (which decides whether the tree is retained). ONE site
        for both fronts; idempotent via end_span's done-latch."""
        # only with a tenancy policy attached: its idle-tenant eviction
        # is what bounds this label's cardinality — without one, a
        # client spraying X-Tenant values could grow the exposition
        # forever (same rationale as the <unmatched> route collapse)
        self._release_version(cached)
        if cached.tenant and self.scheduler.tenancy is not None:
            self._m_tenant_requests.inc(1, service=self.name,
                                        tenant=cached.tenant,
                                        code=str(int(status)))
        span = cached.span
        if span is None:
            return
        already = getattr(span, "_done", False)
        span.set_attr("status", int(status))
        _tracer.end_span(span)
        if not already:
            _flight.note_request(span.trace_id, span.seconds or 0.0,
                                 status=int(status))

    def _observe_request(self, route: str, status: int,
                         seconds: float) -> None:
        """ONE recording site for both fronts: count + latency, by route.

        Only known routes become label values — anything else collapses
        to ``<unmatched>`` so a client spraying distinct paths cannot
        grow the registry (and the /metrics exposition) without bound.
        """
        if route != self.api_path and route not in self._routes:
            route = "<unmatched>"
        self._m_requests.inc(1, service=self.name, route=route,
                             code=str(status))
        if status >= 400:
            self._m_errors.inc(1, service=self.name, route=route)
        self._m_latency.observe(seconds, service=self.name, route=route)
        # EWMA latency for load-aware routing (ServiceInfo carries it to
        # the driver registry); a float read-modify-write race here only
        # smears the smoothing, never corrupts the series
        self._lat_ewma = seconds if not self._lat_seen else \
            0.2 * seconds + 0.8 * self._lat_ewma
        self._lat_seen = True
        self._m_lat_ewma.set(self._lat_ewma, service=self.name)

    def _shed_reply(self, cached: "CachedRequest", reason: str,
                    retry_after: float) -> None:
        """Scheduler ``on_shed`` callback: answer a request shed AFTER
        queueing (deadline expired before execution). Works through
        ``CachedRequest.reply``, so both fronts (threaded wait and
        native reactor) deliver it the same way."""
        resp = HTTPResponseData(
            status_code=429, reason=f"shed: {reason}",
            headers={"Retry-After": str(max(1, int(retry_after)))})
        self._stamp_version(cached, resp)
        cached.reply(resp)

    def _admit(self, cached: "CachedRequest", route: str) -> None:
        """Shared admission path for both fronts: a client can tighten
        its budget with an ``X-Deadline-Ms`` header (capped at the
        service default when one is configured — a client cannot ask
        for MORE queueing than the service allows) and names its quota
        bucket with ``X-Tenant`` (sanitized; junk values collapse to
        the default tenant); raises :class:`~..sched.Shed` when the
        scheduler rejects."""
        budget = None
        tenant = ""
        for k, v in (cached.request.headers or {}).items():
            lk = k.lower()
            if lk == "x-deadline-ms":
                try:
                    # clamp to a positive finite floor: a 0/negative
                    # header must read as "already out of budget"
                    # (immediate shed), NOT as "no deadline", and
                    # "nan"/"inf" parse without ValueError but would
                    # sail through every deadline comparison — all of
                    # them would loosen the budget the contract says
                    # can only be tightened
                    budget = float(v) / 1e3
                    budget = max(budget, 1e-6) \
                        if math.isfinite(budget) else None
                except (TypeError, ValueError):
                    budget = None
                if budget is not None and self.scheduler.default_deadline:
                    budget = min(budget, self.scheduler.default_deadline)
            elif lk == "x-tenant":
                tenant = clean_tenant(v)
        # deploy plane: the router decides WHICH version serves this
        # request (and whether it rides the canary slice under the
        # canary tenant's own quota/budget) at admission — the request
        # then completes on that version even if a flip lands while it
        # queues. assign() acquires the version's inflight slot, so a
        # scheduler rejection must release it before re-raising.
        router = self.version_router
        if router is not None:
            ver, override = router.assign(tenant)
            cached.model_version = ver
            if override:
                tenant = override
        try:
            self.scheduler.submit(cached, route=route, deadline=budget,
                                  tenant=tenant)
        except Shed:
            self._release_version(cached)
            raise

    def __init__(self, name: str, host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", reply_timeout: float = 30.0,
                 max_retries: int = 2, max_queue: int = 0,
                 deadline: float = 0.0, max_inflight: int = 0,
                 tenancy=None):
        self._init_shared_state(name, api_path, reply_timeout,
                                max_retries, max_queue, deadline=deadline,
                                max_inflight=max_inflight,
                                tenancy=tenancy)

        serving = self

        class Handler(LowLatencyHandlerMixin,
                      BaseHTTPRequestHandler):
            def _serve(self):
                # every exit records into the shared per-route series
                # (requests/errors/latency) — same recording site the
                # native front uses, so the two fronts cannot drift
                t0 = time.perf_counter()
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                status = self._serve_inner(path)
                serving._observe_request(path, status,
                                         time.perf_counter() - t0)

            def _serve_inner(self, path: str) -> int:
                # route on the service path like the reference WorkerServer
                # (continuous/HTTPSourceV2.scala PublicHandler): anything
                # not addressed to this service's api_path is 404, never
                # queued.
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else None
                # query-scoped routes first ("/metrics?scope=fleet" is
                # a literal key), then the query-stripped path, then
                # the query-route table (variable query values —
                # /debug/timeline?series=&window=)
                route = None
                query = ""
                if "?" in self.path:
                    query = self.path.split("?", 1)[1]
                    route = serving._routes.get(f"{path}?{query}")
                if route is None:
                    route = serving._routes.get(path)
                if route is None:
                    qroute = serving._query_routes.get(path)
                    if qroute is not None:
                        def route(b, _q=query, _h=qroute):
                            return _h(_q, b)
                if route is not None:
                    status, out = route(body or b"")
                    self.send_response(status)
                    self.send_header("Content-Length", str(len(out)))
                    self.end_headers()
                    self.wfile.write(out)
                    return status
                if path != serving.api_path:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return 404
                req = HTTPRequestData(
                    url=self.path, method=self.command,
                    headers=dict(self.headers.items()), entity=body)
                cached = CachedRequest(id=serving._new_id(), request=req)
                # span opens BEFORE admission so a queue span (and the
                # shed outcome) lands inside the request's trace
                serving._start_request_span(cached, path)
                with serving._lock:
                    serving.history[cached.id] = cached
                try:
                    serving._admit(cached, path)
                except Shed as s:
                    # hard queue overflow keeps the 503 contract; policy
                    # sheds (deadline budget, concurrency) answer 429 —
                    # both carry Retry-After sized to the predicted drain
                    with serving._lock:
                        serving.history.pop(cached.id, None)
                    serving._finish_request(cached, s.status)
                    self.send_response(s.status)
                    self.send_header("Retry-After", str(s.retry_after))
                    if cached.model_version:
                        self.send_header("X-Model-Version",
                                         cached.model_version)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return s.status
                resp = cached.wait(serving.reply_timeout)
                with serving._lock:
                    serving.history.pop(cached.id, None)
                serving._finish_request(cached, resp.status_code or 500)
                serving._stamp_version(cached, resp)
                try:
                    self.send_response(resp.status_code or 500)
                    body = resp.entity or b""
                    for k, v in resp.headers.items():
                        if k.lower() != "content-length":
                            self.send_header(k, v)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # flaky client; reference tolerates these too
                return resp.status_code or 500

            do_GET = do_POST = do_PUT = _serve

        self._httpd = QuietHTTPServer((host, port), Handler)
        self.address = self._httpd.server_address
        self._server_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        _SERVICES[name] = self

    def _new_id(self) -> str:
        """Request id; distributed mode embeds the owning worker."""
        return str(uuid.uuid4())

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._server_thread.start()
        return self

    def stop(self):
        self.scheduler.close()
        self._httpd.shutdown()
        self._httpd.server_close()
        _SERVICES.pop(self.name, None)

    # -- batch intake (called by the query loop) ---------------------------
    def next_batch(self, max_wait: float | None = 0.005,
                   max_batch: int = 1024,
                   linger: float = 0.0) -> list[CachedRequest]:
        """Dynamic batching through the sched subsystem's adaptive
        policy: small batches under light load (a lone request is
        dispatched immediately — condition-variable wakeup, no poll
        floor), large under heavy load, with closes decided by deadline
        slack / padding-bucket fill / the learned service-time EWMA.
        ``max_wait`` bounds the idle wait (None = block until work or a
        ``wake()``/``close()`` — the zero-idle-CPU mode ServingQuery
        uses); ``linger`` is the micro-batch wait budget; ``max_batch=1``
        is strict record-at-a-time (continuous mode)."""
        batch = self.scheduler.next_batch(max_batch=max_batch,
                                          linger=linger, max_wait=max_wait)
        # depth AFTER the drain = standing backlog the executor can't
        # keep up with (qsize is approximate under concurrency; a gauge
        # tolerates that)
        self._m_queue.set(self.queue.qsize(), service=self.name)
        return batch

    def replay(self, cached: CachedRequest) -> None:
        """Reference epoch replay (``recoveredPartitions``,
        ``HTTPSourceV2.scala:488-517``): requeue an in-flight request whose
        processing failed."""
        cached.retries += 1
        if cached.retries > self.max_retries:
            cached.reply(HTTPResponseData(
                status_code=500, reason="pipeline failed after retries"))
            return
        try:
            # non-blocking: with a bounded queue a blocking put here could
            # deadlock the very consumer that would drain it. Replays go
            # to the FRONT: this request already waited through the
            # queue once, and a replay is racing what is left of its
            # deadline budget (resilience: detection-driven requeue)
            self.queue.put_front(cached)
        except queue.Full:
            cached.reply(HTTPResponseData(
                status_code=503, reason="replay rejected: queue full"))


class ServingQuery:
    """The 'streaming query': a thread that pulls request batches through
    the pipeline and replies. ``transform_fn`` receives a DataFrame with
    ``id`` and ``request`` (HTTPRequestData) columns and must either call
    ``send_reply_udf`` itself or return a DataFrame with ``id`` and
    ``reply`` (HTTPResponseData) columns."""

    def __init__(self, server: ServingServer, transform_fn,
                 name: str | None = None, *, max_batch: int = 1024,
                 linger: float = 0.0):
        self.server = server
        self.transform_fn = transform_fn
        self.name = name or server.name
        # max_batch=1 = record-at-a-time (reference continuous mode);
        # linger > 0 = micro-batch throughput mode (wait to grow batches)
        self.max_batch = max_batch
        self.linger = linger
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self.exception: Exception | None = None

    def start(self):
        # AOT warm boot for a transform_fn handed to serving_query
        # directly (a CompiledPipeline, or anything exposing its
        # stages): executables load BEFORE the executor thread can pull
        # a batch, so the first request never pays a compile. The DSL
        # path (ServingStream.start) warms the same way.
        from ..core import aot
        aot.maybe_warm(self.transform_fn, service=self.name)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        # close (not wake) the scheduler: close is sticky, so the
        # executor cannot miss it in the window between checking the
        # stop flag and re-entering next_batch — a wake() generation
        # bump is only visible to an already-parked waiter, and losing
        # it would stall this join for its full timeout
        self.server.scheduler.close()
        self._thread.join(timeout=5)
        self.server.stop()

    def await_termination(self, timeout: float | None = None):
        self._thread.join(timeout)

    def _annotate_batch(self, batch, execute_s: float) -> None:
        """Per-request trace + cost-model bookkeeping for one executed
        batch (obs subsystem): a ``serving.execute`` child span under
        each request's span (the whole batch's transform time — the
        latency each rider actually paid), and one feature-log record
        per request (route, batch/bucket, queue/execute ms, entity
        bytes) — the learned scheduler model's training rows."""
        n = len(batch)
        bucket = bucket_of(n)
        # standing backlog at annotate time: the queue-depth feature the
        # cost model trains on (what admission saw is gone by now; the
        # post-drain depth is the stationary load signal)
        queue_depth = self.server.scheduler.qsize()
        tenancy = self.server.scheduler.tenancy
        # fused-pipeline transparency: a CompiledPipeline transform_fn
        # (or a DSL chain that compiled one) reports how many XLA
        # segments — i.e. device dispatches for the traced portion —
        # served this request; None = plain host path
        segments = getattr(self.transform_fn, "compiled_segments", None)
        # schema v6 (ISSUE 20): the service's summed analytic cost from
        # the attribution table — 0.0 until something compiled for it
        a_flops, a_bytes = _cost_attribution.service_cost(self.name)
        for c in batch:
            sp = getattr(c, "span", None)
            if sp is not None:
                _tracer.emit_span("serving.execute", parent=sp,
                                  seconds=execute_s, service=self.name,
                                  rows=n)
            tenant = getattr(c, "tenant", "")
            queue_s = getattr(c, "queue_wait", None) or 0.0
            _features.record(
                service=self.name,
                route=getattr(c, "route", "/"),
                tenant=tenant,
                batch=n, bucket=bucket,
                # schema v2 (ISSUE 12): the post-bucket padded batch
                # shape the executor actually ran, and the queue depth
                # — the cost model's missing features (schema_version
                # and platform are stamped by FeatureLog.record)
                padded_batch=bucket,
                queue_depth=queue_depth,
                queue_ms=round(queue_s * 1e3, 4),
                execute_ms=round(execute_s * 1e3, 4),
                entity_bytes=len(getattr(c.request, "entity", b"")
                                 or b""),
                compiled_segments=segments,
                analytic_flops=a_flops, analytic_bytes=a_bytes,
                trace_id=(sp.trace_id if sp is not None else None))
            if tenancy is not None and tenant:
                # the tenant's EWMA latency (queue + execute — what the
                # rider actually paid): the autoscaler's SLO pressure
                tenancy.observe_latency(tenant, queue_s + execute_s)

    def _run(self):
        batch_rows = _obs.histogram(
            "serving_batch_rows", "requests per executor batch",
            buckets=tuple(float(1 << k) for k in range(11)))
        batch_seconds = _obs.histogram(
            "serving_batch_seconds", "transform wall seconds per batch")
        batch_failures = _obs.counter(
            "serving_batch_failures_total",
            "executor batches that raised and were replayed")
        while not self._stop.is_set():
            # max_wait=None: block on the scheduler's condition variable
            # until work arrives (zero idle CPU; stop() wakes us)
            batch = self.server.next_batch(max_wait=None,
                                           max_batch=self.max_batch,
                                           linger=self.linger)
            if not batch:
                if self.server.scheduler.closed:
                    # scheduler torn down under us (server.stop()
                    # called before query.stop()): nothing more can
                    # arrive, and next_batch no longer blocks — looping
                    # would busy-spin a full core
                    break
                continue
            batch_rows.observe(len(batch), service=self.name)
            for ver, fn, members in self._transform_groups(batch):
                self._execute_group(ver, fn, members, batch_seconds,
                                    batch_failures)

    def _transform_groups(self, batch) -> list[tuple]:
        """Partition a pulled batch by the version that ADMITTED each
        request (deploy plane, serving.deploy): a request admitted
        before a flip completes on the old version even when the
        executor pulls it after the swap — the drain guarantee.
        Versionless serving yields the whole batch on ``transform_fn``
        (the exact pre-deploy-plane path, zero extra work)."""
        router = getattr(self.server, "version_router", None)
        if router is None:
            return [("", self.transform_fn, batch)]
        by_ver: dict[str, list] = {}
        for c in batch:
            by_ver.setdefault(
                getattr(c, "model_version", "") or "", []).append(c)
        groups = []
        for ver, members in by_ver.items():
            fn = router.transform_for(ver) if ver else None
            groups.append((ver, fn or self.transform_fn, members))
        return groups

    def _execute_group(self, ver: str, fn, members,
                       batch_seconds, batch_failures) -> None:
        """Run one version's sub-batch through its transform and
        reply, stamping ``X-Model-Version``. The seeded ``model.bad``
        fault probes here — at execute time, keyed by version — so a
        bad build's failure mode (injected 5xx, or corrupted output
        bytes) is deterministic per seed like worker.death/worker.slow."""
        act = _inj.apply("model.bad", key=ver) if ver else None
        if act is not None and act.kind == "error":
            # a broken build answering errors: every rider sees the
            # injected status; _finish_request then counts the 5xx
            # under the rider's tenant, which is what the rollout
            # controller's burn signal reads
            for c in members:
                c.reply(HTTPResponseData(
                    status_code=act.status or 500,
                    reason="injected: model.bad",
                    headers={"X-Model-Version": ver}))
            return
        ids = np.empty(len(members), object)
        reqs = np.empty(len(members), object)
        ids[:] = [c.id for c in members]
        reqs[:] = [c.request for c in members]
        df = DataFrame({"id": ids, "request": reqs})
        try:
            # the span roots here (the executor thread has no ambient
            # context); batch latency also lands in the registry
            with batch_seconds.time(service=self.name) as bt, \
                    _tracer.span("serving.batch", parent=None,
                                 service=self.name, rows=len(members)):
                out = fn(df)
            # feed the scheduler's service-time model (EWMA per
            # padding bucket, stored in the obs registry): this is
            # what admission's predictive shed and the batcher's
            # close decision read back
            self.server.scheduler.estimator.observe(
                len(members), bt.seconds)
            self._annotate_batch(members, bt.seconds)
            if out is not None and "reply" in getattr(
                    out, "columns", []):
                corrupt = act is not None and act.kind == "corrupt"
                by_id = {c.id: c for c in members}
                for rid, reply in zip(out["id"], out["reply"]):
                    c = by_id.get(rid)
                    if c is None:
                        continue
                    if corrupt and getattr(reply, "entity", None):
                        # model.bad `corrupt`: wrong bytes under a
                        # healthy status — the failure mode shadow
                        # comparison exists to catch
                        reply.entity = bytes(
                            b ^ 0xFF for b in reply.entity)
                    if ver and isinstance(reply.headers, dict):
                        reply.headers.setdefault(
                            "X-Model-Version", ver)
                    c.reply(reply)
                self._maybe_shadow(ver, df, out)
        except Exception as e:  # replay the whole failed group
            self.exception = e
            batch_failures.inc(1, service=self.name)
            _LOG.warning("serving batch failed, replaying: %s",
                         traceback.format_exc())
            for c in members:
                self.server.replay(c)

    def _maybe_shadow(self, ver: str, df, active_out) -> None:
        """Shadow mode (deploy plane): mirror the active group's frame
        through the candidate and count divergent response payloads —
        compared, never returned to a client."""
        router = getattr(self.server, "version_router", None)
        pair = router.shadow_pair() if router is not None else None
        if pair is None or ver != pair[0]:
            return
        fn = router.transform_for(pair[1])
        if fn is None:
            return
        s_act = _inj.apply("model.bad", key=pair[1])
        if s_act is not None and s_act.kind == "error":
            # a candidate that would answer errors diverges on every
            # mirrored request
            router.note_shadow_mismatch(len(df["id"]))
            return
        try:
            shadow_out = fn(df)
        except Exception:
            router.note_shadow_mismatch(len(df["id"]))
            return
        if s_act is not None and s_act.kind == "corrupt" and \
                shadow_out is not None and "reply" in getattr(
                    shadow_out, "columns", []):
            for reply in shadow_out["reply"]:
                if getattr(reply, "entity", None):
                    reply.entity = bytes(
                        b ^ 0xFF for b in reply.entity)
        replies = {}
        if shadow_out is not None and "reply" in getattr(
                shadow_out, "columns", []):
            replies = dict(zip(shadow_out["id"], shadow_out["reply"]))
        mismatches = 0
        for rid, reply in zip(active_out["id"], active_out["reply"]):
            shadow = replies.get(rid)
            if shadow is None or getattr(shadow, "entity", None) != \
                    getattr(reply, "entity", None):
                mismatches += 1
        router.note_shadow_mismatch(mismatches)


def serving_query(name: str, transform_fn, host: str = "127.0.0.1",
                  port: int = 0, reply_timeout: float = 30.0,
                  backend: str = "auto", max_queue: int = 0,
                  deadline: float = 0.0,
                  max_inflight: int = 0, tenancy=None,
                  router=None) -> ServingQuery:
    """One-call setup: server + query, started.

    ``backend``: ``"auto"`` (the DEFAULT: native when the toolchain
    allows, else python), ``"native"`` (C++ epoll reactor,
    ``native_front.py``), or ``"python"`` (threaded http.server front).
    Native is the serving answer under load: request parsing and
    socket writes stay out of the GIL, so at 16-way closed-loop
    saturation its p99 measures ~5.8 ms vs the python front's ~8.4 ms
    (and it sustains ~35% more throughput); single-connection p99s are
    equal (~1 ms, the reference's continuous-mode figure). Saturated
    closed-loop latency is conc/throughput by Little's law — sub-ms
    tails under load need either moderate load or more than one
    transform executor."""
    cls = ServingServer
    if backend in ("native", "auto"):
        try:
            from .native_front import NativeServingServer
            from ..native.loader import get_httpfront
            if get_httpfront() is None:
                raise RuntimeError("native http front unavailable")
            cls = NativeServingServer
        except Exception:
            if backend == "native":
                raise
    server = cls(name, host=host, port=port, reply_timeout=reply_timeout,
                 max_queue=max_queue, deadline=deadline,
                 max_inflight=max_inflight, tenancy=tenancy)
    if router is not None:
        # deploy plane (serving.deploy): versioned routing from the
        # very first request — admission stamps versions, replies echo
        # X-Model-Version, flips drain through _finish_request
        server.attach_router(router)
    server.start()
    # history plane (obs.timeseries): a served process records its own
    # trajectory — the sentinel's windowed p99 and the /debug/timeline
    # surface need points, not just instantaneous gauges. Idempotent;
    # bare ServingServer construction stays recorder-free so overhead
    # harnesses can measure the recorder-off baseline.
    _recorder.start()
    return ServingQuery(server, transform_fn).start()
