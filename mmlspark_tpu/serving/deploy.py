"""Zero-downtime model lifecycle: versioned registry, blue/green
router, canary burn-rate gating, automatic rollback.

The AOT store's content-addressed static fingerprint (``core/aot.py``)
IS a model version: two builds of the same pipeline class with
different fitted params fingerprint differently, so "deploy a new
model" is "publish new store entries beside the old ones and flip a
pointer". This module makes that flip a first-class operation:

- :class:`ModelRegistry` — named versions keyed by their static AOT
  fingerprints, persisted as ``registry.json`` beside the store root
  (so ``aot gc --keep-versions N`` can protect rollback targets
  without importing this module).
- :class:`VersionRouter` — the per-request routing point both serving
  fronts pass through (``ServingServer._admit``). Active / candidate /
  draining states; a flip is ONE atomic pointer swap under the router
  lock; in-flight requests complete on the version that admitted them
  (the drain is counted in ``deploy_draining_inflight``). Canary
  traffic is a deterministic admission-counter slice re-labeled onto a
  canary TENANT, so the candidate gets its own ``sched_tenant_*`` /
  ``serving_tenant_*`` series and its own error budget through the
  existing tenancy plane — no parallel accounting. Shadow mode mirrors
  active traffic through the candidate and compares responses
  (``deploy_shadow_mismatch_total``) without returning them.
- :class:`RolloutController` — the control loop (same shape as
  ``serving.autoscale.Autoscaler``: hysteresis, cooldown, monotonic
  clock only) that watches the canary tenant's multi-window SLO burn
  (``obs.fleet.BurnRateMonitor``) and the CUSUM sentinel
  (``obs.regression``). Sustained burn over budget rolls back to the
  prior version (``deploy_rollbacks_total{reason}`` + a
  ``deploy.rollback`` span) and degrades ``/healthz`` for the flap
  window; promotion requires N consecutive healthy canary windows.

Design rules (mirroring the autoscaler's):

- **determinism**: the canary slice is an admission-counter stride,
  not an RNG draw — the same request sequence always canaries the
  same requests, so chaos/bench runs reproduce by seed.
- **one atomic swap**: every router transition (flip, rollback,
  stage) happens under one lock; readers (``assign``) see either the
  old world or the new, never a half-flip.
- **drain, never drop**: a flipped-away version keeps serving its
  admitted in-flight requests; it retires only when its inflight
  count returns to zero.
- **monotonic clock only**: the controller runs on
  ``sched.policy.now`` — a wall-clock step must not fake a healthy
  window or a flap expiry (graftcheck wallclock pass).

Import is stdlib + obs/sched only — no JAX (the CI style job smokes
registry + flip + controller tick with no jax in the process).
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
from dataclasses import dataclass, field

from ..obs import registry as _default_registry
from ..obs.tracing import tracer as _tracer
from ..sched.policy import now

_LOG = logging.getLogger("mmlspark_tpu.serving.deploy")

REGISTRY_FILE = "registry.json"

# version lifecycle states
REGISTERED = "registered"   # named, not yet warmed or routed
WARMING = "warming"         # executables pre-loading on live workers
CANDIDATE = "candidate"     # staged for traffic (canary slice/shadow)
ACTIVE = "active"           # owns the traffic pointer
DRAINING = "draining"       # flipped away; finishing admitted work
RETIRED = "retired"         # done; eligible for gc (subject to last-N)

#: states that pin a version's store entries against ``aot.gc`` no
#: matter what keep-last-N says: collecting a rollback target (or the
#: version currently serving) mid-deploy would turn the next flip into
#: a compile storm
DEPLOY_STATES = (WARMING, CANDIDATE, ACTIVE, DRAINING)

_STATE_CODE = {REGISTERED: 0, WARMING: 1, CANDIDATE: 2, ACTIVE: 3,
               DRAINING: 4, RETIRED: 5}


@dataclass
class ModelVersion:
    """One named, deployable model build.

    ``static_fps`` are the AOT static fingerprints of its fused
    segments — the durable identity ``aot.gc`` protects; ``transform``
    is the runtime callable (in-memory only; re-attached after a
    registry reload by re-calling :meth:`ModelRegistry.register`)."""

    name: str
    seq: int
    static_fps: tuple = ()
    state: str = REGISTERED
    warmed: int = 0
    transform: object = None
    meta: dict = field(default_factory=dict)

    def record(self) -> dict:
        return {"name": self.name, "seq": self.seq,
                "static_fps": list(self.static_fps),
                "state": self.state, "warmed": self.warmed,
                "meta": dict(self.meta)}


def static_fps_of(obj, platform: str | None = None) -> tuple:
    """Best-effort static fingerprints of every fused segment in a
    transform object (``aot._segments_of`` reachability). Empty for a
    plain host callable — such a version still deploys, it just has no
    store entries to protect."""
    try:
        from ..core import aot
        fps = []
        for seg in aot._segments_of(obj):
            key = aot.segment_static_key(
                seg.stages, no_donate=getattr(seg, "no_donate", ()),
                expected_host=getattr(seg, "expected_host", ()),
                platform=platform)
            fps.append(aot._sha(key))
        return tuple(dict.fromkeys(fps))
    except Exception:
        return ()


class ModelRegistry:
    """Named model versions keyed by AOT static fingerprints.

    Persists to ``<root>/registry.json`` beside the AOT store tree
    (atomic tmp+replace, like the store's own writes) so the ``aot``
    CLI — a different process — can list versions and protect rollback
    targets during gc. ``root=None`` keeps the registry in-memory
    (tests, pure-routing deployments with no store)."""

    def __init__(self, root: str | None = None, *, service: str = "",
                 registry=None):
        self.root = root
        self.service = service
        self._reg = registry if registry is not None \
            else _default_registry
        self._lock = threading.Lock()
        self._versions: dict[str, ModelVersion] = {}
        self._g_versions = self._reg.gauge(
            "deploy_registry_versions",
            "model versions known to the deploy registry, by service")
        self._g_state = self._reg.gauge(
            "deploy_version_state",
            "version lifecycle state code (0 registered, 1 warming, "
            "2 candidate, 3 active, 4 draining, 5 retired)")
        if root:
            self._load()

    # -- persistence ---------------------------------------------------
    def path(self) -> str | None:
        return os.path.join(self.root, REGISTRY_FILE) if self.root \
            else None

    def _load(self) -> None:
        path = self.path()
        try:
            with open(path, encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return
        with self._lock:
            for rec in payload.get("versions", []):
                v = ModelVersion(
                    name=str(rec.get("name", "")),
                    seq=int(rec.get("seq", 0)),
                    static_fps=tuple(rec.get("static_fps", [])),
                    state=str(rec.get("state", REGISTERED)),
                    warmed=int(rec.get("warmed", 0)),
                    meta=dict(rec.get("meta", {})))
                if v.name:
                    self._versions[v.name] = v
            self._gauges_locked()

    def _save_locked(self) -> None:
        self._gauges_locked()
        path = self.path()
        if path is None:
            return
        payload = {"service": self.service,
                   "versions": [v.record() for v in
                                self._ordered_locked()]}
        os.makedirs(self.root, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".registry-")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _gauges_locked(self) -> None:
        self._g_versions.set(len(self._versions),
                             service=self.service)
        for v in self._versions.values():
            self._g_state.set(_STATE_CODE.get(v.state, 0),
                              service=self.service, version=v.name)

    def _ordered_locked(self) -> list[ModelVersion]:
        return sorted(self._versions.values(), key=lambda v: v.seq)

    # -- registration --------------------------------------------------
    def register(self, name: str, transform=None, *,
                 static_fps=None, meta: dict | None = None
                 ) -> ModelVersion:
        """Register (or re-attach, after a reload) a named version.
        ``static_fps`` defaults to the fingerprints derivable from
        ``transform``; an existing name keeps its sequence number and
        state — re-registering is how a restarted process re-attaches
        the runtime callable to a persisted version."""
        fps = tuple(static_fps) if static_fps is not None \
            else static_fps_of(transform)
        with self._lock:
            v = self._versions.get(name)
            if v is None:
                seq = 1 + max((x.seq for x in
                               self._versions.values()), default=0)
                v = ModelVersion(name=name, seq=seq)
                self._versions[name] = v
            v.transform = transform
            if fps:
                v.static_fps = fps
            if meta:
                v.meta.update(meta)
            self._save_locked()
            return v

    def get(self, name: str) -> ModelVersion | None:
        with self._lock:
            return self._versions.get(name)

    def versions(self) -> list[ModelVersion]:
        """All versions, oldest first (deploy order)."""
        with self._lock:
            return self._ordered_locked()

    def set_state(self, name: str, state: str) -> None:
        with self._lock:
            v = self._versions.get(name)
            if v is None or v.state == state:
                return
            v.state = state
            self._save_locked()

    # -- blue/green warm -----------------------------------------------
    def warm(self, name: str, service: str = "") -> int:
        """Warm-load the version's executables from the active AOT
        store (``aot.maybe_warm``) BEFORE any traffic sees it — the
        blue/green half of a deploy. Counts the loads on the version
        record so ``aot list`` can show warm state offline."""
        with self._lock:
            v = self._versions.get(name)
        if v is None:
            return 0
        from ..core import aot
        n = aot.maybe_warm(v.transform, service=service or self.service)
        with self._lock:
            v.warmed += n
            if v.state == REGISTERED:
                v.state = WARMING
            self._save_locked()
        return n

    def prebuild(self, name: str, store=None, log=_LOG.info) -> dict:
        """Pre-build the version's executables beside the old ones via
        ``aot.build_registered`` (the version's transform is registered
        as a buildable under ``<service>/<name>``). New entries land in
        the SAME content-addressed tree — fingerprints differ, so the
        old version's entries are untouched."""
        with self._lock:
            v = self._versions.get(name)
        if v is None:
            raise KeyError(name)
        from ..core import aot
        report = aot.build_registered(None, store)
        built = {e["static_fp"] for e in report.get("entries", [])}
        if built:
            with self._lock:
                v.static_fps = tuple(dict.fromkeys(
                    list(v.static_fps) + sorted(built)))
                self._save_locked()
        log("deploy prebuild [%s]: %d entries" %
            (name, len(report.get("entries", []))))
        return report

    # -- gc protection -------------------------------------------------
    def protected_fps(self, keep_last: int | None = None) -> set:
        """Static fingerprints ``aot.gc`` must not collect: every
        version in a deploy state (warming/candidate/active/draining —
        the live rollback set), plus the last ``keep_last`` versions by
        sequence (the operator's rollback horizon)."""
        with self._lock:
            ordered = self._ordered_locked()
        keep: set = set()
        for v in ordered:
            if v.state in DEPLOY_STATES:
                keep.update(v.static_fps)
        if keep_last:
            for v in ordered[-int(keep_last):]:
                keep.update(v.static_fps)
        return keep


class VersionRouter:
    """The atomic traffic pointer both serving fronts route through.

    ``assign`` is called once per admitted request (inside
    ``ServingServer._admit``, before the scheduler sees it) and stamps
    the request with the version that must serve it; ``release`` fires
    from ``_finish_request`` — the one terminal site both fronts share
    — so per-version inflight counts are exact and a draining version
    retires precisely when its last admitted request completes."""

    def __init__(self, registry: ModelRegistry, *, service: str = "",
                 canary_share: float = 0.0,
                 canary_tenant: str = "canary",
                 shadow: bool = False, metrics=None):
        self.registry = registry
        self.service = service or registry.service
        self.canary_tenant = canary_tenant
        self.shadow = bool(shadow)
        self._share = 0.0
        self._stride = 0
        self._lock = threading.Lock()
        self.active: str | None = None
        self.candidate: str | None = None
        self.prior: str | None = None
        self._inflight: dict[str, int] = {}
        self._admitted = 0
        reg = metrics if metrics is not None else _default_registry
        self._c_flips = reg.counter(
            "deploy_flips_total",
            "atomic active-version swaps (promotions included)")
        self._c_rollbacks = reg.counter(
            "deploy_rollbacks_total",
            "automatic/manual rollbacks, by service and reason")
        self._c_canary = reg.counter(
            "deploy_canary_requests_total",
            "requests routed to the candidate's canary slice")
        self._c_shadow = reg.counter(
            "deploy_shadow_mismatch_total",
            "shadow-mode responses that differed from the active "
            "version's")
        self._g_draining = reg.gauge(
            "deploy_draining_inflight",
            "admitted requests still completing on a flipped-away "
            "version, by service and version")
        self._set_share(canary_share)

    def _set_share(self, share: float) -> None:
        share = max(0.0, min(1.0, float(share)))
        self._share = share
        # deterministic slice: every stride-th admission canaries, so
        # the same request sequence canaries the same requests (no RNG)
        self._stride = int(round(1.0 / share)) if share > 0 else 0

    # -- lifecycle transitions -----------------------------------------
    def set_active(self, name: str) -> None:
        """Initial deploy (no traffic yet to drain from)."""
        with self._lock:
            old = self.active
            self.active = name
        self.registry.set_state(name, ACTIVE)
        if old and old != name:
            self._drain(old)

    def stage(self, name: str, *, canary_share: float | None = None,
              shadow: bool | None = None) -> None:
        """Stage a warmed version as the candidate: it starts receiving
        the canary slice (or mirrored shadow traffic) on the next
        admission — no restart, no queue flush."""
        with self._lock:
            if canary_share is not None:
                self._set_share(canary_share)
            if shadow is not None:
                self.shadow = bool(shadow)
            self.candidate = name
        self.registry.set_state(name, CANDIDATE)

    def flip(self) -> str | None:
        """Promote the candidate: ONE pointer swap under the lock.
        Requests admitted before the swap complete on the old version
        (it drains); requests admitted after see only the new one."""
        with self._lock:
            if self.candidate is None:
                return None
            old, new = self.active, self.candidate
            self.prior = old
            self.active = new
            self.candidate = None
        self._c_flips.inc(1, service=self.service)
        _tracer.emit_span("deploy.flip", parent=None, seconds=0.0,
                          service=self.service, version=new,
                          prior=old or "")
        self.registry.set_state(new, ACTIVE)
        if old:
            self._drain(old)
        return new

    def rollback(self, reason: str = "manual") -> str | None:
        """Back out the deploy: demote a live candidate, or — after a
        full flip — swap the prior version back in. Returns the demoted
        version (None when there is nothing to roll back)."""
        with self._lock:
            if self.candidate is not None:
                bad, self.candidate = self.candidate, None
                restored = self.active
            elif self.prior is not None:
                bad, self.active = self.active, self.prior
                restored = self.prior
                self.prior = None
            else:
                return None
        self._c_rollbacks.inc(1, service=self.service, reason=reason)
        _tracer.emit_span("deploy.rollback", parent=None, seconds=0.0,
                          service=self.service, version=bad or "",
                          restored=restored or "", reason=reason)
        _LOG.warning("deploy rollback [%s]: %s -> %s (%s)",
                     self.service, bad, restored, reason)
        if bad:
            self._drain(bad)
        return bad

    def _drain(self, name: str) -> None:
        with self._lock:
            left = self._inflight.get(name, 0)
        if left > 0:
            self.registry.set_state(name, DRAINING)
            self._g_draining.set(left, service=self.service,
                                 version=name)
        else:
            self.registry.set_state(name, RETIRED)
            self._g_draining.set(0, service=self.service, version=name)

    # -- per-request hot path ------------------------------------------
    def assign(self, tenant: str = "") -> tuple[str, str | None]:
        """Admission-time routing decision: ``(version, tenant_override)``.
        Acquires the version's inflight slot — the caller must
        ``release`` on every terminal outcome (the serving layer wires
        this through ``_finish_request``)."""
        with self._lock:
            self._admitted += 1
            ver = self.active or ""
            override = None
            if (self.candidate is not None and not self.shadow
                    and self._stride
                    and self._admitted % self._stride == 0):
                ver = self.candidate
                override = self.canary_tenant
            if ver:
                self._inflight[ver] = self._inflight.get(ver, 0) + 1
        if override is not None:
            self._c_canary.inc(1, service=self.service, version=ver)
        return ver, override

    def release(self, name: str) -> None:
        with self._lock:
            left = max(self._inflight.get(name, 1) - 1, 0)
            self._inflight[name] = left
        v = self.registry.get(name)
        if v is not None and v.state == DRAINING:
            self._g_draining.set(left, service=self.service,
                                 version=name)
            if left == 0:
                self.registry.set_state(name, RETIRED)

    def inflight(self, name: str) -> int:
        with self._lock:
            return self._inflight.get(name, 0)

    def draining_inflight(self) -> int:
        """Total admitted requests still completing on draining
        versions (0 = every flip fully drained)."""
        total = 0
        with self._lock:
            counts = dict(self._inflight)
        for name, left in counts.items():
            v = self.registry.get(name)
            if v is not None and v.state == DRAINING:
                total += left
        return total

    # -- executor / worker-pool lookups --------------------------------
    def transform_for(self, name: str):
        v = self.registry.get(name)
        return v.transform if v is not None else None

    def active_transform(self):
        with self._lock:
            name = self.active
        return self.transform_for(name) if name else None

    def transform_factory(self):
        """A zero-arg factory for ``ComputeWorkerPool``: a worker added
        by the autoscaler mid-deploy builds (and AOT-warms) the version
        that is active AT SPAWN TIME, not whatever was active when the
        pool was constructed."""
        def factory():
            return self.active_transform()
        return factory

    def shadow_pair(self) -> tuple[str, str] | None:
        """(active, candidate) when shadow comparison should run."""
        with self._lock:
            if self.shadow and self.candidate and self.active:
                return self.active, self.candidate
        return None

    def note_shadow_mismatch(self, n: int = 1) -> None:
        if n > 0:
            self._c_shadow.inc(n, service=self.service)

    def describe(self) -> dict:
        with self._lock:
            state = {
                "service": self.service,
                "active": self.active,
                "candidate": self.candidate,
                "prior": self.prior,
                "canary_share": self._share,
                "canary_tenant": self.canary_tenant,
                "shadow": self.shadow,
                "admitted": self._admitted,
                "inflight": dict(self._inflight),
            }
        state["versions"] = [v.record() for v in
                             self.registry.versions()]
        return state


@dataclass
class RolloutConfig:
    """Rollback/promotion policy knobs (autoscaler-config idiom)."""

    interval: float = 0.5        # control period (start() cadence)
    burn_threshold: float = 2.0  # canary fast-window burn => unhealthy
    slow_threshold: float = 1.0  # slow-window confirmation (multi-
                                 # window: a blip must not roll back)
    rollback_windows: int = 2    # consecutive unhealthy ticks to act
    promote_windows: int = 6     # consecutive healthy ticks to promote
    cooldown: float = 2.0        # post-action quiet period
    flap_s: float = 5.0          # /healthz degraded window after a
                                 # rollback


class RolloutController:
    """Watches the canary and decides: hold, promote, or roll back.

    Same control shape as ``serving.autoscale.Autoscaler``: periodic
    ``tick`` on a monotonic clock, hysteresis streaks, post-action
    cooldown, an events list for forensics. The canary's health signal
    is the existing SLO plane — the canary tenant's multi-window burn
    from :class:`~mmlspark_tpu.obs.fleet.BurnRateMonitor` plus the
    CUSUM sentinel's sustained set — so a rollback needs no new
    measurement machinery, only a policy over signals the fleet
    already pages on."""

    def __init__(self, router: VersionRouter, *, burn=None,
                 sentinel=None, config: RolloutConfig | None = None,
                 health=None, metrics=None, clock=now):
        self.router = router
        self.burn = burn
        self.sentinel = sentinel
        self.config = config or RolloutConfig()
        self.clock = clock
        reg = metrics if metrics is not None else _default_registry
        self._g_healthy = reg.gauge(
            "deploy_canary_healthy_windows",
            "consecutive healthy canary windows (promotion progress)")
        self._c_promotions = reg.counter(
            "deploy_promotions_total",
            "candidates promoted to active after N healthy windows")
        self.events: list[dict] = []
        self._lock = threading.Lock()
        self._healthy = 0
        self._unhealthy = 0
        self._cooldown_until = 0.0
        self._flap_until = 0.0
        self._flap_version = ""
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if health is not None:
            attach = getattr(health, "attach_deploy", None)
            if callable(attach):
                attach(self.deploy_reasons)

    def _record(self, kind: str, **attrs) -> None:
        self.events.append({"t": self.clock(), "kind": kind, **attrs})

    def deploy_reasons(self) -> list[str]:
        """The /healthz hook (``FleetHealth.attach_deploy``): non-empty
        while a rollback flap is in progress — the fleet must read
        degraded while traffic snaps back to the prior version."""
        with self._lock:
            if self.clock() < self._flap_until:
                return [f"deploy rollback flap ({self._flap_version})"]
        return []

    def tick(self, burns: dict | None = None) -> str:
        """One control decision. ``burns`` (``{tenant: {window:
        burn}}``) is read from the attached BurnRateMonitor when not
        injected (tests/scenarios pass it directly)."""
        cfg = self.config
        t = self.clock()
        if self.router.candidate is None:
            with self._lock:
                self._healthy = self._unhealthy = 0
            self._g_healthy.set(0, service=self.router.service)
            return "idle"
        if t < self._cooldown_until:
            return "cooldown"
        if burns is None:
            burns = self.burn.tick() if self.burn is not None else {}
        canary = burns.get(self.router.canary_tenant, {})
        fast = float(canary.get("fast", 0.0))
        slow = float(canary.get("slow", 0.0))
        sustained = frozenset()
        if self.sentinel is not None:
            sustained = self.sentinel.sustained()
        burning = fast >= cfg.burn_threshold \
            and slow >= cfg.slow_threshold
        if burning or sustained:
            with self._lock:
                self._unhealthy += 1
                self._healthy = 0
                unhealthy = self._unhealthy
            self._g_healthy.set(0, service=self.router.service)
            if unhealthy < cfg.rollback_windows:
                return "hold"
            reason = "burn" if burning else "regression"
            bad = self.router.rollback(reason)
            with self._lock:
                self._unhealthy = 0
                self._cooldown_until = t + cfg.cooldown
                self._flap_until = t + cfg.flap_s
                self._flap_version = bad or ""
            self._record("rollback", version=bad, reason=reason,
                         fast_burn=round(fast, 3),
                         slow_burn=round(slow, 3),
                         regressions=sorted(sustained))
            return "rollback"
        with self._lock:
            self._healthy += 1
            self._unhealthy = 0
            healthy = self._healthy
        self._g_healthy.set(healthy, service=self.router.service)
        if healthy < cfg.promote_windows:
            return "hold"
        promoted = self.router.flip()
        self._c_promotions.inc(1, service=self.router.service)
        with self._lock:
            self._healthy = 0
            self._cooldown_until = t + cfg.cooldown
        self._record("promote", version=promoted,
                     healthy_windows=healthy)
        return "promote"

    # -- background loop (autoscaler idiom) ----------------------------
    def start(self) -> "RolloutController":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rollout-controller")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.config.interval):
            try:
                self.tick()
            except Exception:
                _LOG.warning("rollout tick failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
