"""Serving DSL — the reader/writer chain of the reference.

Reference ``io/IOImplicits.scala:20-100``:

    spark.readStream.server().address(host, port, api).load()
      ...pipeline...
    .writeStream.server().replyTo(api).start()

Here:

    (read_stream().server().address(host, port, "api")
       .load()                       # -> ServingStream
       .transform(stage_or_fn)       # any Transformer or df->df callable
       .with_reply(fn)               # row value -> reply body
       .start())                     # -> ServingQuery
"""

from __future__ import annotations

import threading

import numpy as np

from ..core import DataFrame
from ..io.http.schema import request_to_string
from .server import ServingQuery, ServingServer
from .udfs import make_reply_udf


_shared_registry = None
_registry_lock = threading.Lock()


def _default_registry():
    """Process-wide DriverRegistry, created on first distributed load —
    the role of the reference's implicitly-started driver service
    (``DriverServiceUtils.createDriverService``). Creation is locked:
    two racing loads must not split the mesh across two registries."""
    global _shared_registry
    with _registry_lock:
        if _shared_registry is None:
            from .distributed import DriverRegistry
            _shared_registry = DriverRegistry().start()
        return _shared_registry


class _ReadStreamBuilder:
    def __init__(self):
        self._mode = "server"

    def server(self):
        self._mode = "server"
        return self

    def distributedServer(self):
        """Worker-mesh mode (reference ``distributedServer()``): the
        loaded server registers with a driver registry (pass one with
        ``.option("driverAddress", (host, port))`` or share the implicit
        process-wide one) so compute workers can lease its requests and
        replies route across processes."""
        self._mode = "distributed"
        return self

    def continuousServer(self):
        self._mode = "continuous"
        return self

    def address(self, host: str, port: int, api: str):
        self._host, self._port, self._api = host, port, api
        return self

    def option(self, key: str, value):
        setattr(self, f"_{key}", value)
        return self

    def load(self) -> "ServingStream":
        kwargs = dict(
            host=getattr(self, "_host", "127.0.0.1"),
            port=int(getattr(self, "_port", 0)),
            api_path="/" + getattr(self, "_api", ""),
            reply_timeout=float(getattr(self, "_replyTimeout", 30.0)),
            max_queue=int(getattr(self, "_maxQueue", 0)),
            # sched subsystem knobs: per-request deadline budget
            # (seconds; drives 429 load shedding + adaptive batch
            # closes) and per-route concurrency limit
            deadline=float(getattr(self, "_deadline", 0.0)),
            max_inflight=int(getattr(self, "_maxInflight", 0)))
        name = getattr(self, "_api", "default")
        if self._mode == "distributed":
            from .distributed import DistributedServingServer
            driver = getattr(self, "_driverAddress", None) or \
                _default_registry().address
            server = DistributedServingServer(
                name, driver, mesh_secret=getattr(self, "_meshSecret", ""),
                **kwargs)
        else:
            server = ServingServer(name, **kwargs)
        return ServingStream(server, mode=self._mode,
                             max_batch=int(getattr(self, "_maxBatch", 0)),
                             linger=float(getattr(self, "_linger", 0.0)))


def read_stream() -> _ReadStreamBuilder:
    return _ReadStreamBuilder()


class ServingStream:
    """A composable request stream: chain transforms, then reply.

    ``continuousServer()`` loads run record-at-a-time (``max_batch=1``,
    the reference's continuous-trigger semantics); other modes use
    dynamic batching, optionally with a micro-batch ``linger``."""

    def __init__(self, server: ServingServer, mode: str = "server",
                 max_batch: int = 0, linger: float = 0.0):
        self.server = server
        self.mode = mode
        self.max_batch = max_batch or (1 if mode == "continuous" else 1024)
        self.linger = linger
        self._stages: list = []
        self._reply_fn = None
        self._reply_col = "reply"

    def transform(self, stage):
        self._stages.append(stage)
        return self

    def compile_pipeline(self, example_df, aot_buckets=None,
                         **compile_kw):
        """Lower the transform chain added so far into ONE
        :class:`~mmlspark_tpu.core.compile.CompiledPipeline`: maximal
        runs of traceable stages fuse into single jitted XLA segments
        (donated inter-stage buffers), host-bound stages keep running
        eagerly between them. ``example_df`` must look like the frames
        the executor will build (typically ``{"id", "request"}`` plus
        whatever ``parse_request`` produces) — it drives the schema
        propagation that decides segment boundaries.

        ``aot_buckets``: padding-bucket row counts to register with the
        AOT executable store's build CLI (``python -m
        mmlspark_tpu.core.aot build``) — compilation becomes a build
        step, and ``start()`` warm-loads the store so a fresh worker's
        first request never pays a compile (``docs/aot.md``)."""
        from ..core.compile import compile_pipeline
        compile_kw.setdefault("service", "serving")
        pre_stages = list(self._stages)
        self._stages = [compile_pipeline(pre_stages, example_df,
                                         **compile_kw)]
        if aot_buckets:
            from ..core import aot
            service = self.server.name
            buckets = tuple(int(b) for b in aot_buckets)
            aot.register_buildable(
                service,
                lambda: {"stages": pre_stages, "example": example_df,
                         "buckets": buckets,
                         "mesh": compile_kw.get("mesh"),
                         "rules": compile_kw.get("rules")})
        return self

    def parse_request(self, parser=None):
        """Add a stage turning the raw request into a value column
        (reference ``ServingImplicits.parseRequest``). Default: body text →
        'value' column."""
        parser = parser or (lambda r: request_to_string(r))

        def stage(df):
            col = np.empty(len(df), object)
            col[:] = [parser(r) for r in df["request"]]
            return df.with_column("value", col)
        self._stages.append(stage)
        return self

    def with_reply(self, fn, input_col: str = "value"):
        """Final stage: fn(row value) → reply body
        (reference ``makeReply``)."""
        self._reply_fn = (fn, input_col)
        return self

    def start(self, name: str | None = None) -> ServingQuery:
        stages = list(self._stages)
        reply = self._reply_fn

        def run(df: DataFrame) -> DataFrame:
            for s in stages:
                df = s.transform(df) if hasattr(s, "transform") else s(df)
            if reply is not None:
                fn, col = reply
                out = np.empty(len(df), object)
                out[:] = [make_reply_udf(fn(v)) for v in df[col]]
                df = df.with_column("reply", out)
            return df

        # surface fused-pipeline dispatch counts to the executor's
        # FeatureLog rows (ServingQuery reads transform_fn.compiled_segments).
        # None = compile_pipeline never ran; 0 = it ran and everything
        # stayed host-bound — operators auditing fusion coverage need
        # the distinction
        segs = [s.compiled_segments for s in stages
                if hasattr(s, "compiled_segments")]
        run.compiled_segments = sum(segs) if segs else None
        # the warm helpers (core/aot.maybe_warm) and introspection walk
        # the chain through this attribute — the closure hides it.
        # ServingQuery.start() below owns the AOT warm boot (it follows
        # run.stages to the fused segments), so the chain loads its
        # store executables before the first request on either path.
        run.stages = stages

        self.server.start()
        return ServingQuery(self.server, run, name=name,
                            max_batch=self.max_batch,
                            linger=self.linger).start()
