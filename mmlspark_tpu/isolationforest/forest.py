"""Isolation forest: batched random trees in XLA.

Standard iForest (Liu, Ting, Zhou 2008), the algorithm under the
reference's LinkedIn wrapper. TPU formulation: a forest is three dense
arrays [T, NN] (feature, threshold, children implicit by index); growth is
vmapped over trees; path length is a fixed-depth ``fori_loop`` gather.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import ComplexParam, Estimator, Model, Param, \
    TypeConverters as TC
from ..core.contracts import HasFeaturesCol
from ..core.utils import as_2d_features


def _c_factor(n: float) -> float:
    """Average unsuccessful BST search length (anomaly-score normalizer)."""
    if n <= 1:
        return 0.0
    return 2.0 * (np.log(n - 1) + 0.5772156649) - 2.0 * (n - 1) / n


def _grow_forest(x: np.ndarray, num_trees: int, sample_size: int,
                 max_depth: int, rng: np.random.Generator):
    """Host-side growth (cheap: sample_size ≤ 256 rows/tree), producing
    fixed-shape arrays for the jitted scorer."""
    n, F = x.shape
    NN = 2 ** (max_depth + 1) - 1
    feature = np.full((num_trees, NN), -1, np.int32)
    thresh = np.zeros((num_trees, NN), np.float32)
    size = np.zeros((num_trees, NN), np.float32)   # rows at node (leaf term)

    for t in range(num_trees):
        take = rng.choice(n, size=min(sample_size, n), replace=False)
        # node_rows[i] = bool mask over the tree's sample
        stack = [(0, np.ones(len(take), bool), 0)]
        while stack:
            node, mask, depth = stack.pop()
            rows = x[take][mask]
            size[t, node] = mask.sum()
            if depth >= max_depth or mask.sum() <= 1:
                continue
            f = int(rng.integers(F))
            lo, hi = rows[:, f].min(), rows[:, f].max()
            if lo == hi:
                continue
            s = float(rng.uniform(lo, hi))
            feature[t, node] = f
            thresh[t, node] = s
            go_left = np.zeros_like(mask)
            go_left[mask] = x[take][mask][:, f] < s
            stack.append((2 * node + 1, go_left, depth + 1))
            stack.append((2 * node + 2, mask & ~go_left, depth + 1))
    return feature, thresh, size


@functools.partial(jax.jit, static_argnames=("max_depth",))
def _path_lengths(feature, thresh, size, x, *, max_depth: int):
    """[Q] mean path length over trees; heap-indexed trees, fixed depth."""
    Q = x.shape[0]
    T = feature.shape[0]

    def one_tree(feat_t, thr_t, size_t):
        node = jnp.zeros(Q, jnp.int32)
        depth = jnp.zeros(Q, jnp.float32)
        done = jnp.zeros(Q, bool)

        def step(_, carry):
            node, depth, done = carry
            f = feat_t[node]
            is_leaf = f < 0
            xv = jnp.take_along_axis(x, jnp.maximum(f, 0)[:, None],
                                     axis=1)[:, 0]
            left = xv < thr_t[node]
            nxt = jnp.where(left, 2 * node + 1, 2 * node + 2)
            newly_done = (~done) & is_leaf
            done2 = done | is_leaf
            node2 = jnp.where(done2, node, nxt)
            depth2 = jnp.where(done2, depth, depth + 1.0)
            del newly_done
            return node2, depth2, done2

        node, depth, done = jax.lax.fori_loop(
            0, max_depth + 1, step, (node, depth, done))
        # leaf adjustment: c(size) term for unsplit leaves
        leaf_n = size_t[node]
        adj = jnp.where(
            leaf_n > 1.0,
            2.0 * (jnp.log(jnp.maximum(leaf_n - 1.0, 1e-9)) + 0.5772156649)
            - 2.0 * (leaf_n - 1.0) / jnp.maximum(leaf_n, 1.0),
            0.0)
        return depth + adj

    paths = jax.vmap(one_tree)(feature, thresh, size)    # [T, Q]
    return paths.mean(axis=0)


class IsolationForest(Estimator, HasFeaturesCol):
    numEstimators = Param("numEstimators", "trees in the forest", TC.toInt,
                          default=100)
    maxSamples = Param("maxSamples", "subsample per tree", TC.toInt,
                       default=256)
    maxDepth = Param("maxDepth", "tree depth cap (0 = log2(maxSamples))",
                     TC.toInt, default=0)
    contamination = Param("contamination",
                          "expected anomaly fraction (sets threshold)",
                          TC.toFloat, default=0.1)
    randomSeed = Param("randomSeed", "seed", TC.toInt, default=0)
    predictionCol = Param("predictionCol", "0/1 anomaly flag column",
                          TC.toString, default="predictedLabel")
    scoreCol = Param("scoreCol", "anomaly score column", TC.toString,
                     default="outlierScore")

    def _fit(self, df):
        x = as_2d_features(df, self.getFeaturesCol()).astype(np.float32)
        rng = np.random.default_rng(self.get("randomSeed"))
        sample = min(self.get("maxSamples"), x.shape[0])
        depth = self.get("maxDepth") or max(
            1, int(np.ceil(np.log2(max(sample, 2)))))
        feature, thresh, size = _grow_forest(
            x, self.get("numEstimators"), sample, depth, rng)
        c = _c_factor(sample)
        # threshold from train-set score quantile at `contamination`
        lengths = np.asarray(_path_lengths(
            jnp.asarray(feature), jnp.asarray(thresh), jnp.asarray(size),
            jnp.asarray(x), max_depth=depth))
        scores = 2.0 ** (-lengths / max(c, 1e-9))
        thr = float(np.quantile(scores, 1.0 - self.get("contamination")))
        model = IsolationForestModel(
            feature=feature, thresh=thresh, size=size, cFactor=c,
            treeDepth=depth, threshold=thr)
        self._copy_params_to(model)
        return model


class IsolationForestModel(Model, HasFeaturesCol):
    feature = ComplexParam("feature", "[T, NN] split features")
    thresh = ComplexParam("thresh", "[T, NN] split thresholds")
    size = ComplexParam("size", "[T, NN] node sizes")
    cFactor = Param("cFactor", "normalizer c(sample_size)", TC.toFloat)
    treeDepth = Param("treeDepth", "depth cap", TC.toInt)
    threshold = Param("threshold", "score threshold", TC.toFloat)
    predictionCol = Param("predictionCol", "0/1 anomaly flag column",
                          TC.toString, default="predictedLabel")
    scoreCol = Param("scoreCol", "anomaly score column", TC.toString,
                     default="outlierScore")

    def _transform(self, df):
        x = as_2d_features(df, self.getFeaturesCol()).astype(np.float32)
        lengths = np.asarray(_path_lengths(
            jnp.asarray(self.get("feature")), jnp.asarray(self.get("thresh")),
            jnp.asarray(self.get("size")), jnp.asarray(x),
            max_depth=self.get("treeDepth")))
        scores = 2.0 ** (-lengths / max(self.get("cFactor"), 1e-9))
        flags = (scores >= self.get("threshold")).astype(np.float64)
        return (df.with_column(self.get("scoreCol"),
                               scores.astype(np.float64))
                  .with_column(self.get("predictionCol"), flags))
