"""Isolation forest anomaly detection.

Reference ``isolationforest/IsolationForest.scala:18-66`` wraps LinkedIn's
``isolation-forest`` JVM library; here the algorithm itself is implemented
TPU-first: all trees grow at once as fixed-shape arrays (vmapped random
splits), and scoring routes every row through every tree in one jitted
program.
"""

from .forest import IsolationForest, IsolationForestModel

__all__ = ["IsolationForest", "IsolationForestModel"]
