"""BERT-architecture encoder for EXTERNAL checkpoint ingestion.

``TextEncoder`` (pre-LN, sinusoidal positions) is the framework's native
architecture; foreign pretrained checkpoints (BERT-class: post-LN
blocks, LEARNED position + token-type embeddings, embedding LayerNorm)
cannot be mapped onto it weight-for-weight. This module reproduces the
published BERT computation exactly so ``models.convert
.torch_bert_to_flax`` can ingest a foreign ``state_dict`` and the
result is numerically the checkpoint's own network (oracle-tested
against a locally-constructed torch reference, the vision-converter
pattern). Fills the reference's pretrained-model supply chain for text
(``downloader/ModelDownloader.scala:37-60`` + ``image/ImageFeaturizer
.scala:81-85`` run real downloaded weights).

Output contract matches ``TextEncoder`` — ``{"tokens": [N, T, W],
"pooled": [N, W]}`` (masked mean over non-pad tokens) — so
``TextEncoderFeaturizer`` and the zoo treat both interchangeably; a
converted checkpoint additionally exposes ``"cls"`` (the [CLS]
position) and, when the checkpoint carried a pooler, ``"cls_pooled"``
(tanh-projected [CLS], BERT's sentence vector).

The attention implementation is pluggable exactly like
``TextEncoder``'s (dense/pallas/blockwise/ring/ulysses) — attention has
no parameters, so converted weights are valid under any impl.
"""

from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp
from flax import linen as nn

from ..parallel.partition import constrain_activation
from .text_encoder import _dense_attention


class BertBlock(nn.Module):
    """Post-LN transformer block (the published BERT layer): attention
    and feed-forward residuals each followed by LayerNorm, exact-erf
    GELU in the feed-forward."""
    heads: int
    mlp_dim: int
    width: int
    attention_fn: Callable = _dense_attention
    dtype: Any = jnp.float32

    def setup(self):
        W = self.width
        self.q = nn.Dense(W, dtype=self.dtype, name="q")
        self.k = nn.Dense(W, dtype=self.dtype, name="k")
        self.v = nn.Dense(W, dtype=self.dtype, name="v")
        self.out = nn.Dense(W, dtype=self.dtype, name="out")
        self.ln_att = nn.LayerNorm(epsilon=1e-12, dtype=jnp.float32,
                                   name="ln_att")
        self.mlp_1 = nn.Dense(self.mlp_dim, dtype=self.dtype,
                              name="mlp_1")
        self.mlp_2 = nn.Dense(W, dtype=self.dtype, name="mlp_2")
        self.ln_ffn = nn.LayerNorm(epsilon=1e-12, dtype=jnp.float32,
                                   name="ln_ffn")

    def __call__(self, x, key_mask=None):
        B, T, W = x.shape
        hd = W // self.heads

        def split(a):
            return a.reshape(B, T, self.heads, hd).transpose(0, 2, 1, 3)

        o = self.attention_fn(split(self.q(x)), split(self.k(x)),
                              split(self.v(x)), key_mask)
        o = o.transpose(0, 2, 1, 3).reshape(B, T, W).astype(self.dtype)
        x = self.ln_att(x + self.out(o)).astype(self.dtype)
        h = nn.gelu(self.mlp_1(x), approximate=False)
        return self.ln_ffn(x + self.mlp_2(h)).astype(self.dtype)


class BertEncoder(nn.Module):
    """Token ids [N, T] → ``{"tokens", "pooled", "cls"[, "cls_pooled"]}``.

    Same attribute names as ``TextEncoder`` (vocab/width/depth/heads/
    mlp_dim/max_len/dtype/attention_fn) so ``TextEncoderFeaturizer``
    rebuilds either architecture with a requested attention impl; pad
    id 0 is masked out of attention keys and the mean pool, the
    framework-wide convention (standard BERT vocabularies also place
    [PAD] at 0)."""
    vocab: int = 30522
    width: int = 256
    depth: int = 4
    heads: int = 4
    mlp_dim: int = 1024
    max_len: int = 512
    type_vocab: int = 2
    pooler: bool = True
    attention_fn: Callable = _dense_attention
    dtype: Any = jnp.float32
    # rematerialize blocks in the backward (the same fine-tuning memory
    # lever TextEncoder exposes — activations recomputed, not stored)
    remat: bool = False

    def setup(self):
        self.word = nn.Embed(self.vocab, self.width, dtype=self.dtype,
                             name="word")
        self.pos = nn.Embed(self.max_len, self.width, dtype=self.dtype,
                            name="pos")
        self.typ = nn.Embed(self.type_vocab, self.width,
                            dtype=self.dtype, name="type")
        self.embed_ln = nn.LayerNorm(epsilon=1e-12, dtype=jnp.float32,
                                   name="embed_ln")  # BERT layer_norm_eps
        block_cls = nn.remat(BertBlock) if self.remat else BertBlock
        self.blocks = [block_cls(self.heads, self.mlp_dim, self.width,
                                 attention_fn=self.attention_fn,
                                 dtype=self.dtype, name=f"block{i}")
                       for i in range(self.depth)]
        if self.pooler:
            self.pooler_dense = nn.Dense(self.width, dtype=self.dtype,
                                         name="pooler")

    def __call__(self, ids, train: bool = False, type_ids=None):
        T = ids.shape[1]
        if T > self.max_len:
            # learned positions end at max_len; nn.Embed would silently
            # CLAMP indices past the table (every overflow position
            # reusing the last embedding) — fail loudly instead
            raise ValueError(
                f"sequence length {T} exceeds this checkpoint's "
                f"learned position table ({self.max_len}); truncate or "
                "chunk upstream (WordPieceTokenizerModel maxLength)")
        x = self.word(ids) + self.pos(jnp.arange(T))[None]
        x = x + self.typ(jnp.zeros_like(ids) if type_ids is None
                         else type_ids)
        # block-boundary activation sharding (batch over dp, per the
        # registered activation spec): under a mesh-scoped partitioned
        # step this pins the [B, T, W] residual stream — and the remat
        # recompute buffers with it — batch-sharded between blocks;
        # with no mesh in scope it is the identity
        x = constrain_activation(self.embed_ln(x).astype(self.dtype),
                                 "BertEncoder")
        key_mask = ids != 0
        for block in self.blocks:
            x = constrain_activation(block(x, key_mask), "BertEncoder")
        mask = key_mask.astype(jnp.float32)[..., None]
        pooled = (x * mask).sum(1) / jnp.maximum(mask.sum(1), 1.0)
        out = {"tokens": x, "pooled": pooled.astype(jnp.float32),
               "cls": x[:, 0].astype(jnp.float32)}
        if self.pooler:
            out["cls_pooled"] = jnp.tanh(
                self.pooler_dense(x[:, 0])).astype(jnp.float32)
        return out


# Partition rules for ingested BERT checkpoints: vocab-sharded word
# embedding (the one genuinely large table), Megatron column→row pairs
# inside each block (q/k/v/mlp_1 shard outputs, out/mlp_2 shard
# inputs), everything per-channel replicated. Specs right-align
# (parallel/partition.py); `re.search` is unanchored, so the same
# rules match the tree under any prefix — a bare params dict, a
# TrainState, or an optax moment tree.
from ..parallel.partition import DtypePolicy, register_partition_rules

register_partition_rules("BertEncoder", [
    (r"word/embedding", ("tp", None)),
    (r"(pos|type)/embedding", ()),
    (r"(embed_ln|ln_att|ln_ffn)/(scale|bias)", ()),
    (r"(q|k|v)/kernel", (None, "tp")),
    (r"(q|k|v)/bias", ("tp",)),
    (r"out/kernel", ("tp", None)),
    (r"out/bias", ()),
    (r"mlp_1/kernel", (None, "tp")),
    (r"mlp_1/bias", ("tp",)),
    (r"mlp_2/kernel", ("tp", None)),
    (r"mlp_2/bias", ()),
    (r"pooler/(kernel|bias)", ()),
],
    # chip-tuned defaults, selectable via dtype_policy_for: bf16
    # compute with fp32 storage/accum (arXiv:2008.01040's safe point);
    # activations batch-shard over dp at block boundaries
    dtype_policy=DtypePolicy(param_dtype="float32",
                             compute_dtype="bfloat16",
                             grad_accum_dtype="float32"),
    activation_spec=("dp",))
