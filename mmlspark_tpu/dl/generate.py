"""Autoregressive generation for causal-LM models.

Rounds out the text stack (BPE → causal pretraining → generation); the
reference has no language-model surface at all (SURVEY §5 marks text as
the framework's extension axis).

TPU shape discipline: the ids buffer is a FIXED [B, max_len] array and
the whole decode is one ``lax.scan`` under one ``jit`` — every step
re-encodes the buffer through the causal encoder (prefill-style
decode; the pad mask hides unwritten positions, and causality makes
the logits at the last written position independent of the padding).
O(steps · T²) attention: right for short generations and exact; a KV
cache is the optimization, not a semantic change.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _make_run(module, max_new_tokens: int, temperature: float,
              pad_id: int):
    """One jitted decode program per (module, decode config) — weights
    and buffers are traced arguments, so repeated generate() calls with
    the same shapes hit the compile cache instead of retracing."""

    @jax.jit
    def run(params, buf, ptr, key):
        B = buf.shape[0]

        def step(carry, _):
            buf, ptr, key = carry
            logits = module.apply({"params": params}, buf)["logits"]
            # logits at the LAST WRITTEN position predict the next token
            last = jnp.take_along_axis(
                logits, (ptr - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]                           # [B, V]
            # never emit pad: it would terminate the row's mask early
            last = last.at[:, pad_id].set(-jnp.inf)
            key, sub = jax.random.split(key)
            if temperature > 0:
                nxt = jax.random.categorical(sub, last / temperature,
                                             axis=-1)
            else:
                nxt = jnp.argmax(last, axis=-1)
            nxt = nxt.astype(jnp.int32)
            buf = buf.at[jnp.arange(B), ptr].set(nxt)
            return (buf, ptr + 1, key), None

        (buf, ptr, _), _ = jax.lax.scan(
            step, (buf, ptr, key), None, length=max_new_tokens)
        return buf

    return run


_RUN_CACHE: dict = {}


def generate(module, variables, prompt_ids, *, max_new_tokens: int,
             max_len: int | None = None, temperature: float = 0.0,
             seed: int = 0, pad_id: int = 0):
    """Generate continuations for a batch of prompts.

    ``module`` must produce token logits (``MaskedLMModel`` — the same
    trunk+head causal pretraining trains) and must run causal
    attention — enforced by the same perturbation probe
    ``pretrain_causal_lm`` uses (a bidirectional encoder would
    condition on its own padding, silently).

    ``prompt_ids``: [B, Tp] int32, RIGHT-padded with ``pad_id`` (a
    left-padded or empty row raises — the write pointer is the non-pad
    count). Returns [B, max_len] int32 — prompts, then generated
    tokens, then pad. ``temperature`` 0 = greedy; > 0 = softmax
    sampling."""
    from .pretrain import assert_causal

    prompt_ids = np.asarray(prompt_ids, np.int32)
    B, Tp = prompt_ids.shape
    max_len = max_len or (Tp + max_new_tokens)
    if max_len < Tp + max_new_tokens:
        raise ValueError(
            f"max_len={max_len} cannot hold the prompt ({Tp}) plus "
            f"{max_new_tokens} new tokens")
    # per-row write pointer = non-pad count — only correct for strictly
    # right-padded prompts, so validate instead of silently scrambling
    ptr = (prompt_ids != pad_id).sum(axis=1).astype(np.int32)
    if (ptr == 0).any():
        raise ValueError("empty (all-pad) prompt row")
    trailing_ok = np.all(
        (np.arange(Tp)[None, :] < ptr[:, None])
        == (prompt_ids != pad_id))
    if not trailing_ok:
        raise ValueError(
            f"prompts must be RIGHT-padded with pad_id={pad_id} "
            "(found a pad before a real token)")
    vocab = getattr(getattr(module, "encoder", None), "vocab",
                    int(prompt_ids.max()) + 2)
    assert_causal(module, {"params": variables["params"]},
                  prompt_ids[:1, :max(int(ptr[0]), 2)], vocab)

    buf = np.full((B, max_len), pad_id, np.int32)
    buf[:, :Tp] = prompt_ids
    # keyed on the module OBJECT (hashable frozen dataclass): an id()
    # key could collide after garbage collection and silently serve a
    # different model's compiled program
    key = (module, max_new_tokens, float(temperature), pad_id)
    run = _RUN_CACHE.get(key)
    if run is None:
        run = _RUN_CACHE[key] = _make_run(module, max_new_tokens,
                                          temperature, pad_id)
    return np.asarray(run(variables["params"], jnp.asarray(buf),
                          jnp.asarray(ptr), jax.random.PRNGKey(seed)))
