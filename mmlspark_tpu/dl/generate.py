"""Autoregressive generation for causal-LM models.

Rounds out the text stack (BPE → causal pretraining → generation); the
reference has no language-model surface at all (SURVEY §5 marks text as
the framework's extension axis).

TPU shape discipline: the ids buffer is a FIXED [B, max_len] array and
the whole decode is one ``lax.scan`` under one ``jit``. The default
path keeps per-block KV caches — prefill and decode unify into one
scan where each step embeds one token and attends over the cache
(O(L²·W) total). ``use_cache=False`` re-encodes the buffer every step
through the encoder's own attention_fn (O(steps·L²·W)) — the reference
the cached path is equivalence-tested against.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from ..core.contracts import HasInputCol, HasOutputCol
from ..core.param import (ComplexParam, Param, StageParam,
                          TypeConverters as TC)
from ..core.pipeline import Transformer


def _sample(logits, key, temperature: float, pad_id: int):
    """Shared sampling epilogue — ONE copy so the cached and re-encode
    paths cannot drift. Never emits pad (it would terminate the row's
    mask early)."""
    logits = logits.at[:, pad_id].set(-jnp.inf)
    if temperature > 0:
        nxt = jax.random.categorical(key, logits / temperature, axis=-1)
    else:
        nxt = jnp.argmax(logits, axis=-1)
    return nxt.astype(jnp.int32)


def _make_run(module, max_new_tokens: int, temperature: float,
              pad_id: int):
    """One jitted decode program per (module, decode config) — weights
    and buffers are traced arguments, so repeated generate() calls with
    the same shapes hit the compile cache instead of retracing."""

    @jax.jit
    def run(params, buf, ptr, key):
        B = buf.shape[0]

        def step(carry, i):
            buf, ptr = carry
            logits = module.apply({"params": params}, buf)["logits"]
            # logits at the LAST WRITTEN position predict the next token
            last = jnp.take_along_axis(
                logits, (ptr - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]                           # [B, V]
            # per-step key by fold_in (not a split chain): deterministic
            # given (seed, step index) alone
            nxt = _sample(last, jax.random.fold_in(key, i), temperature,
                          pad_id)
            buf = buf.at[jnp.arange(B), ptr].set(nxt)
            return (buf, ptr + 1), None

        (buf, ptr), _ = jax.lax.scan(
            step, (buf, ptr), jnp.arange(max_new_tokens))
        return buf

    return run


def _make_cached_run(module, max_new_tokens: int, temperature: float,
                     pad_id: int, scan_len: int, prefill_len: int):
    """KV-cached decode: batched prefill + ONE scan over the writable
    positions. The first ``prefill_len`` positions (statically
    ``min(prompt_len) - 1`` — guaranteed real tokens in every row) seed
    the per-block KV caches in one causal forward whose projections are
    large MXU matmuls; the scan then starts at the first position whose
    write can matter, each step embedding one token and attending over
    the caches (O(L·W) per step instead of a full O(L²·W) re-encode)."""

    @jax.jit
    def run(params, buf, ptr, key):
        B, L = buf.shape
        enc = module.encoder
        hd = enc.width // enc.heads
        caches = tuple(
            (jnp.zeros((B, enc.heads, L, hd), enc.dtype),
             jnp.zeros((B, enc.heads, L, hd), enc.dtype))
            for _ in range(enc.depth))
        if prefill_len > 0:
            caches = module.apply(
                {"params": params}, buf[:, :prefill_len], caches,
                method="prefill")

        def step(carry, pos):
            buf, caches = carry
            tok = jax.lax.dynamic_slice_in_dim(buf, pos, 1,
                                               axis=1)[:, 0]
            logits, caches = module.apply(
                {"params": params}, tok, caches, pos,
                method="decode_step")                   # [B, V]
            # per-POSITION fold_in: for ragged batches the same written
            # token index lands at different positions per row, so the
            # temperature>0 stream is path-specific (greedy is the
            # cached-vs-reencode equivalence contract)
            nxt = _sample(logits, jax.random.fold_in(key, pos),
                          temperature, pad_id)
            # write at pos+1 only inside this row's generation window;
            # prompt positions keep their tokens, the rest stays pad
            write = (pos + 1 >= ptr) & (pos + 1 < ptr + max_new_tokens)
            cur = jax.lax.dynamic_slice_in_dim(buf, pos + 1, 1,
                                               axis=1)[:, 0]
            buf = jax.lax.dynamic_update_slice(
                buf, jnp.where(write, nxt, cur)[:, None], (0, pos + 1))
            return (buf, caches), None

        # scan only positions that can still write: start past the
        # prefilled prefix, stop at the last useful write position (the
        # buffer tail past every row's window would burn full decode
        # steps for nothing)
        (buf, _), _ = jax.lax.scan(
            step, (buf, caches),
            jnp.arange(prefill_len, min(scan_len, L - 1)))
        return buf

    return run


# bounded LRU: each entry pins its flax module AND its jitted decode
# program for as long as it stays hot — an unbounded dict would leak
# compiled programs in long-lived serving processes that cycle models
_RUN_CACHE: OrderedDict = OrderedDict()
_RUN_CACHE_MAX = 16
# modules whose causality probe already passed — the property is fixed
# per module architecture, so re-probing every generate() call would
# cost two eager encoder forwards per request on the serving path
_CAUSAL_OK: OrderedDict = OrderedDict()
# one lock for both caches: concurrent serving threads cycling > MAX
# models would otherwise race get/move_to_end against popitem eviction
_CACHE_LOCK = threading.Lock()


def generate(module, variables, prompt_ids, *, max_new_tokens: int,
             max_len: int | None = None, temperature: float = 0.0,
             seed: int = 0, pad_id: int = 0, use_cache: bool = True):
    """Generate continuations for a batch of prompts.

    ``module`` must produce token logits (``MaskedLMModel`` — the same
    trunk+head causal pretraining trains) and must run causal
    attention — enforced by the same perturbation probe
    ``pretrain_causal_lm`` uses (a bidirectional encoder would
    condition on its own padding, silently).

    ``prompt_ids``: [B, Tp] int32, RIGHT-padded with ``pad_id`` (a
    left-padded or empty row raises — the write pointer is the non-pad
    count). Returns [B, max_len] int32 — prompts, then generated
    tokens, then pad. ``temperature`` 0 = greedy; > 0 = softmax
    sampling.

    ``use_cache`` (default): KV-cached decode — O(L²·W) total via one
    scan with per-block caches. ``use_cache=False`` re-encodes the
    whole buffer every step (O(steps·L²·W)) through the encoder's own
    attention_fn — the reference path the cached one is tested
    against."""
    from .pretrain import assert_causal

    prompt_ids = np.asarray(prompt_ids, np.int32)
    B, Tp = prompt_ids.shape
    max_len = max_len or (Tp + max_new_tokens)
    if max_len < Tp + max_new_tokens:
        raise ValueError(
            f"max_len={max_len} cannot hold the prompt ({Tp}) plus "
            f"{max_new_tokens} new tokens")
    # per-row write pointer = non-pad count — only correct for strictly
    # right-padded prompts, so validate instead of silently scrambling
    ptr = (prompt_ids != pad_id).sum(axis=1).astype(np.int32)
    if (ptr == 0).any():
        raise ValueError("empty (all-pad) prompt row")
    trailing_ok = np.all(
        (np.arange(Tp)[None, :] < ptr[:, None])
        == (prompt_ids != pad_id))
    if not trailing_ok:
        raise ValueError(
            f"prompts must be RIGHT-padded with pad_id={pad_id} "
            "(found a pad before a real token)")
    with _CACHE_LOCK:
        causal_ok = module in _CAUSAL_OK
    if not causal_ok:
        vocab = getattr(getattr(module, "encoder", None), "vocab",
                        int(prompt_ids.max()) + 2)
        probe = prompt_ids[:1, :max(int(ptr[0]), 2)]
        if probe.shape[1] < 2:
            # a single-token prompt would make the probe a silent no-op
            # — duplicate the token so the check always actually runs
            # before the module is marked causally OK
            probe = np.repeat(probe, 2, axis=1)
        assert_causal(module, {"params": variables["params"]}, probe,
                      vocab)
        with _CACHE_LOCK:
            _CAUSAL_OK[module] = True
            while len(_CAUSAL_OK) > _RUN_CACHE_MAX:
                _CAUSAL_OK.popitem(last=False)

    buf = np.full((B, max_len), pad_id, np.int32)
    buf[:, :Tp] = prompt_ids
    # keyed on the module OBJECT (hashable frozen dataclass): an id()
    # key could collide after garbage collection and silently serve a
    # different model's compiled program
    scan_len = Tp + max_new_tokens - 1  # last useful write position
    # batched-prefill length: positions [0, min(ptr) - 1) hold real
    # tokens in EVERY row, so their caches can be seeded in one causal
    # forward; the scan takes over at the first position whose write
    # can matter. Static (ptr is host-side numpy), part of the key —
    # bucketed DOWN to a power of two so ragged serving batches whose
    # shortest prompt wobbles by a token share a compiled program
    # (any prefix ≤ min(ptr)-1 is a valid prefill; the scan streams
    # the remainder)
    prefill_len = max(int(ptr.min()) - 1, 0)
    if prefill_len >= 64:
        prefill_len -= prefill_len % 64   # ≤ 63 steps streamed instead
    elif prefill_len > 0:
        prefill_len = 1 << (prefill_len.bit_length() - 1)
    key = (module, max_new_tokens, float(temperature), pad_id,
           bool(use_cache),
           (scan_len, prefill_len) if use_cache else None)
    with _CACHE_LOCK:
        run = _RUN_CACHE.get(key)
        if run is not None:
            _RUN_CACHE.move_to_end(key)
    if run is None:
        if use_cache:
            run = _make_cached_run(module, max_new_tokens, temperature,
                                   pad_id, scan_len, prefill_len)
        else:
            run = _make_run(module, max_new_tokens, temperature, pad_id)
        with _CACHE_LOCK:
            _RUN_CACHE[key] = run
            while len(_RUN_CACHE) > _RUN_CACHE_MAX:
                _RUN_CACHE.popitem(last=False)
    return np.asarray(run(variables["params"], jnp.asarray(buf),
                          jnp.asarray(ptr), jax.random.PRNGKey(seed)))


class ContinuousGenerator:
    """Continuous batching for causal-LM decoding: a FIXED pool of
    sequence slots over a fixed ``[slots, max_len]`` token buffer, with
    new sequences admitted into free slots at **step boundaries**
    instead of waiting for the whole batch to drain.

    Why: classic dynamic batching (``generate`` behind a batcher) makes
    an arriving prompt wait for every in-flight generation to finish —
    up to ``max_new_tokens`` full steps of queueing. Here a sequence
    waits at most ONE decode step for a free slot. Slot bookkeeping and
    admission order live in ``sched.SlotScheduler`` (the same policy
    layer online serving uses — pure Python, device-free); this class
    is the device half: ONE jitted step program whose shapes never
    change (``[slots, max_len]``), so admission costs a buffer write,
    never a recompile.

    Decode math matches ``generate(use_cache=False)``: each step runs a
    full causal forward and samples from the logits at each row's
    ``ptr - 1`` (``_sample``, the shared epilogue). With
    ``temperature=0`` (greedy) per-sequence outputs are IDENTICAL to
    the non-continuous path — rows of a causal transformer are batch-
    independent — which is the equivalence contract the tests pin.
    With ``temperature > 0`` each token is still a sample from the
    model's distribution, but the sampled STREAM differs from
    ``generate``'s: keys fold in the global step index, and a sequence
    admitted mid-flight sees different step indices than one starting a
    fresh batch (same caveat as ``TextGenerator.draftLm``).

    Each step re-encodes the whole buffer (O(L²·W) per step, the
    ``use_cache=False`` reference path); slot-wise KV caches with
    per-slot prefill are the follow-up optimization and change nothing
    about the admission protocol.
    """

    def __init__(self, module, variables, *, slots: int = 4,
                 max_len: int = 64, temperature: float = 0.0,
                 pad_id: int = 0, seed: int = 0,
                 service: str = "generate", registry=None):
        from ..sched import SlotScheduler

        self.module = module
        self.variables = variables
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.temperature = float(temperature)
        self.pad_id = int(pad_id)
        self.sched = SlotScheduler(self.slots, service=service,
                                   registry=registry)
        self._buf = jnp.full((self.slots, self.max_len), self.pad_id,
                             jnp.int32)
        # free slots idle at ptr=1 (keeps the ptr-1 logit gather in
        # bounds); their sampled tokens are never written (write mask)
        self._ptr = jnp.ones((self.slots,), jnp.int32)
        self._active = np.zeros(self.slots, bool)
        self._key = jax.random.PRNGKey(seed)
        self._step_idx = 0
        self._probed = False
        self._run = self._make_step()

    def _make_step(self):
        module, temperature, pad_id = \
            self.module, self.temperature, self.pad_id
        S, L = self.slots, self.max_len

        @jax.jit
        def step(params, buf, ptr, active, key, i):
            logits = module.apply({"params": params}, buf)["logits"]
            last = jnp.take_along_axis(
                logits, (ptr - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]                            # [S, V]
            nxt = _sample(last, jax.random.fold_in(key, i), temperature,
                          pad_id)
            write = active & (ptr < L)
            at = jnp.minimum(ptr, L - 1)
            cur = buf[jnp.arange(S), at]
            buf = buf.at[jnp.arange(S), at].set(
                jnp.where(write, nxt, cur))
            return buf, ptr + write.astype(jnp.int32)

        return step

    # -- intake ------------------------------------------------------------
    def submit(self, seq_id, prompt_ids, max_new_tokens: int) -> None:
        """Queue one sequence. ``prompt_ids``: 1-D int32, no padding.
        Admitted at the next step boundary with a free slot."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if (prompt == self.pad_id).any():
            raise ValueError(f"prompt contains pad_id={self.pad_id}")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + {max_new_tokens} new tokens "
                f"exceeds max_len={self.max_len}")
        if not self._probed:
            # same causality gate as generate(): a bidirectional
            # encoder would silently condition on its own padding
            from .pretrain import assert_causal
            probe = prompt[None, :] if prompt.size >= 2 else \
                np.repeat(prompt[None, :], 2, axis=1)
            vocab = getattr(getattr(self.module, "encoder", None),
                            "vocab", int(probe.max()) + 2)
            assert_causal(self.module,
                          {"params": self.variables["params"]}, probe,
                          vocab)
            self._probed = True
        self.sched.offer(seq_id, prompt, int(max_new_tokens))

    # -- the boundary protocol ---------------------------------------------
    def step(self) -> list:
        """One step boundary: admit pending sequences into free slots,
        run one jitted decode step, account completions. Returns
        ``(seq_id, output_row)`` pairs finished by this step."""
        for a in self.sched.admit():
            row = np.full(self.max_len, self.pad_id, np.int32)
            row[:len(a.prompt)] = a.prompt
            self._buf = self._buf.at[a.slot].set(jnp.asarray(row))
            self._ptr = self._ptr.at[a.slot].set(len(a.prompt))
            self._active[a.slot] = True
        if not self._active.any():
            return []
        self._buf, self._ptr = self._run(
            self.variables["params"], self._buf, self._ptr,
            jnp.asarray(self._active), self._key, self._step_idx)
        self._step_idx += 1
        done = []
        for seq_id, slot in self.sched.step():
            self._active[slot] = False
            done.append((seq_id, np.asarray(self._buf[slot])))
        return done

    def run_until_drained(self) -> dict:
        """Step until every offered sequence completes; returns
        ``{seq_id: [max_len] int32 row}`` (prompt, then generated
        tokens, then pad)."""
        out = {}
        while self.sched.busy:
            for seq_id, row in self.step():
                out[seq_id] = row
        return out


class TextGenerator(Transformer, HasInputCol, HasOutputCol):
    """Pipeline stage: text prompts → generated continuations.

    Composes the whole decoder stack at the framework's core
    abstraction: a fitted ``BpeTokenizerModel`` encodes prompts to id
    rows, :func:`generate` decodes with the causal LM (KV-cached), and
    the tokenizer's ``decode`` renders continuations back to text. No
    reference counterpart (SURVEY §5: text/long-context is the
    framework's extension axis)."""

    # StageParam: fitted stages round-trip through their OWN save/load
    # (raw pickling would bake BpeTokenizerModel's internal caches and
    # attribute layout into the artifact)
    tokenizer = StageParam("tokenizer", "fitted BpeTokenizerModel")
    lm = ComplexParam("lm", "(module, variables): a causal MaskedLMModel "
                      "and its trained variables")
    maxNewTokens = Param("maxNewTokens", "tokens to generate per row",
                         TC.toInt, default=16, has_default=True)
    temperature = Param("temperature", "0 = greedy; > 0 = sampling",
                        TC.toFloat, default=0.0, has_default=True)
    seed = Param("seed", "sampling seed", TC.toInt, default=0,
                 has_default=True)
    draftLm = ComplexParam(
        "draftLm", "(module, variables) of a smaller same-vocab causal "
        "LM: when set, decoding runs SPECULATIVELY (dl.speculative — "
        "the draft proposes, the lm verifies k positions per pass). "
        "temperature=0: output identical to the non-draft stage. "
        "temperature>0: each token is still an EXACT sample from the "
        "lm's distribution (rejection-sampling acceptance, see "
        "dl.speculative), but the sampled STREAM differs from the "
        "non-draft stage run — length-grouping changes batch "
        "composition and per-row key schedules, so equality is "
        "distribution-exactness, not stream equality. Rows are "
        "grouped by prompt length (speculation needs dense "
        "equal-length rows), one compiled program per distinct "
        "length.",
        default=None, has_default=True)
    speculativeK = Param(
        "speculativeK", "draft tokens proposed per verify pass",
        TC.toInt, default=4, has_default=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="text", outputCol="generated")

    def _transform(self, df):
        tok = self.get("tokenizer")
        module, variables = self.get("lm")
        if len(df) == 0:  # nothing to decode (and generate() reduces
            return df.with_column(self.getOutputCol(),
                                  np.empty(0, object))
        ids = tok.transform(
            df.with_column(tok.getInputCol(),
                           df[self.getInputCol()]))[tok.getOutputCol()]
        ids = np.asarray(ids, np.int32)
        # generate() requires non-empty rows; give blank prompts UNK
        ptr = (ids != 0).sum(axis=1)
        ids[ptr == 0, 0] = 1
        ptr = np.maximum(ptr, 1)
        n_new = self.get("maxNewTokens")
        draft = self.get("draftLm")
        texts = np.empty(len(ids), object)
        if draft is not None:
            from .speculative import generate_speculative
            draft_module, draft_variables = draft
            # speculation needs dense equal-length rows: group ragged
            # prompts by length, one batched call per group
            for plen in np.unique(ptr):
                rows = np.flatnonzero(ptr == plen)
                out_g, _ = generate_speculative(
                    module, variables, draft_module, draft_variables,
                    ids[rows, :plen], max_new_tokens=n_new,
                    k=self.get("speculativeK"),
                    temperature=self.get("temperature"),
                    seed=self.get("seed"))
                for r, row in zip(rows, out_g):
                    texts[r] = tok.decode(row[plen:plen + n_new])
            return df.with_column(self.getOutputCol(), texts)
        out = generate(module, variables, ids, max_new_tokens=n_new,
                       temperature=self.get("temperature"),
                       seed=self.get("seed"))
        # each row's continuation starts at ITS prompt length (ragged
        # prompts generate before Tp), never contains pad
        texts[:] = [tok.decode(row[p:p + n_new])
                    for row, p in zip(out, ptr)]
        return df.with_column(self.getOutputCol(), texts)
