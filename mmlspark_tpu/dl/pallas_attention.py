"""Pallas TPU kernel: fused flash attention (forward).

The long-context encoder's hot op. The XLA formulation
(``text_encoder._dense_attention``) materializes the [T, T] score matrix
in HBM — at T=2048, B=32, H=8 that is 4 GB of f32 score traffic per
layer, and HBM bandwidth, not the MXU, bounds throughput. The TPU-native
formulation streams K/V blocks through VMEM with a running-softmax
accumulator (same math as ``parallel/ring_attention._block_update``), so
scores never leave the chip:

    grid = (B*H, T/block_q, T/block_k), k-blocks innermost
    per (q-block, k-block) cell:  s = q k^T on the MXU,
        online max/denominator update in VMEM scratch,
        acc += softmax-weights @ v on the MXU
    emit acc / l once per q-block on the last k step.

Backward runs the blockwise (XLA) formulation via recompute — inference
is the featurizer's hot path; training pays one extra forward.

Tiling: q/k/v blocks keep head_dim on the lane axis (pads to 128 lanes
below head_dim 128 — run heads at 64 or 128 wide for best effect), and
the running max/denominator ride a (block_q, 128) f32 scratch so their
updates stay VPU-shaped. Mask handling matches the dense path bit-wise:
fully-masked rows emit zeros.

No reference counterpart (SURVEY §5: long-context is "absent in the
reference") — this kernel serves the framework's first-class extension.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.compat import tpu_compiler_params as _CompilerParams

from ..utils.platform import target_platform  # noqa: F401 (re-export)

_NEG = -1e30  # additive mask value; -inf breaks the running-max algebra


def _allowed_2d(mask_ref, off_ref, shape, qb_idx, kb_idx, causal: bool):
    """[BQ, BK] validity: key mask (row-broadcast) ∧, when causal, the
    lower-triangular position constraint from GLOBAL positions —
    per-call offset (``off_ref`` [1, 2] = (q_off, k_off), traced: ring
    attention passes each step's shard offsets) + block index × block
    size + in-block iota on each axis."""
    # 2-D [1, BK] load — a 1-D vector load here crashes the Mosaic
    # layout pass ("arr.size() >= layout_rank")
    valid = mask_ref[0] != 0
    if not causal:
        return jnp.broadcast_to(valid, shape)
    qpos = off_ref[0, 0] + qb_idx * shape[0] + jax.lax.broadcasted_iota(
        jnp.int32, shape, 0)
    kpos = off_ref[0, 1] + kb_idx * shape[1] + jax.lax.broadcasted_iota(
        jnp.int32, shape, 1)
    return valid & (kpos <= qpos)


def _block_reachable(off_ref, bq: int, bk: int, qb_idx, kb_idx,
                     causal: bool):
    """False iff EVERY (q, k) pair in this grid cell is above the
    causal diagonal — such cells contribute exactly zero and their MXU
    work can be skipped (the ~2x causal saving). Dynamic predicate, so
    it composes with traced ring offsets."""
    if not causal:
        return True
    first_q = off_ref[0, 0] + qb_idx * bq        # smallest q position
    first_k = off_ref[0, 1] + kb_idx * bk        # smallest k position
    return first_k <= first_q + bq - 1


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, off_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale: float,
                  causal: bool = False):
    """One (bh, q-block, k-block) grid cell of the online softmax."""
    qb = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(_block_reachable(off_ref, q_ref.shape[1], k_ref.shape[1],
                              qb, kb, causal))
    def _compute():
        q = q_ref[0]                               # [BQ, D]
        k = k_ref[0]                               # [BK, D]
        s = jax.lax.dot_general(                   # [BQ, BK] f32 on MXU
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        allowed = _allowed_2d(mask_ref, off_ref, s.shape, qb, kb,
                              causal)
        s = jnp.where(allowed, s, _NEG)

        m_prev = m_scr[:, :1]                      # [BQ, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                     # [BQ, BK]
        # a fully-masked block: every s is _NEG and m_new is _NEG, so
        # p = exp(0) = 1 row-wide — kill it with the validity mask
        p = jnp.where(allowed, p, 0.0)
        corr = jnp.exp(m_prev - m_new)             # [BQ, 1]
        l_scr[:, :1] = l_prev * corr \
            + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[:, :1] = m_new
        # p rounds to the value dtype before the MXU pass — bit-matching
        # the dense path's ``p.astype(v.dtype)`` (text_encoder.py:48)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:, :1], 1e-35)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def _flash_kernel_lse(q_ref, k_ref, v_ref, mask_ref, off_ref, o_ref,
                      lse_ref, m_scr, l_scr, acc_scr, *, scale: float,
                      causal: bool = False):
    """Forward cell that additionally emits the logsumexp row stats the
    fused backward needs (same math as ``_flash_kernel``)."""
    _flash_kernel(q_ref, k_ref, v_ref, mask_ref, off_ref, o_ref,
                  m_scr, l_scr, acc_scr, scale=scale, causal=causal)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == nk - 1)
    def _emit_lse():
        l = jnp.maximum(l_scr[:, :1], 1e-35)
        lse_ref[0] = m_scr[:, :1] + jnp.log(l)


def _flash_kernel_causal_packed(q_ref, k_ref, v_ref, mask_ref, off_ref,
                                o_ref, *maybe_lse, scale: float,
                                bk: int, with_lse: bool):
    """Causal forward with REAL grid pruning: one grid cell per
    (bh, q-block), K/V resident whole-row in VMEM, and a
    ``fori_loop`` over ONLY the reachable k-blocks — above-diagonal
    blocks are never fetched, never launched, never masked. The
    streaming-grid kernel (``_flash_kernel``) skips their MXU work via
    ``pl.when`` but still runs their grid slots and block copies; this
    kernel removes the slots themselves (the true ~2x causal saving),
    at the cost of requiring K/V to fit VMEM — the fallback below keeps
    the streaming path for longer T (and the sharded ring/ulysses
    variants shrink per-device T long before that matters)."""
    lse_ref = maybe_lse[0] if with_lse else None
    qb = pl.program_id(1)
    bq = q_ref.shape[1]
    nk = k_ref.shape[1] // bk
    # reachable bound from GLOBAL positions (traced ring offsets ride
    # off_ref exactly as in the streaming kernel)
    last_q = off_ref[0, 0] + qb * bq + bq - 1
    n_reach = jnp.clip((last_q - off_ref[0, 1]) // bk + 1, 0, nk)

    q = q_ref[0]                                   # [BQ, D]

    def body(kb, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, pl.ds(kb * bk, bk), :]        # [BK, D]
        v = v_ref[0, pl.ds(kb * bk, bk), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        valid = mask_ref[0, :, pl.ds(kb * bk, bk)] != 0   # [1, BK]
        qpos = off_ref[0, 0] + qb * bq + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0)
        kpos = off_ref[0, 1] + kb * bk + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        allowed = valid & (kpos <= qpos)
        s = jnp.where(allowed, s, _NEG)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(allowed, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    D = q_ref.shape[2]
    m0 = jnp.full((bq, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_reach, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-35)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    if with_lse:
        lse_ref[0] = m + jnp.log(l_safe)


# K+V whole-row VMEM budget for the packed causal kernel; beyond this
# the streaming grid takes over (VMEM is ~16 MiB/core — leave room for
# q/o blocks, scratch, and double-buffering)
_PACKED_KV_BYTES = 4 * 1024 * 1024

# per-block K (and V) VMEM budget for the AUTO block_k choice below
_AUTO_BK_BYTES = 512 * 1024


def _resolve_block_k(block_k, k, causal: bool) -> int:
    """Default block_k. The k-block size IS the contraction dim of the
    p·V matmul, so on the MXU bigger is directly faster: a v5e sweep at
    T=2048/D=64 measured 554 encoder seqs/s at bk=2048 (single k-block,
    one-pass softmax) vs 368 at the old fixed 512 (+51%). Auto picks
    the whole padded row when a K block fits ``_AUTO_BK_BYTES``, else
    the largest 128-multiple that does. CAUSAL keeps 512: bk is the
    pruning granularity there, and coarse blocks forfeit the ~2x
    triangle saving (measured 1.57x at T=2048 with bk=512)."""
    if block_k is not None:
        return block_k
    if causal:
        return 512
    T, D = k.shape[2], k.shape[3]
    tk = -(-T // 128) * 128               # padded row length
    budget = _AUTO_BK_BYTES // max(D * k.dtype.itemsize, 1)
    # hard 2048 cap: the fused BACKWARD holds several [block_q, bk]
    # f32 intermediates (s/p/dp/ds) in VMEM — 2048 is measured to
    # compile and win on v5e; 4096 would put ~16 MB of score blocks in
    # a ~16 MB VMEM
    return max(min(tk, budget // 128 * 128, 2048), 512)


def _flash_pack(q, k, v, key_mask, block_q, block_k):
    """Shared padding/reshape for forward and backward kernels."""
    B, H, T, D = q.shape
    bq = min(block_q, max(8, T))
    bk = min(block_k, max(128, T))
    qp = (-T) % bq
    kp = (-T) % bk
    qf = jnp.pad(q.reshape(B * H, T, D), ((0, 0), (0, qp), (0, 0)))
    kf = jnp.pad(k.reshape(B * H, T, D), ((0, 0), (0, kp), (0, 0)))
    vf = jnp.pad(v.reshape(B * H, T, D), ((0, 0), (0, kp), (0, 0)))
    # [B, T] bool → [B*H, 1, Tk] i8, padded keys invalid. The unit
    # middle axis is load-bearing on TPU: Mosaic requires a block's
    # last-two dims to be (8k, 128k) or match the array, and a
    # per-(b,h) mask row can only block as (1, bk) if the sublane axis
    # is a real size-1 array dim.
    mask = jnp.broadcast_to(key_mask[:, None, :], (B, H, T)) \
        .reshape(B * H, T).astype(jnp.int8)
    mask = jnp.pad(mask, ((0, 0), (0, kp)))[:, None, :]
    return qf, kf, vf, mask, (B, H, T, D, bq, bk, qp, kp)


@functools.partial(jax.jit,
                   static_argnames=("block_q", "block_k", "interpret",
                                    "with_lse", "causal"))
def _flash_forward(q, k, v, key_mask, offs=None, *, block_q: int = 256,
                   block_k: int = 512, interpret: bool = False,
                   with_lse: bool = False, causal: bool = False):
    qf, kf, vf, mask, (B, H, T, D, bq, bk, qp, kp) = _flash_pack(
        q, k, v, key_mask, block_q, block_k)
    scale = D ** -0.5
    nq, nk = (T + qp) // bq, (T + kp) // bk
    if offs is None:
        offs = jnp.zeros((1, 2), jnp.int32)
    kv_bytes = 2 * (T + kp) * D * k.dtype.itemsize
    if causal and kv_bytes <= _PACKED_KV_BYTES:
        # pruned-grid causal path: grid cells exist only per q-block;
        # reachable k-blocks iterate INSIDE the cell, so above-diagonal
        # work is never launched at all
        packed_specs = [
            pl.BlockSpec((1, bq, D), lambda b, iq: (b, iq, 0)),
            pl.BlockSpec((1, T + kp, D), lambda b, iq: (b, 0, 0)),
            pl.BlockSpec((1, T + kp, D), lambda b, iq: (b, 0, 0)),
            pl.BlockSpec((1, 1, T + kp), lambda b, iq: (b, 0, 0)),
            pl.BlockSpec((1, 2), lambda b, iq: (0, 0)),
        ]
        o_spec = pl.BlockSpec((1, bq, D), lambda b, iq: (b, iq, 0))
        o_shape = jax.ShapeDtypeStruct((B * H, T + qp, D), v.dtype)
        params = _CompilerParams(
            dimension_semantics=("parallel", "parallel"))
        kern = functools.partial(_flash_kernel_causal_packed,
                                 scale=scale, bk=bk, with_lse=with_lse)
        if with_lse:
            out, lse = pl.pallas_call(
                kern, grid=(B * H, nq), in_specs=packed_specs,
                out_specs=(o_spec,
                           pl.BlockSpec((1, bq, 1),
                                        lambda b, iq: (b, iq, 0))),
                out_shape=(o_shape,
                           jax.ShapeDtypeStruct((B * H, T + qp, 1),
                                                jnp.float32)),
                compiler_params=params, interpret=interpret,
            )(qf, kf, vf, mask, offs)
            return (out[:, :T].reshape(B, H, T, D),
                    lse[:, :T, 0].reshape(B, H, T))
        out = pl.pallas_call(
            kern, grid=(B * H, nq), in_specs=packed_specs,
            out_specs=o_spec, out_shape=o_shape,
            compiler_params=params, interpret=interpret,
        )(qf, kf, vf, mask, offs)
        return out[:, :T].reshape(B, H, T, D)
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, iq, ik: (b, iq, 0)),
        pl.BlockSpec((1, bk, D), lambda b, iq, ik: (b, ik, 0)),
        pl.BlockSpec((1, bk, D), lambda b, iq, ik: (b, ik, 0)),
        pl.BlockSpec((1, 1, bk), lambda b, iq, ik: (b, 0, ik)),
        pl.BlockSpec((1, 2), lambda b, iq, ik: (0, 0)),
    ]
    o_spec = pl.BlockSpec((1, bq, D), lambda b, iq, ik: (b, iq, 0))
    o_shape = jax.ShapeDtypeStruct((B * H, T + qp, D), v.dtype)
    scratch = [
        pltpu.VMEM((bq, 128), jnp.float32),   # running max
        pltpu.VMEM((bq, 128), jnp.float32),   # running denominator
        pltpu.VMEM((bq, D), jnp.float32),     # output accumulator
    ]
    params = _CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))
    if with_lse:
        out, lse = pl.pallas_call(
            functools.partial(_flash_kernel_lse, scale=scale,
                              causal=causal),
            grid=(B * H, nq, nk),
            in_specs=in_specs,
            out_specs=(o_spec,
                       pl.BlockSpec((1, bq, 1),
                                    lambda b, iq, ik: (b, iq, 0))),
            out_shape=(o_shape,
                       jax.ShapeDtypeStruct((B * H, T + qp, 1),
                                            jnp.float32)),
            scratch_shapes=scratch,
            compiler_params=params,
            interpret=interpret,
        )(qf, kf, vf, mask, offs)
        return (out[:, :T].reshape(B, H, T, D),
                lse[:, :T, 0].reshape(B, H, T))
    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal),
        grid=(B * H, nq, nk),
        in_specs=in_specs,
        out_specs=o_spec,
        out_shape=o_shape,
        scratch_shapes=scratch,
        compiler_params=params,
        interpret=interpret,
    )(qf, kf, vf, mask, offs)
    return out[:, :T].reshape(B, H, T, D)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, mask_ref, off_ref, do_ref,
                   lse_ref, dsum_ref, dq_ref, dq_scr, *, scale: float,
                   causal: bool = False):
    """dq = Σ_k ds·K with ds = p·(dp − D)·scale, p = exp(s − lse)."""
    qb = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(_block_reachable(off_ref, q_ref.shape[1], k_ref.shape[1],
                              qb, kb, causal))
    def _compute():
        q = q_ref[0]                               # [BQ, D]
        k = k_ref[0]                               # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        allowed = _allowed_2d(mask_ref, off_ref, s.shape, qb, kb,
                              causal)
        p = jnp.exp(s - lse_ref[0])                # lse [BQ, 1] bcasts
        p = jnp.where(allowed, p, 0.0)
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(                  # [BQ, BK]
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - dsum_ref[0]) * scale        # dsum [BQ, 1]
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == nk - 1)
    def _emit():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, mask_ref, off_ref, q_ref, do_ref,
                    lse_ref, dsum_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale: float, causal: bool = False):
    """dv = Σ_q pᵀ·dO; dk = Σ_q dsᵀ·Q — accumulated over q blocks."""
    ikb = pl.program_id(1)
    qb = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qb == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(_block_reachable(off_ref, q_ref.shape[1], k_ref.shape[1],
                              qb, ikb, causal))
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [BQ, BK]
        # grid here is (bh, k-block, q-block): q index is program_id(2)
        allowed = _allowed_2d(mask_ref, off_ref, s.shape, qb, ikb,
                              causal)
        p = jnp.exp(s - lse_ref[0])
        p = jnp.where(allowed, p, 0.0)
        do = do_ref[0].astype(jnp.float32)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(  # pᵀ [BK,BQ] · dO
            p.astype(do_ref.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - dsum_ref[0]) * scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(  # dsᵀ [BK,BQ] · Q
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qb == nq - 1)
    def _emit():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("block_q", "block_k", "interpret",
                                    "causal"))
def _flash_backward(q, k, v, key_mask, o, lse, g, dlse=None,
                    offs=None, *, block_q: int = 256,
                    block_k: int = 512, interpret: bool = False,
                    causal: bool = False):
    """Fused FlashAttention-2-style backward: recompute p per block from
    the saved logsumexp, never materializing [T, T] in HBM.

    ``dlse``: cotangent of the logsumexp output (the lse-returning
    variant). ∂lse/∂s_j = p_j folds into the D-term: ds = p·(dp − (D −
    dlse))·scale."""
    qf, kf, vf, mask, (B, H, T, D, bq, bk, qp, kp) = _flash_pack(
        q, k, v, key_mask, block_q, block_k)
    scale = D ** -0.5
    gf = jnp.pad(g.reshape(B * H, T, D), ((0, 0), (0, qp), (0, 0)))
    # D_i = Σ_d dO·O per row; zero for padded rows since g pads with 0
    dsum = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                   axis=-1)                                 # [B, H, T]
    if dlse is not None:
        dsum = dsum - dlse.astype(jnp.float32)
    dsum = jnp.pad(dsum.reshape(B * H, T),
                   ((0, 0), (0, qp)))[..., None]            # [BH, Tq, 1]
    lse_f = jnp.pad(lse.reshape(B * H, T), ((0, 0), (0, qp)),
                    constant_values=0.0)[..., None]      # [BH, Tq, 1]
    nq, nk = (T + qp) // bq, (T + kp) // bk
    if offs is None:
        offs = jnp.zeros((1, 2), jnp.int32)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal),
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, bk, D), lambda b, iq, ik: (b, ik, 0)),
            pl.BlockSpec((1, bk, D), lambda b, iq, ik: (b, ik, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, iq, ik: (b, 0, ik)),
            pl.BlockSpec((1, 2), lambda b, iq, ik: (0, 0)),
            pl.BlockSpec((1, bq, D), lambda b, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, iq, ik: (b, iq, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, iq, ik: (b, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T + qp, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, mask, offs, gf, lse_f, dsum)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal),
        grid=(B * H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bk, D), lambda b, ik, iq: (b, ik, 0)),
            pl.BlockSpec((1, bk, D), lambda b, ik, iq: (b, ik, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, ik, iq: (b, 0, ik)),
            pl.BlockSpec((1, 2), lambda b, ik, iq: (0, 0)),
            pl.BlockSpec((1, bq, D), lambda b, ik, iq: (b, iq, 0)),
            pl.BlockSpec((1, bq, D), lambda b, ik, iq: (b, iq, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, ik, iq: (b, iq, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, ik, iq: (b, iq, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, bk, D), lambda b, ik, iq: (b, ik, 0)),
            pl.BlockSpec((1, bk, D), lambda b, ik, iq: (b, ik, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B * H, T + kp, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, T + kp, D), v.dtype),
        ),
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(kf, vf, mask, offs, qf, gf, lse_f, dsum)

    return (dq[:, :T].reshape(B, H, T, D),
            dk[:, :T].reshape(B, H, T, D),
            dv[:, :T].reshape(B, H, T, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, key_mask, offs, block_q, block_k, interpret,
           bwd_impl, causal):
    return _flash_forward(q, k, v, key_mask, offs, block_q=block_q,
                          block_k=block_k, interpret=interpret,
                          causal=causal)


def _flash_fwd(q, k, v, key_mask, offs, block_q, block_k, interpret,
               bwd_impl, causal):
    # forward-for-gradient also emits the logsumexp row stats, but only
    # when the fused backward will actually consume them — the blockwise
    # backward recomputes from q/k/v and would otherwise pin out+lse in
    # the residuals for nothing
    fused_bwd = bwd_impl == "pallas" or (bwd_impl == "auto"
                                         and not interpret)
    if fused_bwd:
        out, lse = _flash_forward(q, k, v, key_mask, offs,
                                  block_q=block_q, block_k=block_k,
                                  interpret=interpret, with_lse=True,
                                  causal=causal)
        return out, (q, k, v, key_mask, offs, out, lse)
    out = _flash_forward(q, k, v, key_mask, offs, block_q=block_q,
                         block_k=block_k, interpret=interpret,
                         causal=causal)
    return out, (q, k, v, key_mask, offs, None, None)


def _flash_bwd(block_q, block_k, interpret, bwd_impl, causal, res, g):
    q, k, v, key_mask, offs, out, lse = res
    if bwd_impl == "pallas" or (bwd_impl == "auto" and not interpret):
        # fused FA2-style backward: per-block p recomputed from the
        # saved logsumexp, [T, T] never touches HBM
        dq, dk, dv = _flash_backward(q, k, v, key_mask, out, lse, g,
                                     offs=offs, block_q=block_q,
                                     block_k=block_k,
                                     interpret=interpret, causal=causal)
        return dq, dk, dv, None, None
    # recompute-based backward through the XLA blockwise formulation:
    # same math, O(T) memory — with the causal mask's global-position
    # offsets threaded through (the ring path's shard coordinates)
    from ..parallel.ring_attention import blockwise_attention

    def ref(q, k, v):
        return blockwise_attention(q, k, v, block_size=block_k,
                                   key_mask=key_mask, causal=causal,
                                   q_offset=offs[0, 0],
                                   k_offset=offs[0, 1])

    _, vjp = jax.vjp(ref, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_lse(q, k, v, key_mask, offs, block_q, block_k, interpret,
               causal):
    return _flash_forward(q, k, v, key_mask, offs, block_q=block_q,
                          block_k=block_k, interpret=interpret,
                          with_lse=True, causal=causal)


def _flash_lse_fwd(q, k, v, key_mask, offs, block_q, block_k, interpret,
                   causal):
    out, lse = _flash_forward(q, k, v, key_mask, offs, block_q=block_q,
                              block_k=block_k, interpret=interpret,
                              with_lse=True, causal=causal)
    return (out, lse), (q, k, v, key_mask, offs, out, lse)


# test hook: force the fused backward through the interpreter so the
# dlse kernel math is exercised off-TPU (tiny shapes only — slow)
_FORCE_FUSED_LSE_BWD = False


def _flash_lse_bwd(block_q, block_k, interpret, causal, res, cots):
    g, dlse = cots
    q, k, v, key_mask, offs, out, lse = res
    if not interpret or _FORCE_FUSED_LSE_BWD:
        dq, dk, dv = _flash_backward(q, k, v, key_mask, out, lse, g,
                                     dlse=dlse, offs=offs,
                                     block_q=block_q, block_k=block_k,
                                     interpret=interpret, causal=causal)
        return dq, dk, dv, None, None
    # off-TPU: XLA recompute through the blockwise (o, lse) reference
    # with the causal offsets threaded through — the interpreted Pallas
    # backward would crawl (tests force it via _FORCE_FUSED_LSE_BWD)
    from ..parallel.ring_attention import blockwise_attention

    def ref(q, k, v):
        return blockwise_attention(q, k, v, block_size=block_k,
                                   key_mask=key_mask, causal=causal,
                                   q_offset=offs[0, 0],
                                   k_offset=offs[0, 1],
                                   return_lse=True)

    _, vjp = jax.vjp(ref, q, k, v)
    dq, dk, dv = vjp((g, dlse))
    return dq, dk, dv, None, None


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _pack_offs(q_offset, k_offset):
    return jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(k_offset, jnp.int32)]).reshape(1, 2)


def _tuned_blocks(T: int, D: int, causal: bool,
                  platform: str) -> tuple[int, int] | None:
    """Autotuned (block_q, block_k) for this (shape-bucket, platform)
    from the offline winner registry (``perf.autotune``, ISSUE 12), or
    None when untuned — the hand-picked defaults apply then, so an
    untuned shape behaves exactly as before. The lookup is a plain
    dict read: flash_attention runs at jit trace time inside jitted
    encoders, where locks/IO/clock are trace-safety hazards."""
    try:
        from ..perf import autotune
    except Exception:  # pragma: no cover - perf layer optional
        return None
    w = autotune.kernel_winner("flash_attention",
                               autotune.attn_key(T, D, causal), platform)
    if not w:
        return None
    try:
        return int(w["block_q"]), int(w["block_k"])
    except (KeyError, TypeError, ValueError):
        return None


def _resolve_blocks(q, k, block_q, block_k, causal: bool,
                    platform: str) -> tuple[int, int]:
    """Final (block_q, block_k): explicit caller values win; otherwise
    the autotuned winner for this shape bucket; otherwise the measured
    hand-picked defaults (256 / ``_resolve_block_k`` auto)."""
    tuned = None
    if block_q is None or block_k is None:
        tuned = _tuned_blocks(int(q.shape[2]), int(q.shape[3]),
                              bool(causal), platform)
    if block_q is None:
        block_q = tuned[0] if tuned else 256
    if block_k is None and tuned is not None:
        block_k = tuned[1]
    return int(block_q), _resolve_block_k(block_k, k, causal)


def flash_attention_lse(q, k, v, key_mask=None, *,
                        block_q: int | None = None,
                        block_k: int | None = None,
                        interpret: bool | None = None,
                        causal: bool = False, q_offset=0, k_offset=0):
    """Flash attention that also returns the per-row logsumexp of the
    scaled scores — the merge statistic ring attention needs to combine
    per-shard partial attentions. Returns ``(o [B,H,T,D], lse [B,H,T])``;
    fully-masked rows report lse ≈ -1e30 (their o is zero), which the
    standard lse-merge treats as an empty contribution. Differentiable
    in both outputs (fused Pallas backward).

    ``causal`` masks GLOBAL positions ``offset + index`` — the
    (possibly traced) ``q_offset``/``k_offset`` let sequence-sharded
    callers (the causal ring) express each shard's true coordinates.

    ``block_q``/``block_k`` default to the autotuned winner for this
    (shape-bucket, platform) when one is registered (``perf.autotune``),
    else the measured hand-picked tiles — explicit values always win."""
    plat = target_platform()
    if interpret is None:
        interpret = plat not in ("tpu", "axon")
    if key_mask is None:
        key_mask = jnp.ones((q.shape[0], q.shape[2]), bool)
    block_q, block_k = _resolve_blocks(q, k, block_q, block_k, causal,
                                       plat)
    return _flash_lse(q, k, v, key_mask, _pack_offs(q_offset, k_offset),
                      block_q, block_k, bool(interpret), bool(causal))


def flash_attention(q, k, v, key_mask=None, *,
                    block_q: int | None = None,
                    block_k: int | None = None,
                    interpret: bool | None = None,
                    bwd_impl: str = "auto", causal: bool = False,
                    q_offset=0, k_offset=0):
    """Fused flash attention. q/k/v [B, H, T, D]; ``key_mask`` [B, T]
    bool (True = valid). Off-TPU it runs the Pallas interpreter (slow —
    tests only); the XLA ``blockwise`` impl is the right CPU choice.

    ``bwd_impl``: "auto" uses the fused Pallas backward on TPU and the
    XLA blockwise recompute elsewhere; "pallas"/"blockwise" force one
    (tests force "pallas" under the interpreter).

    ``causal``: lower-triangular masking from GLOBAL positions
    (``offset + index``; offsets may be traced — sequence-sharded
    callers pass shard coordinates), fused into both forward and
    backward kernels. The forward PRUNES the grid outright when K/V
    fit the VMEM budget (one cell per q-block, an inner loop over only
    reachable k-blocks — above-diagonal work never launches); longer
    sequences and the backward fall back to the streaming grid with a
    ``pl.when`` reachability skip. The saving is the pruned-cell
    fraction and trades against k-block width (the non-causal path
    auto-sizes bk to the whole row; causal keeps bk=512 as its pruning
    granularity — v5e-measured best for it). Net: causal ≈ parity with
    the auto-bk full path at T=2048, 1.55x faster at T=8192
    (``bench.py`` flashcausal rows).

    ``block_q``/``block_k`` default to the autotuned winner for this
    (shape-bucket, platform) when one is registered (``perf.autotune``),
    else the measured hand-picked tiles — explicit values always win.
    """
    plat = target_platform()
    if interpret is None:
        interpret = plat not in ("tpu", "axon")
    if bwd_impl not in ("auto", "pallas", "blockwise"):
        raise ValueError(f"bwd_impl={bwd_impl!r} is not one of "
                         "auto|pallas|blockwise")
    if key_mask is None:
        key_mask = jnp.ones((q.shape[0], q.shape[2]), bool)
    block_q, block_k = _resolve_blocks(q, k, block_q, block_k, causal,
                                       plat)
    return _flash(q, k, v, key_mask, _pack_offs(q_offset, k_offset),
                  block_q, block_k, bool(interpret), bwd_impl,
                  bool(causal))
