"""Pallas TPU kernel: fused flash attention (forward).

The long-context encoder's hot op. The XLA formulation
(``text_encoder._dense_attention``) materializes the [T, T] score matrix
in HBM — at T=2048, B=32, H=8 that is 4 GB of f32 score traffic per
layer, and HBM bandwidth, not the MXU, bounds throughput. The TPU-native
formulation streams K/V blocks through VMEM with a running-softmax
accumulator (same math as ``parallel/ring_attention._block_update``), so
scores never leave the chip:

    grid = (B*H, T/block_q, T/block_k), k-blocks innermost
    per (q-block, k-block) cell:  s = q k^T on the MXU,
        online max/denominator update in VMEM scratch,
        acc += softmax-weights @ v on the MXU
    emit acc / l once per q-block on the last k step.

Backward runs the blockwise (XLA) formulation via recompute — inference
is the featurizer's hot path; training pays one extra forward.

Tiling: q/k/v blocks keep head_dim on the lane axis (pads to 128 lanes
below head_dim 128 — run heads at 64 or 128 wide for best effect), and
the running max/denominator ride a (block_q, 128) f32 scratch so their
updates stay VPU-shaped. Mask handling matches the dense path bit-wise:
fully-masked rows emit zeros.

No reference counterpart (SURVEY §5: long-context is "absent in the
reference") — this kernel serves the framework's first-class extension.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.platform import target_platform  # noqa: F401 (re-export)

_NEG = -1e30  # additive mask value; -inf breaks the running-max algebra


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale: float):
    """One (bh, q-block, k-block) grid cell of the online softmax."""
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0]                                   # [BQ, D]
    k = k_ref[0]                                   # [BK, D]
    s = jax.lax.dot_general(                       # [BQ, BK] f32 on MXU
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    valid = mask_ref[0, :] != 0                    # [BK]
    s = jnp.where(valid[None, :], s, _NEG)

    m_prev = m_scr[:, :1]                          # [BQ, 1]
    l_prev = l_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)                         # [BQ, BK]
    # a fully-masked block: every s is _NEG and m_new is _NEG, so
    # p = exp(0) = 1 row-wide — kill it with the validity mask
    p = jnp.where(valid[None, :], p, 0.0)
    corr = jnp.exp(m_prev - m_new)                 # [BQ, 1]
    l_scr[:, :1] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    m_scr[:, :1] = m_new
    # p rounds to the value dtype before the MXU pass — bit-matching the
    # dense path's ``p.astype(v.dtype)`` (text_encoder.py:48)
    acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(kb == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:, :1], 1e-35)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("block_q", "block_k", "interpret"))
def _flash_forward(q, k, v, key_mask, *, block_q: int = 256,
                   block_k: int = 512, interpret: bool = False):
    B, H, T, D = q.shape
    scale = D ** -0.5
    bq = min(block_q, max(8, T))
    bk = min(block_k, max(128, T))
    qp = (-T) % bq
    kp = (-T) % bk

    qf = jnp.pad(q.reshape(B * H, T, D), ((0, 0), (0, qp), (0, 0)))
    kf = jnp.pad(k.reshape(B * H, T, D), ((0, 0), (0, kp), (0, 0)))
    vf = jnp.pad(v.reshape(B * H, T, D), ((0, 0), (0, kp), (0, 0)))
    # [B, T] bool → [B*H, Tk] i8, padded keys invalid
    mask = jnp.broadcast_to(key_mask[:, None, :], (B, H, T)) \
        .reshape(B * H, T).astype(jnp.int8)
    mask = jnp.pad(mask, ((0, 0), (0, kp)))

    nq, nk = (T + qp) // bq, (T + kp) // bk
    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale),
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, bk, D), lambda b, iq, ik: (b, ik, 0)),
            pl.BlockSpec((1, bk, D), lambda b, iq, ik: (b, ik, 0)),
            pl.BlockSpec((1, bk), lambda b, iq, ik: (b, ik)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, iq, ik: (b, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T + qp, D), v.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max
            pltpu.VMEM((bq, 128), jnp.float32),   # running denominator
            pltpu.VMEM((bq, D), jnp.float32),     # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, mask)
    return out[:, :T].reshape(B, H, T, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, key_mask, block_q, block_k, interpret):
    return _flash_forward(q, k, v, key_mask, block_q=block_q,
                          block_k=block_k, interpret=interpret)


def _flash_fwd(q, k, v, key_mask, block_q, block_k, interpret):
    out = _flash(q, k, v, key_mask, block_q, block_k, interpret)
    return out, (q, k, v, key_mask)


def _flash_bwd(block_q, block_k, interpret, res, g):
    # recompute-based backward through the XLA blockwise formulation:
    # same math, O(T) memory, and jax.vjp handles the chain exactly
    from ..parallel.ring_attention import blockwise_attention
    q, k, v, key_mask = res

    def ref(q, k, v):
        return blockwise_attention(q, k, v, block_size=block_k,
                                   key_mask=key_mask)

    _, vjp = jax.vjp(ref, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, key_mask=None, *, block_q: int = 256,
                    block_k: int = 512, interpret: bool | None = None):
    """Fused flash attention. q/k/v [B, H, T, D]; ``key_mask`` [B, T]
    bool (True = valid). Off-TPU it runs the Pallas interpreter (slow —
    tests only); the XLA ``blockwise`` impl is the right CPU choice.
    """
    if interpret is None:
        interpret = target_platform() not in ("tpu", "axon")
    if key_mask is None:
        key_mask = jnp.ones((q.shape[0], q.shape[2]), bool)
    return _flash(q, k, v, key_mask, block_q, block_k, bool(interpret))
