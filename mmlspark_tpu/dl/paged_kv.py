"""Paged (block) KV cache for LLM serving: host block table + device pools.

A dense per-sequence KV cache sizes every sequence at ``max_len`` —
HBM pays for the worst case while the mean sequence uses a fraction of
it, and two requests sharing a long system prompt pay for it twice.
The paged layout (vLLM's PagedAttention; the TPU serving comparison in
arXiv:2605.25645 attributes most of its throughput win to it) instead
carves the cache into fixed ``[num_blocks, block_len, heads, head_dim]``
pools and gives each sequence a CHAIN of block indices: memory is
allocated in ``block_len``-token quanta as decoding advances, and a
block holding a popular prompt prefix is SHARED copy-free between
sequences via refcounts.

Two halves, same split as continuous batching
(``sched.SlotScheduler`` / ``dl.ContinuousGenerator``):

- **Host half (this module's** :class:`PagedKVManager` **— pure Python,
  no JAX)**: the block table. Free-list allocation, per-sequence chains,
  refcounted prefix reuse keyed by a rolling prompt-prefix hash (one
  hash per full ``block_len`` chunk, chained so a block's key commits to
  everything before it), LRU eviction of retired-but-cached blocks, and
  a block budget derived from the live HBM headroom (``obs.memory``).
  Importable and testable with no device — the serving control plane
  runs it from handler threads (CI style smoke asserts no jax).
- **Device half (lazy jax imports)**: pool init plus the gather/scatter
  bridges the prefill/decode executors (``serving.llm``) jit around the
  existing ``MaskedLMModel.prefill/decode_step/decode_window`` numerics
  — the paged path reuses the exact attention math ``dl.generate`` is
  equivalence-tested against, so paged decode stays greedy-identical.

Block 0 is RESERVED as the trash block: padded batch rows and inactive
slots point their block-table entries at it, so fixed-shape device
programs can always write "somewhere" without corrupting a live
sequence (gathers from it are masked by sequence length).

Obs families (federated fleet-wide, recorded by the history plane):
``kv_blocks_used`` / ``kv_blocks_free`` / ``kv_blocks_cached`` gauges,
``kv_prefix_hits_total`` / ``kv_prefix_misses_total`` /
``kv_prefix_tokens_reused_total`` / ``kv_evictions_total`` counters.
"""

from __future__ import annotations

import hashlib
import os
from collections import OrderedDict, deque
from dataclasses import dataclass, field

import numpy as np

from ..obs import registry as _default_registry

__all__ = ["PagedKVManager", "SequenceHandle", "OutOfBlocks",
           "blocks_for_hbm_budget", "init_pools", "gather_dense",
           "paged_attention_enabled", "scatter_positions",
           "take_positions"]

#: the reserved trash block — device programs route padded/inactive
#: writes here; the host half never hands it to a sequence
TRASH_BLOCK = 0


def paged_attention_enabled() -> bool:
    """Kill switch for the paged-attention decode kernel
    (``dl.pallas_paged_attention``): ``MMLSPARK_TPU_PAGED_ATTN=0``
    routes the serving executors back through the dense
    ``gather_dense`` round-trip (same escape-hatch pattern as
    ``MMLSPARK_TPU_COSTMODEL=0``). The fallback is loud:
    ``kv_dense_gather_bytes_total`` counts every byte it re-gathers,
    and reads 0 when the kernel path is live. JAX-free on purpose —
    the bookkeeping half stays importable without a backend."""
    return os.environ.get("MMLSPARK_TPU_PAGED_ATTN", "1") != "0"


class OutOfBlocks(RuntimeError):
    """The pool cannot serve an allocation: every non-reserved block is
    referenced by a live sequence (nothing evictable). Callers queue the
    sequence and retry at a later step boundary — admission control,
    not a crash."""


@dataclass
class SequenceHandle:
    """One sequence's view of the pool: the block chain and how many
    token positions are filled. ``prompt_len`` rides along so executors
    can split prefill cost from decode cost without a side channel."""
    seq_id: object
    chain: list[int]
    length: int
    prompt_len: int
    reused_tokens: int = 0
    # hashes for the full prompt chunks this sequence must publish into
    # the prefix index once prefill has actually filled them
    pending_publish: list[tuple[str, int]] = field(default_factory=list)

    def to_state(self) -> dict:
        """JSON-able handoff payload (the mesh ``__lease__`` envelope
        carries dicts): everything the decode side needs to adopt the
        sequence."""
        return {"seq_id": self.seq_id, "chain": list(self.chain),
                "length": int(self.length),
                "prompt_len": int(self.prompt_len),
                "reused_tokens": int(self.reused_tokens)}

    @classmethod
    def from_state(cls, state: dict) -> "SequenceHandle":
        return cls(seq_id=state["seq_id"],
                   chain=[int(b) for b in state["chain"]],
                   length=int(state["length"]),
                   prompt_len=int(state["prompt_len"]),
                   reused_tokens=int(state.get("reused_tokens", 0)))


def _chunk_hash(prev: str, tokens) -> str:
    """Rolling hash for one full ``block_len`` chunk: commits to the
    previous chunk's hash, so equal blocks match only on equal whole
    prefixes (prefix reuse must never splice a block into a different
    history)."""
    h = hashlib.blake2b(prev.encode(), digest_size=16)
    h.update(b"|")
    h.update(",".join(str(int(t)) for t in tokens).encode())
    return h.hexdigest()


def blocks_for_hbm_budget(block_bytes: int, *, fraction: float = 0.5,
                          default: int = 0) -> int:
    """How many KV blocks fit in ``fraction`` of the CURRENT free HBM
    (``obs.memory.device_memory_stats``; limit − in_use of the first
    local device). Returns ``default`` when no backend/allocator stats
    exist (CPU, host-only process) — the no-JAX half must size pools
    without a device."""
    from ..obs.memory import device_memory_stats
    stats = device_memory_stats()
    if not stats or block_bytes <= 0:
        return int(default)
    s = stats[0]
    limit = s.get("bytes_limit")
    in_use = s.get("bytes_in_use")
    if not limit:
        return int(default)
    free = max(int(limit) - int(in_use or 0), 0)
    return max(int(free * float(fraction)) // int(block_bytes), 0)


class PagedKVManager:
    """Host-side block table: pure-Python bookkeeping, no JAX.

    ``num_blocks`` counts the WHOLE pool including the reserved trash
    block 0; ``block_budget`` (optional, defaults to every allocatable
    block) caps how many blocks may be used+cached at once — set it
    from :func:`blocks_for_hbm_budget` to keep the KV pools under the
    live HBM headroom, or lower it at runtime via
    :meth:`set_block_budget` (cached blocks are LRU-evicted to fit).

    Lifecycle per sequence::

        h = mgr.allocate(seq_id, prompt_tokens)   # prefix reuse happens here
        mgr.publish(seq_id)                       # after prefill fills blocks
        mgr.ensure_capacity(seq_id, n)            # before writes past capacity
        mgr.advance(seq_id, k)                    # after k tokens committed
        mgr.release(seq_id)                       # blocks cached for reuse

    A released sequence's published prompt blocks stay in the prefix
    index (refcount 0, LRU-ordered) until eviction recycles them — the
    "cache" in KV cache hit rate.
    """

    def __init__(self, num_blocks: int, block_len: int, *,
                 block_budget: int | None = None, service: str = "llm",
                 registry=None):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is the "
                             "reserved trash block)")
        if block_len < 1:
            raise ValueError("block_len must be >= 1")
        reg = registry if registry is not None else _default_registry
        self.num_blocks = int(num_blocks)
        self.block_len = int(block_len)
        self.service = service
        self._free: deque[int] = deque(range(1, self.num_blocks))
        self._ref: dict[int, int] = {}
        self._seqs: dict[object, SequenceHandle] = {}
        # published full prompt chunks: hash -> block, block -> hash
        self._prefix_index: dict[str, int] = {}
        self._block_hash: dict[int, str] = {}
        # zero-ref published blocks, least-recently-retired first
        self._lru: OrderedDict[int, str] = OrderedDict()
        self._budget = int(block_budget) if block_budget else \
            self.num_blocks - 1
        self._budget = max(min(self._budget, self.num_blocks - 1), 1)
        self._g_used = reg.gauge(
            "kv_blocks_used",
            "KV blocks referenced by live sequences, by service")
        self._g_free = reg.gauge(
            "kv_blocks_free",
            "KV blocks on the free list (never-written or recycled), "
            "by service")
        self._g_cached = reg.gauge(
            "kv_blocks_cached",
            "retired zero-ref KV blocks still indexed for prefix "
            "reuse, by service")
        self._c_hits = reg.counter(
            "kv_prefix_hits_total",
            "prompt-prefix blocks served copy-free from the index, "
            "by service")
        self._c_misses = reg.counter(
            "kv_prefix_misses_total",
            "full prompt chunks that found no indexed block, by service")
        self._c_reused = reg.counter(
            "kv_prefix_tokens_reused_total",
            "prompt tokens whose prefill was skipped via prefix reuse, "
            "by service")
        self._c_evict = reg.counter(
            "kv_evictions_total",
            "cached KV blocks recycled under pool/HBM pressure, "
            "by service")
        self._publish_gauges()

    # -- internals ---------------------------------------------------------
    def _publish_gauges(self) -> None:
        self._g_used.set(len(self._ref), service=self.service)
        self._g_free.set(len(self._free), service=self.service)
        self._g_cached.set(len(self._lru), service=self.service)

    def _in_budget(self) -> bool:
        return len(self._ref) + len(self._lru) < self._budget

    def _evict_one(self) -> int | None:
        """Recycle the least-recently-retired cached block onto the
        free list; None when nothing is evictable."""
        if not self._lru:
            return None
        block, h = self._lru.popitem(last=False)
        self._prefix_index.pop(h, None)
        self._block_hash.pop(block, None)
        self._free.append(block)
        self._c_evict.inc(1, service=self.service)
        return block

    def _take_block(self) -> int:
        # budget first: even with free blocks in hand, used+cached must
        # stay under the HBM-derived cap, so pressure evicts the cache
        # before it grows the working set
        while not self._in_budget():
            if self._evict_one() is None:
                raise OutOfBlocks(
                    f"block budget {self._budget} exhausted by live "
                    f"sequences ({len(self._ref)} blocks referenced)")
        if not self._free and self._evict_one() is None:
            raise OutOfBlocks(
                f"all {self.num_blocks - 1} blocks referenced by live "
                "sequences — queue the request and retry at the next "
                "step boundary")
        return self._free.popleft()

    # -- intake ------------------------------------------------------------
    def allocate(self, seq_id, prompt_tokens) -> SequenceHandle:
        """Build ``seq_id``'s chain for ``prompt_tokens``: reuse indexed
        blocks for the longest matching whole-chunk prefix (refcount++,
        copy-free), allocate fresh blocks for the rest. The handle's
        ``reused_tokens`` tells the prefill executor where to start —
        the TTFT win is exactly the prefill it skips."""
        if seq_id in self._seqs:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        prompt = [int(t) for t in np.asarray(prompt_tokens).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        bl = self.block_len
        full_chunks = len(prompt) // bl
        chain: list[int] = []
        pending: list[tuple[str, int]] = []
        reused = 0
        h = ""
        matching = True
        try:
            for c in range(full_chunks):
                h = _chunk_hash(h, prompt[c * bl:(c + 1) * bl])
                block = self._prefix_index.get(h) if matching else None
                if block is not None:
                    self._c_hits.inc(1, service=self.service)
                    self._ref[block] = self._ref.get(block, 0) + 1
                    if block in self._lru:       # revived from cache
                        del self._lru[block]
                    chain.append(block)
                    reused += bl
                    continue
                if matching:
                    matching = False
                self._c_misses.inc(1, service=self.service)
                block = self._take_block()
                self._ref[block] = 1
                chain.append(block)
                pending.append((h, block))
            # tail block for the partial prompt chunk; decode growth is
            # on-demand via ensure_capacity
            if len(prompt) % bl:
                block = self._take_block()
                self._ref[block] = 1
                chain.append(block)
        except OutOfBlocks:
            # unwind: a half-allocated chain must not leak references
            for b in chain:
                self._unref(b)
            self._publish_gauges()
            raise
        if reused:
            self._c_reused.inc(reused, service=self.service)
        handle = SequenceHandle(seq_id=seq_id, chain=chain,
                                length=reused, prompt_len=len(prompt),
                                reused_tokens=reused,
                                pending_publish=pending)
        self._seqs[seq_id] = handle
        self._publish_gauges()
        return handle

    def publish(self, seq_id) -> int:
        """Index ``seq_id``'s freshly prefilled full prompt chunks for
        future prefix reuse. Call AFTER the prefill executor has written
        the blocks — publishing earlier would let a concurrent allocate
        share a block whose kv is still zeros. Returns chunks published."""
        handle = self._seqs[seq_id]
        n = 0
        for h, block in handle.pending_publish:
            # first writer wins: two identical prompts racing through
            # prefill both hold private blocks; only one gets indexed
            if h not in self._prefix_index and block in self._ref:
                self._prefix_index[h] = block
                self._block_hash[block] = h
                n += 1
        handle.pending_publish = []
        return n

    # -- growth / accounting -----------------------------------------------
    def capacity(self, seq_id) -> int:
        return len(self._seqs[seq_id].chain) * self.block_len

    def length(self, seq_id) -> int:
        return self._seqs[seq_id].length

    def handle(self, seq_id) -> SequenceHandle:
        return self._seqs[seq_id]

    def ensure_capacity(self, seq_id, tokens: int) -> SequenceHandle:
        """Grow ``seq_id``'s chain until it can hold ``tokens`` positions
        (speculative decode writes up to k+1 ahead each step)."""
        handle = self._seqs[seq_id]
        while len(handle.chain) * self.block_len < tokens:
            block = self._take_block()
            self._ref[block] = 1
            handle.chain.append(block)
        self._publish_gauges()
        return handle

    def advance(self, seq_id, n: int = 1) -> int:
        """Account ``n`` committed token positions; returns the new
        length. Positions must already be within capacity."""
        handle = self._seqs[seq_id]
        new_len = handle.length + int(n)
        if new_len > len(handle.chain) * self.block_len:
            raise ValueError(
                f"sequence {seq_id!r} advanced past capacity "
                f"({new_len} > {len(handle.chain)} blocks × "
                f"{self.block_len})")
        handle.length = new_len
        return handle.length

    # -- retirement --------------------------------------------------------
    def _unref(self, block: int) -> None:
        refs = self._ref.get(block, 0) - 1
        if refs > 0:
            self._ref[block] = refs
            return
        self._ref.pop(block, None)
        h = self._block_hash.get(block)
        if h is not None and self._prefix_index.get(h) == block:
            self._lru[block] = h        # retire into the reuse cache
            self._lru.move_to_end(block)
        else:
            self._block_hash.pop(block, None)
            self._free.append(block)

    def release(self, seq_id) -> None:
        """Drop the sequence: published blocks retire into the LRU reuse
        cache, everything else returns to the free list."""
        handle = self._seqs.pop(seq_id)
        for block in handle.chain:
            self._unref(block)
        self._publish_gauges()

    # -- handoff (prefill -> decode over the mesh lease plumbing) ----------
    def export_seq(self, seq_id) -> dict:
        """Detach the sequence for handoff: ownership of its block
        references moves WITH the returned payload (the manager keeps
        the refcounts; the seq is simply no longer addressable here
        until :meth:`adopt` re-registers it). Round-trips through JSON
        — the shape the mesh ``__lease__`` envelope carries."""
        handle = self._seqs.pop(seq_id)
        if handle.pending_publish:
            raise ValueError(
                f"sequence {seq_id!r} still has unpublished prefill "
                "blocks — publish() before handoff")
        self._publish_gauges()
        return handle.to_state()

    def adopt(self, state: dict) -> SequenceHandle:
        """Re-register an exported sequence (same pool — prefill and
        decode executors share the device pools on a host; cross-host
        adoption additionally ships the block contents)."""
        handle = SequenceHandle.from_state(state)
        if handle.seq_id in self._seqs:
            raise ValueError(f"sequence {handle.seq_id!r} already "
                             "registered")
        for block in handle.chain:
            if block not in self._ref:
                raise ValueError(
                    f"handoff chain references unowned block {block} — "
                    "the payload does not match this pool")
        self._seqs[handle.seq_id] = handle
        self._publish_gauges()
        return handle

    # -- device bridge -----------------------------------------------------
    def block_rows(self, seq_ids, max_blocks: int) -> np.ndarray:
        """``[len(seq_ids), max_blocks]`` int32 block table for the
        fixed-shape device step: each row is the sequence's chain padded
        with the trash block. ``None`` entries (empty slots) become
        all-trash rows."""
        rows = np.full((len(seq_ids), int(max_blocks)), TRASH_BLOCK,
                       np.int32)
        for i, sid in enumerate(seq_ids):
            if sid is None:
                continue
            chain = self._seqs[sid].chain
            if len(chain) > max_blocks:
                raise ValueError(
                    f"sequence {sid!r} has {len(chain)} blocks > "
                    f"max_blocks={max_blocks}")
            rows[i, :len(chain)] = chain
        return rows

    # -- budget / introspection --------------------------------------------
    def set_block_budget(self, budget: int) -> int:
        """Lower (or raise) the used+cached cap; cached blocks are
        LRU-evicted immediately to fit. Returns blocks evicted — the
        fleet health plane calls this when ``mem_hbm_*`` pressure
        crosses its watermark.

        Eviction here aligns with :meth:`_take_block`'s strict
        ``used + cached < budget`` pre-allocation invariant: a shrink
        pays its whole eviction debt now (counted
        ``kv_evictions_total``), so the next ``allocate`` never evicts
        on the lowered budget's behalf. Stopping at ``== budget`` — the
        old behaviour — left exactly one cached block to be reclaimed
        lazily at the next allocation."""
        self._budget = max(min(int(budget), self.num_blocks - 1), 1)
        evicted = 0
        while len(self._ref) + len(self._lru) >= self._budget:
            if self._evict_one() is None:
                break
            evicted += 1
        self._publish_gauges()
        return evicted

    @property
    def block_budget(self) -> int:
        return self._budget

    def stats(self) -> dict:
        """One-glance pool state (the bench banks hit rate from the
        registry; this is the debugging view)."""
        return {
            "blocks": self.num_blocks,
            "block_len": self.block_len,
            "budget": self._budget,
            "used": len(self._ref),
            "free": len(self._free),
            "cached": len(self._lru),
            "sequences": len(self._seqs),
            "indexed_prefixes": len(self._prefix_index),
        }


# --------------------------------------------------------------- device half
# Everything below imports jax lazily: the bookkeeping half above must
# stay importable (and CI-smoked) with no backend in the process.

def init_pools(encoder, num_blocks: int, block_len: int):
    """Per-layer ``([num_blocks, block_len, heads, head_dim]`` k, same v)
    device pools for ``encoder`` (a ``TextEncoder``)."""
    import jax.numpy as jnp
    hd = encoder.width // encoder.heads
    shape = (int(num_blocks), int(block_len), encoder.heads, hd)
    return tuple(
        (jnp.zeros(shape, encoder.dtype), jnp.zeros(shape, encoder.dtype))
        for _ in range(encoder.depth))


def _flat_positions(rows, pos, block_len: int):
    """[S, w] absolute positions -> flat pool indices via the block
    table: ``rows[s, p // bl] * bl + p % bl``. Out-of-chain positions
    clamp into the trash block's row (rows pads with TRASH_BLOCK)."""
    import jax.numpy as jnp
    bi = jnp.clip(pos // block_len, 0, rows.shape[1] - 1)   # [S, w]
    block = jnp.take_along_axis(rows, bi, axis=1)           # [S, w]
    return block * block_len + pos % block_len


def gather_dense(pools, rows):
    """Gather each slot's chained blocks into dense per-layer caches
    ``[S, heads, max_blocks*block_len, head_dim]`` — the exact cache
    layout ``MaskedLMModel.decode_step/decode_window`` run over, so the
    paged path reuses their (equivalence-tested) attention math
    unchanged. Positions ≥ the slot's length hold stale/trash data; the
    decode mask (``arange < pos``) never attends them.

    DEPRECATION SEAM: the serving executors no longer call this per
    step — ``dl.pallas_paged_attention`` reads the pools in place. It
    stays callable behind ``MMLSPARK_TPU_PAGED_ATTN=0``
    (:func:`paged_attention_enabled`), where every re-gathered byte is
    counted in ``kv_dense_gather_bytes_total``."""
    import jax.numpy as jnp
    S, MB = rows.shape
    out = []
    for k_pool, v_pool in pools:
        NB, BL, H, hd = k_pool.shape
        flat_k = k_pool.reshape(NB * BL, H, hd)
        flat_v = v_pool.reshape(NB * BL, H, hd)
        idx = (rows[:, :, None] * BL
               + jnp.arange(BL)[None, None, :]).reshape(S, MB * BL)
        k = jnp.transpose(flat_k[idx], (0, 2, 1, 3))   # [S, H, L, hd]
        v = jnp.transpose(flat_v[idx], (0, 2, 1, 3))
        out.append((k, v))
    return tuple(out)


def take_positions(dense, pos):
    """Extract the kv written at absolute positions ``pos`` ([S, w])
    from dense caches ``[S, H, L, hd]`` -> per-layer ``[S, w, H, hd]``
    (the delta the device step scatters back into the pools).

    DEPRECATION SEAM: only the ``MMLSPARK_TPU_PAGED_ATTN=0`` fallback
    executors still round-trip through this — the paged-attention path
    computes layer kv directly and scatters once."""
    import jax.numpy as jnp
    out = []
    for k, v in dense:
        idx = pos[:, None, :, None]                     # [S, 1, w, 1]
        kw = jnp.take_along_axis(
            k, jnp.broadcast_to(idx, (k.shape[0], k.shape[1],
                                      pos.shape[1], k.shape[3])), axis=2)
        vw = jnp.take_along_axis(
            v, jnp.broadcast_to(idx, (v.shape[0], v.shape[1],
                                      pos.shape[1], v.shape[3])), axis=2)
        out.append((jnp.transpose(kw, (0, 2, 1, 3)),
                    jnp.transpose(vw, (0, 2, 1, 3))))   # [S, w, H, hd]
    return tuple(out)


def scatter_positions(pools, rows, pos, new_kv, valid=None):
    """Write per-layer ``[S, w, H, hd]`` kv into the pools at absolute
    positions ``pos`` ([S, w]) through the block table. Positions with
    ``valid`` ([S, w] bool) false — padded prefill rows, inactive decode
    slots — are redirected into the trash block's first row, so every
    program instance writes a fixed index set (shape-stable) without
    ever touching a live chain. Live chains are disjoint, so the
    scatter has no real-block collisions and stays deterministic."""
    import jax.numpy as jnp
    out = []
    for (k_pool, v_pool), (kw, vw) in zip(pools, new_kv):
        NB, BL, H, hd = k_pool.shape
        fidx = _flat_positions(rows, pos, BL)           # [S, w]
        if valid is not None:
            fidx = jnp.where(valid, fidx, TRASH_BLOCK * BL)
        flat_k = k_pool.reshape(NB * BL, H, hd).at[fidx].set(kw)
        flat_v = v_pool.reshape(NB * BL, H, hd).at[fidx].set(vw)
        out.append((flat_k.reshape(NB, BL, H, hd),
                    flat_v.reshape(NB, BL, H, hd)))
    return tuple(out)
