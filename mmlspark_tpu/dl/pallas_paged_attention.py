"""Pallas TPU kernel: paged decode attention over the block table.

The LLM serving engine's hot op. The PR 15 executors materialize each
slot's whole KV history with ``paged_kv.gather_dense`` before every
decode step — O(context) HBM traffic per generated token and a second
resident copy of the KV working set, exactly the bandwidth the paged
pool exists to save. This kernel reads the fixed per-layer pools
``[num_blocks, block_len, heads, head_dim]`` IN PLACE:

    grid = (slots/slots_tile, slots_tile, max_blocks), blocks innermost
    the int32 block table rides scalar prefetch (SMEM), so each grid
    cell's BlockSpec index_map streams pool block ``rows[s, j]``
    straight HBM→VMEM — the gather IS the block fetch, no dense copy
    per (slot, chain-position) cell: per-head q·kᵀ on the MXU,
        online max/denominator update in VMEM scratch (flash style),
        acc += softmax-weights @ v
    emit acc / l once per slot on the last chain block.

Masking: table rows pad with ``TRASH_BLOCK`` — those cells are skipped
outright (``pl.when``), and in-block key positions mask against each
query row's global position (``t <= pos + i``), which also covers
positions ≥ the slot's length inside the tail block. A windowed variant
(q = k+1 rows per slot) serves speculative verify with the same kernel.

Off-TPU the SAME call runs a pure-``lax`` reference (``jnp.take`` over
the table inside the jit — no pool-level dense gather round-trip, no
writeback) whose formulation matches ``EncoderBlock.decode_window`` /
``_dense_attention`` bit-for-bit, so CPU tier-1 asserts byte-identical
greedy serving through identical program logic. The platform switch is
the same one ``pallas_attention.flash_attention`` uses.

Tile tuning: ``block_kv`` (key positions per inner VMEM chunk — the
score-block width, same VMEM discipline as ``_resolve_block_k``) and
``slots_tile`` (slots packed per parallel grid row — launch geometry
for tiny per-slot decode work) default to the ``perf.autotune`` winner
for this (context-bucket, platform) when one is registered, keyed
``kernel="paged_attn"``; explicit values always win, and every config
computes identical results (tuning moves time, never tokens).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.compat import tpu_compiler_params as _CompilerParams
from ..utils.platform import target_platform
from .paged_kv import TRASH_BLOCK, paged_attention_enabled  # noqa: F401

_NEG = -1e30  # additive mask value; -inf breaks the running-max algebra


# --------------------------------------------------------------- lax path
@jax.jit
def _paged_reference(q, k_pool, v_pool, rows, pos):
    """Pure-lax paged attention: ``jnp.take`` each slot's chained
    blocks THROUGH the table inside the jit (fused by XLA — no
    materialized dense cache crossing a program boundary, no
    writeback), then the exact ``decode_window`` score formulation:
    f32 einsum × hd^-0.5, -inf outside ``t <= pos + i``, softmax,
    NaN→0 for fully-masked rows, ``p.astype(v.dtype)`` before the
    value einsum. Bit-identical to the dense-cache decode math — the
    byte-identity contract with ``dl.generate`` rides on it."""
    S, H, w, hd = q.shape
    NB, BL = k_pool.shape[0], k_pool.shape[1]
    MB = rows.shape[1]
    L = MB * BL
    idx = (rows[:, :, None] * BL
           + jnp.arange(BL)[None, None, :]).reshape(S, L)
    k = jnp.take(k_pool.reshape(NB * BL, H, hd), idx, axis=0)
    v = jnp.take(v_pool.reshape(NB * BL, H, hd), idx, axis=0)
    k = jnp.transpose(k, (0, 2, 1, 3))                  # [S, H, L, hd]
    v = jnp.transpose(v, (0, 2, 1, 3))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * (hd ** -0.5)
    allowed = (jnp.arange(L)[None, None, :]
               <= (pos[:, None] + jnp.arange(w)[None, :])[:, :, None])
    s = jnp.where(allowed[:, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isnan(p), 0.0, p)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ------------------------------------------------------------ pallas path
def _paged_kernel(rows_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale: float, heads: int,
                  w: int, block_len: int, block_kv: int,
                  slots_tile: int):
    """One (slot-group, slot, chain-block) grid cell. The k/v refs
    already hold pool block ``rows[s, j]`` — the scalar-prefetched
    table drove the fetch; this body only ever sees one slot's own
    chain (or the trash block, which it skips)."""
    g = pl.program_id(0)
    u = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    s_idx = g * slots_tile + u

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    block_id = rows_ref[s_idx, j]
    pos = pos_ref[s_idx, 0]

    @pl.when(block_id != TRASH_BLOCK)
    def _compute():
        q = q_ref[0]                       # [heads*w, hd]
        k = k_ref[0]                       # [block_len, H, hd]
        v = v_ref[0]
        for c in range(-(-block_len // block_kv)):
            lo = c * block_kv
            hi = min(block_len, lo + block_kv)
            cw = hi - lo
            # chain-logical key positions of this chunk vs each query
            # row's global position: covers causality AND length (the
            # tail block's unwritten positions are > pos + i)
            tpos = j * block_len + lo + jax.lax.broadcasted_iota(
                jnp.int32, (w, cw), 1)
            qpos = pos + jax.lax.broadcasted_iota(jnp.int32, (w, cw), 0)
            allowed = tpos <= qpos
            for h in range(heads):
                r0 = h * w
                s = jax.lax.dot_general(   # [w, cw] f32 on the MXU
                    q[r0:r0 + w], k[lo:hi, h, :],
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
                s = jnp.where(allowed, s, _NEG)
                m_prev = m_scr[r0:r0 + w, :1]
                l_prev = l_scr[r0:r0 + w, :1]
                m_new = jnp.maximum(
                    m_prev, jnp.max(s, axis=-1, keepdims=True))
                p = jnp.exp(s - m_new)
                p = jnp.where(allowed, p, 0.0)
                corr = jnp.exp(m_prev - m_new)
                l_scr[r0:r0 + w, :1] = l_prev * corr \
                    + jnp.sum(p, axis=-1, keepdims=True)
                m_scr[r0:r0 + w, :1] = m_new
                acc_scr[r0:r0 + w, :] = acc_scr[r0:r0 + w, :] * corr \
                    + jax.lax.dot_general(
                        p.astype(v.dtype), v[lo:hi, h, :],
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _emit():
        R = heads * w
        l = jnp.maximum(l_scr[:R, :1], 1e-35)
        o_ref[0] = (acc_scr[:R] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_kv", "slots_tile",
                                             "interpret"))
def _paged_pallas(q, k_pool, v_pool, rows, pos, *, block_kv: int,
                  slots_tile: int, interpret: bool):
    S, H, w, hd = q.shape
    BL = k_pool.shape[1]
    MB = rows.shape[1]
    st = max(min(int(slots_tile), max(S, 1)), 1)
    bkv = max(min(int(block_kv), BL), 1)
    Sp = -(-S // st) * st
    R = H * w
    Rp = max(R, 8)                        # sublane-minimum scratch rows
    qf = jnp.pad(q.reshape(S, R, hd), ((0, Sp - S), (0, 0), (0, 0)))
    rows_p = jnp.pad(rows.astype(jnp.int32), ((0, Sp - S), (0, 0)),
                     constant_values=TRASH_BLOCK)
    pos_p = jnp.pad(pos.astype(jnp.int32), (0, Sp - S))[:, None]
    kern = functools.partial(_paged_kernel, scale=hd ** -0.5, heads=H,
                             w=w, block_len=BL, block_kv=bkv,
                             slots_tile=st)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(Sp // st, st, MB),
        in_specs=[
            pl.BlockSpec((1, R, hd),
                         lambda g, u, j, rt, pt: (g * st + u, 0, 0)),
            # the zero-copy read: the table entry IS the block index
            pl.BlockSpec((1, BL, H, hd),
                         lambda g, u, j, rt, pt:
                         (rt[g * st + u, j], 0, 0, 0)),
            pl.BlockSpec((1, BL, H, hd),
                         lambda g, u, j, rt, pt:
                         (rt[g * st + u, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, R, hd), lambda g, u, j, rt, pt: (g * st + u, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Rp, 128), jnp.float32),   # running max
            pltpu.VMEM((Rp, 128), jnp.float32),   # running denominator
            pltpu.VMEM((Rp, hd), jnp.float32),    # output accumulator
        ],
    )
    out = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Sp, R, hd), v_pool.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(rows_p, pos_p, qf, k_pool, v_pool)
    return out[:S].reshape(S, H, w, hd)


# ------------------------------------------------------------- resolution
def _tuned_paged(context: int, hd: int, w: int,
                 platform: str) -> tuple[int, int] | None:
    """Autotuned (block_kv, slots_tile) for this (context-bucket,
    platform) from the offline winner registry, or None when untuned.
    A plain dict read — this runs at jit trace time inside the serving
    programs, where locks/IO/clock are trace-safety hazards."""
    try:
        from ..perf import autotune
    except Exception:  # pragma: no cover - perf layer optional
        return None
    win = autotune.kernel_winner("paged_attn",
                                 autotune.paged_key(context, hd, w),
                                 platform)
    if not win:
        return None
    try:
        return int(win["block_kv"]), int(win["slots_tile"])
    except (KeyError, TypeError, ValueError):
        return None


def _resolve_paged(block_kv, slots_tile, *, context: int,
                   block_len: int, hd: int, w: int,
                   platform: str) -> tuple[int, int]:
    """Final (block_kv, slots_tile): explicit caller values win; then
    the autotuned winner for this context bucket; then the defaults
    (whole pool block per chunk, one slot per grid row)."""
    tuned = None
    if block_kv is None or slots_tile is None:
        tuned = _tuned_paged(context, hd, w, platform)
    if block_kv is None:
        block_kv = tuned[0] if tuned else block_len
    if slots_tile is None:
        slots_tile = tuned[1] if tuned else 1
    return (max(min(int(block_kv), int(block_len)), 1),
            max(int(slots_tile), 1))


# --------------------------------------------------------------- public
def paged_window_attention(q, k_pool, v_pool, rows, pos, *,
                           block_kv: int | None = None,
                           slots_tile: int | None = None,
                           impl: str | None = None,
                           interpret: bool | None = None):
    """Windowed paged attention: ``q`` [S, H, w, hd] holds w query rows
    per slot at global positions ``pos[s] + i`` (speculative verify
    passes the k+1 draft window); ``k_pool``/``v_pool`` are ONE layer's
    pools ``[num_blocks, block_len, H, hd]``; ``rows`` [S, max_blocks]
    is the ``PagedKVManager.block_rows`` table (TRASH_BLOCK padding);
    ``pos`` [S] int32. Query row i attends pool positions
    ``t <= pos + i`` through the slot's chain — the window's own k/v
    must already be scattered (write-then-attend, like
    ``decode_window``'s cache update). Returns [S, H, w, hd].

    ``impl``: "pallas" | "lax" | None (platform switch — TPU-class
    backends run the kernel, everything else the bit-exact lax
    reference). ``interpret`` forces the Pallas interpreter (tests).
    ``block_kv``/``slots_tile`` default to the autotuned winner
    (``perf.autotune``, kernel "paged_attn"), else block_len / 1;
    every config returns identical values."""
    plat = target_platform()
    if impl is None:
        impl = "pallas" if plat in ("tpu", "axon") else "lax"
    rows = jnp.asarray(rows, jnp.int32)
    pos = jnp.asarray(pos, jnp.int32)
    if impl == "lax":
        return _paged_reference(q, k_pool, v_pool, rows, pos)
    if impl != "pallas":
        raise ValueError(f"impl={impl!r} is not one of pallas|lax")
    if interpret is None:
        interpret = plat not in ("tpu", "axon")
    BL = int(k_pool.shape[1])
    context = int(rows.shape[1]) * BL
    block_kv, slots_tile = _resolve_paged(
        block_kv, slots_tile, context=context, block_len=BL,
        hd=int(q.shape[3]), w=int(q.shape[2]), platform=plat)
    return _paged_pallas(q, k_pool, v_pool, rows, pos,
                         block_kv=block_kv, slots_tile=slots_tile,
                         interpret=bool(interpret))


def paged_attention(q, k_pool, v_pool, rows, pos, *,
                    block_kv: int | None = None,
                    slots_tile: int | None = None,
                    impl: str | None = None,
                    interpret: bool | None = None):
    """Single-token paged decode attention: ``q`` [S, H, hd] is each
    slot's newest query at global position ``pos[s]`` (already written
    to the pools); attends pool positions ``t <= pos[s]`` through the
    block table. The w=1 case of :func:`paged_window_attention` —
    returns [S, H, hd]."""
    out = paged_window_attention(q[:, :, None, :], k_pool, v_pool,
                                 rows, pos, block_kv=block_kv,
                                 slots_tile=slots_tile, impl=impl,
                                 interpret=interpret)
    return out[:, :, 0, :]
