"""Deep-learning runtime: device-resident model transformer + sharded
training.

Replaces the reference's CNTK-on-Spark layer (``cntk/CNTKModel.scala``,
``com/microsoft/CNTK/SerializableFunction.scala``): instead of broadcasting
serialized native graphs to executor JVMs and crossing JNI per batch, models
are flax modules jitted once, with weights living in device memory, sharded
by ``jax.sharding`` over the mesh.
"""

from .bert import BertEncoder
from .generate import ContinuousGenerator, TextGenerator, generate
from .speculative import generate_speculative
from .model import TPUModel
from .pretrain import (MaskedLMModel, encoder_variables,
                       pretrain_causal_lm, pretrain_masked_lm)
from .text_encoder import (TextEncoder, TextEncoderFeaturizer,
                           make_attention_fn)
from .train import (TrainState, make_train_step, shard_train_state,
                    train_epoch)

__all__ = ["TPUModel", "TrainState", "make_train_step",
           "shard_train_state", "train_epoch", "TextEncoder",
           "TextEncoderFeaturizer", "make_attention_fn",
           "MaskedLMModel", "encoder_variables", "pretrain_masked_lm",
           "pretrain_causal_lm", "generate", "generate_speculative",
           "TextGenerator", "ContinuousGenerator",
           "BertEncoder"]
