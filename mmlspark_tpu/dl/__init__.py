"""Deep-learning runtime: device-resident model transformer + sharded
training.

Replaces the reference's CNTK-on-Spark layer (``cntk/CNTKModel.scala``,
``com/microsoft/CNTK/SerializableFunction.scala``): instead of broadcasting
serialized native graphs to executor JVMs and crossing JNI per batch, models
are flax modules jitted once, with weights living in device memory, sharded
by ``jax.sharding`` over the mesh.

The package __init__ is LAZY (no jax at import time): the LLM-serving
control plane imports the paged-KV bookkeeping half (``dl.paged_kv``)
from handler threads and host-only processes, and a submodule import
must not drag flax/backend bring-up into every importer (the same
no-JAX discipline ``sched``/``obs``/``perf`` keep, asserted by the CI
style smoke). Heavy submodules load on first attribute access;
``import mmlspark_tpu.dl.paged_kv`` alone stays jax-free.
"""

from __future__ import annotations

import importlib
import sys
import types

# public name -> defining submodule. Resolution is lazy: the submodule
# imports (and its partition-rule registration runs) on first access.
_EXPORTS = {
    "BertEncoder": ".bert",
    "ContinuousGenerator": ".generate",
    "TextGenerator": ".generate",
    "generate": ".generate",
    "generate_speculative": ".speculative",
    "TPUModel": ".model",
    "MaskedLMModel": ".pretrain",
    "encoder_variables": ".pretrain",
    "pretrain_causal_lm": ".pretrain",
    "pretrain_masked_lm": ".pretrain",
    "TextEncoder": ".text_encoder",
    "TextEncoderFeaturizer": ".text_encoder",
    "make_attention_fn": ".text_encoder",
    "TrainState": ".train",
    "make_train_step": ".train",
    "shard_train_state": ".train",
    "train_epoch": ".train",
    "PagedKVManager": ".paged_kv",
    "SequenceHandle": ".paged_kv",
    "paged_attention": ".pallas_paged_attention",
    "paged_window_attention": ".pallas_paged_attention",
}

__all__ = sorted(_EXPORTS)


class _LazyDlModule(types.ModuleType):
    """Module class carrying the lazy exports.

    ``generate`` needs special care: it is BOTH a submodule
    (``dl/generate.py``) and an exported function. The import system
    unconditionally ``setattr``\\ s a submodule onto its parent package
    on first import — so a plain lazy ``__getattr__`` would race:
    whichever of ``from mmlspark_tpu.dl import generate`` and an import
    of ``dl.speculative`` (whose ``from .generate import ...`` triggers
    that setattr) runs first would decide whether the attribute is the
    function or the module. A data descriptor (property) on the module
    CLASS always wins attribute lookup over the instance ``__dict__``,
    so reads deterministically get the function no matter the import
    order; the setter swallows the import system's module setattr.
    """

    @property
    def generate(self):
        mod = importlib.import_module(".generate", __name__)
        return mod.generate

    @generate.setter
    def generate(self, value):
        # the import system setattr()s the freshly imported submodule
        # here; the property getter shadows it either way, so nothing
        # to store — rebinding the public name to anything else is a
        # programming error worth surfacing
        if not isinstance(value, types.ModuleType):
            raise AttributeError(
                "mmlspark_tpu.dl.generate is a lazy export; import "
                "the submodule to patch its contents instead")

    def __getattr__(self, name):
        try:
            modname = _EXPORTS[name]
        except KeyError:
            raise AttributeError(
                f"module {__name__!r} has no attribute {name!r}"
            ) from None
        mod = importlib.import_module(modname, __name__)
        value = getattr(mod, name)
        # cache everything except the descriptor-managed name (its
        # property must keep winning over the instance __dict__)
        if name != "generate":
            setattr(self, name, value)
        return value

    def __dir__(self):
        return sorted(set(super().__dir__()) | set(__all__))


sys.modules[__name__].__class__ = _LazyDlModule
