"""In-framework masked-LM pretraining for the text encoder.

The reference ships pretrained models through its downloader
(``downloader/ModelDownloader.scala:37-60``) and never trains one; this
build is zero-egress, so pretrained text representations are produced
IN the framework: BERT-style masked-token prediction over any corpus,
yielding encoder weights the zoo serves to ``TextEncoderFeaturizer``
exactly like the vision checkpoints (``image/ImageFeaturizer.scala:81-85``
is the consumption pattern being mirrored).

TPU shape notes: the whole step is one jitted graph (embedding + blocks
+ LM head + masked xent), masking is host-side numpy (cheap, keeps the
graph static), batches stream through ``train_epoch``'s overlapped
transfer loop.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from ..parallel.partition import (DtypePolicy, activation_spec_for,
                                  dtype_policy_for, partition_rules_for,
                                  register_partition_rules)
from .text_encoder import TextEncoder
from .train import (TrainState, init_train_state,
                    make_partitioned_train_step, make_train_step,
                    partition_train_state, train_epoch)


class MaskedLMModel(nn.Module):
    """Encoder trunk + token-level LM head. Params nest under
    ``params["encoder"]`` / ``params["lm_head"]``, so the trunk's
    weights lift out cleanly for zoo publication
    (:func:`encoder_variables`)."""
    encoder: TextEncoder

    def setup(self):
        self.lm_head = nn.Dense(self.encoder.vocab, dtype=jnp.float32,
                                name="lm_head")

    def __call__(self, ids, train: bool = False):
        out = self.encoder(ids, train)
        return {"logits": self.lm_head(out["tokens"]), **out}

    def decode_step(self, tok, caches, pos):
        """One cached autoregressive step: [B] token ids at (traced)
        position ``pos`` → ([B, V] logits, updated per-block KV
        caches). Same params/math as the full forward restricted to the
        causal row (``dl.generate`` uses this; equivalence pinned by
        test)."""
        x = self.encoder.embed_token(tok, pos)
        x, caches = self.encoder.decode_blocks(x, caches, pos)
        return self.lm_head(x)[:, 0], caches

    def prefill(self, ids_prefix, caches):
        """Batched prompt prefill: seed the KV caches for positions
        ``[0, P)`` in one causal forward (``TextEncoder.prefill_caches``)
        so ``dl.generate`` scans only from the first writable position
        instead of streaming the whole prompt token-by-token."""
        return self.encoder.prefill_caches(ids_prefix, caches)

    def decode_window(self, toks, caches, pos):
        """Cached forward over a w-position window: [B, w] token ids
        at global positions ``[pos, pos+w)`` → ([B, w, V] logits,
        updated caches). Speculative decoding's verify pass — the
        target scores every draft position in ONE call
        (``dl.speculative``)."""
        x = self.encoder.embed_window(toks, pos)
        x, caches = self.encoder.decode_window_blocks(x, caches, pos)
        return self.lm_head(x), caches


# Partition rules for the pretraining LM: the encoder trunk's rules
# (paths under ``encoder/`` still hit them — re.search is unanchored)
# plus the LM head, column-parallel like every other vocab-sized
# projection. Registered here, next to MaskedLMModel, so the rule set
# lives beside the architecture it describes.
register_partition_rules("TextEncoderLM", (
    *partition_rules_for("TextEncoder"),
    (r"lm_head/kernel", (None, "tp")),
    (r"lm_head/bias", ("tp",)),
),
    # inherit the trunk's chip defaults (bf16 compute / fp32 accum,
    # dp-sharded block-boundary activations)
    dtype_policy=dtype_policy_for("TextEncoder") or DtypePolicy(
        param_dtype="float32", compute_dtype="bfloat16",
        grad_accum_dtype="float32"),
    activation_spec=activation_spec_for("TextEncoder") or ("dp",))


def _mesh_step_and_state(module, tx, state, mesh, dtype_policy,
                         batch_size):
    """Shared mesh plumbing for both pretraining objectives: validate
    the mesh/batch pairing, shard the LM TrainState per the
    TextEncoderLM rules, build the pjit'd step, and return the batch
    placement ``train_epoch`` should device_put host batches with
    (rows over ``dp``)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    if mesh is None:
        step = make_train_step(module, tx, fetch="logits",
                               loss_fn=masked_xent)
        return step, jax.tree.map(jnp.asarray, state), None
    if "dp" not in mesh.shape:
        raise ValueError(
            f"pretraining shards batches over axis 'dp'; mesh has "
            f"{tuple(mesh.shape)}")
    if batch_size % mesh.shape["dp"]:
        raise ValueError(
            f"batch_size={batch_size} must divide by the dp axis "
            f"({mesh.shape['dp']})")
    state, shardings = partition_train_state(
        state, mesh, partition_rules_for("TextEncoderLM"),
        dtype_policy=dtype_policy)
    step = make_partitioned_train_step(
        module, tx, mesh, shardings, fetch="logits",
        loss_fn=masked_xent, dtype_policy=dtype_policy)
    # spec spelled exactly like the step's batch in_shardings so the
    # device_put in train_epoch and the compiled signature agree
    return step, state, NamedSharding(mesh, P("dp"))


def masked_xent(logits, labels):
    """Cross-entropy over positions with ``labels >= 0`` (−1 = ignore:
    unmasked or pad). Mean over masked positions only."""
    valid = labels >= 0
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    ll = jnp.take_along_axis(
        logp, jnp.maximum(labels, 0)[..., None], axis=-1)[..., 0]
    return -(ll * valid).sum() / jnp.maximum(valid.sum(), 1)


def assert_causal(module, variables, sample_ids: np.ndarray,
                  vocab: int) -> None:
    """Causality probe: perturb the LAST position of ``sample_ids``
    [1, T]; logits at earlier positions must not move. Catches a
    bidirectional encoder passed where causality is required
    (pretraining, generation) — the failure mode is silent
    otherwise."""
    probe = np.asarray(sample_ids, np.int32)[:1].copy()
    if probe.shape[1] < 2:
        return
    base = module.apply(variables, jnp.asarray(probe))["logits"]
    probe2 = probe.copy()
    probe2[0, -1] = (probe2[0, -1] % (vocab - 2)) + 1
    alt = module.apply(variables, jnp.asarray(probe2))["logits"]
    drift = float(jnp.abs(base[0, :-1] - alt[0, :-1]).max())
    if drift > 1e-4:
        raise ValueError(
            "encoder attends to FUTURE positions (logit drift "
            f"{drift:.2e} after perturbing the last token) — build it "
            "with make_attention_fn(..., causal=True)")


def mask_batch(ids: np.ndarray, rng: np.random.Generator, *,
               mask_id: int, mask_frac: float = 0.15,
               pad_id: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """BERT-style corruption: ``mask_frac`` of non-pad positions are
    replaced by ``mask_id``; labels carry the original id there and −1
    everywhere else."""
    maskable = ids != pad_id
    pick = (rng.random(ids.shape) < mask_frac) & maskable
    x = np.where(pick, mask_id, ids).astype(np.int32)
    y = np.where(pick, ids, -1).astype(np.int32)
    return x, y


def pretrain_masked_lm(encoder: TextEncoder, ids: np.ndarray, *,
                       steps: int = 200, batch_size: int = 32,
                       learning_rate: float = 1e-3,
                       mask_frac: float = 0.15, mask_id: int | None = None,
                       seed: int = 0, mesh=None, dtype_policy=None,
                       tx: Any = None) -> tuple[TrainState, list[float]]:
    """Pretrain ``encoder`` on token-id rows ``ids`` [N, T] (pad id 0).

    ``mask_id`` defaults to the encoder's top vocab slot — reserve it
    when fitting the tokenizer (``BpeTokenizer`` never emits an id ≥ its
    ``vocabSize``, so an encoder ``vocab`` of ``vocabSize + 1`` leaves
    the slot free). Returns the full LM train state (resumable via
    ``CheckpointManager``) and per-batch losses; lift the trunk with
    :func:`encoder_variables` for zoo publication.

    ``mesh``: pjit the step over it (batch over ``dp``, weights per the
    TextEncoderLM partition rules; ``dtype_policy`` rides along) —
    ``batch_size`` must divide by the ``dp`` axis size."""
    ids = np.asarray(ids, np.int32)
    if mask_id is None:
        mask_id = encoder.vocab - 1
    if ids.max(initial=0) >= mask_id:
        raise ValueError(
            f"corpus uses id {ids.max()} but mask_id={mask_id}; give the "
            "encoder a spare top slot (vocab >= tokenizer vocab + 1)")
    module = MaskedLMModel(encoder)
    tx = tx or optax.adamw(learning_rate)
    state = init_train_state(module, jax.random.PRNGKey(seed), ids[:1],
                             tx)
    rng = np.random.default_rng(seed)

    def batches():
        for _ in range(steps):
            rows = ids[rng.integers(0, len(ids), size=batch_size)]
            yield mask_batch(rows, rng, mask_id=mask_id,
                             mask_frac=mask_frac)

    step, state, placement = _mesh_step_and_state(
        module, tx, state, mesh, dtype_policy, batch_size)
    return train_epoch(step, state, batches(), placement=placement)


def encoder_variables(state: TrainState) -> dict:
    """Extract the encoder trunk's variables from an LM train state, in
    the shape ``TextEncoder.apply`` (and the zoo checkpoint format)
    expects."""
    return {"params": state.params["encoder"]}


def pretrain_causal_lm(encoder: TextEncoder, ids: np.ndarray, *,
                       steps: int = 200, batch_size: int = 32,
                       learning_rate: float = 1e-3, seed: int = 0,
                       mesh=None, dtype_policy=None,
                       tx: Any = None) -> tuple[TrainState, list[float]]:
    """Next-token pretraining (the decoder-side twin of
    :func:`pretrain_masked_lm`): logits at position t predict token
    t+1, pad targets ignored. Pad id is 0 — the framework-wide
    convention ``TextEncoder`` hardcodes for its attention key mask and
    mean-pool (a configurable pad id here would silently desynchronize
    from the encoder's).

    The ``encoder`` MUST run causal attention (build it with
    ``make_attention_fn(impl, causal=True)``) — with bidirectional
    attention the objective is trivially cheatable by copying the next
    token, and the check below rejects it: position i's logits must be
    invariant to tokens at positions > i.

    ``mesh``/``dtype_policy``: same pjit contract as
    :func:`pretrain_masked_lm`."""
    ids = np.asarray(ids, np.int32)
    module = MaskedLMModel(encoder)  # same trunk + token head
    tx = tx or optax.adamw(learning_rate)
    state = init_train_state(module, jax.random.PRNGKey(seed), ids[:1],
                             tx)
    assert_causal(module, {"params": state.params}, ids[:1],
                  encoder.vocab)
    rng = np.random.default_rng(seed)

    def batches():
        for _ in range(steps):
            rows = ids[rng.integers(0, len(ids), size=batch_size)]
            x = rows[:, :-1]
            y = np.where(rows[:, 1:] != 0, rows[:, 1:],
                         -1).astype(np.int32)
            yield x.astype(np.int32), y

    step, state, placement = _mesh_step_and_state(
        module, tx, state, mesh, dtype_policy, batch_size)
    return train_epoch(step, state, batches(), placement=placement)
