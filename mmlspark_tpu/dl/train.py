"""Sharded training step for the DL path (transfer learning / fine-tune).

The reference has no in-framework DL training (CNTK models arrive
pretrained; ``ImageFeaturizer`` only extracts features, with the classifier
trained by SparkML — see call stack SURVEY §3.2). Because the TPU framework
runs models natively, fine-tuning is first-class: a jitted SPMD train step
over the full mesh, with

- batch sharded over ``dp`` (and ``sp`` for sequence models),
- wide parameter matrices sharded over ``tp`` (GSPMD inserts the
  collectives),
- gradient psum handled by jit itself via sharding propagation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import compat as _compat


@dataclasses.dataclass
class TrainState:
    params: Any
    batch_stats: Any
    opt_state: Any
    step: Any

    def tree_flatten(self):  # pragma: no cover - pytree plumbing
        return ((self.params, self.batch_stats, self.opt_state, self.step),
                None)

    @classmethod
    def tree_unflatten(cls, _, leaves):  # pragma: no cover
        return cls(*leaves)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)


def param_spec(path: tuple, leaf, tp_size: int) -> P:
    """Tensor-parallel sharding rule: shard the output-channel (last) dim of
    large kernels over ``tp``; replicate everything else.

    Keeping small tensors replicated avoids collectives that cost more than
    they save — the scaling-book recipe: pick a mesh, annotate only the big
    matmuls, let XLA do the rest.
    """
    if leaf.ndim >= 2 and leaf.shape[-1] % tp_size == 0 \
            and leaf.shape[-1] >= 2 * tp_size and leaf.size >= 4096:
        return P(*([None] * (leaf.ndim - 1) + ["tp"]))
    return P()


def shard_train_state(state: TrainState, mesh) -> TrainState:
    """device_put a TrainState with tp-sharded params over a mesh."""
    tp = mesh.shape.get("tp", 1)

    def put(path, leaf):
        arr = jnp.asarray(leaf)
        spec = param_spec(path, arr, tp) if tp > 1 else P()
        return jax.device_put(arr, NamedSharding(mesh, spec))

    params = jax.tree_util.tree_map_with_path(put, state.params)
    rest = jax.tree.map(
        lambda l: jax.device_put(jnp.asarray(l), NamedSharding(mesh, P())),
        (state.batch_stats, state.opt_state, state.step))
    return TrainState(params, rest[0], rest[1], rest[2])


def init_train_state(module, rng, sample_input, tx) -> TrainState:
    variables = module.init(rng, jnp.asarray(sample_input), True)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(params=params, batch_stats=batch_stats,
                      opt_state=tx.init(params), step=jnp.zeros((), jnp.int32))


def softmax_xent(logits, labels):
    logp = jax.nn.log_softmax(logits)
    onehot = jax.nn.one_hot(labels, logits.shape[-1])
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def _make_loss_of(module, loss_fn: Callable, fetch: str):
    """(params, stats, imgs, lbls) → (loss, new_model_state): the ONE
    forward+loss body shared by the jitted single-device step and the
    pjit'd partitioned step — the numerical-equivalence contract
    between them is this function being literally the same code."""

    def loss_of(params, stats, imgs, lbls):
        variables = {"params": params}
        if stats:
            variables["batch_stats"] = stats
            outputs, new_model_state = module.apply(
                variables, imgs, True, mutable=["batch_stats"])
        else:
            # no mutable kwarg at all: flax returns (out, state) for
            # ANY list-valued mutable, including []
            outputs = module.apply(variables, imgs, True)
            new_model_state = {}
        logits = outputs[fetch] if isinstance(outputs, dict) else outputs
        return loss_fn(logits, lbls), new_model_state

    return loss_of


def make_train_step(module, tx, mesh=None,
                    loss_fn: Callable = softmax_xent,
                    fetch: str = "logits",
                    batch_axes: tuple[str, ...] = ("dp",),
                    accum_steps: int = 1):
    """Build a jitted SPMD train step: (state, images, labels) → (state,
    loss). With a mesh, inputs are constrained batch-sharded and params
    follow their placed shardings (GSPMD adds the gradient reductions).

    ``accum_steps > 1``: the batch splits into that many microbatches
    whose gradients average under one ``lax.scan`` before a single
    optimizer update — the large-effective-batch pattern when one
    microbatch is all HBM affords. The batch dimension must divide by
    ``accum_steps`` (and, with a mesh, each microbatch must still divide
    the batch axes — otherwise GSPMD has to gather the unshardable
    remainder). BatchNorm-style mutable stats take the LAST microbatch's
    update (running averages, not exact-batch stats)."""

    def step(state: TrainState, images, labels):
        if mesh is None:
            return _body(state, images, labels)
        # trace under the mesh context so block-boundary activation
        # constraints inside the MODEL (partition.constrain_activation)
        # resolve against this mesh instead of no-op'ing
        with mesh:
            return _body(state, images, labels)

    def _body(state: TrainState, images, labels):
        if mesh is not None:
            bspec = P(batch_axes if len(batch_axes) > 1 else batch_axes[0])
            images = _compat.with_sharding_constraint(
                images, NamedSharding(mesh, P(*bspec)))
            labels = _compat.with_sharding_constraint(
                labels, NamedSharding(mesh, P(*bspec)))

        loss_of = _make_loss_of(module, loss_fn, fetch)
        grad_fn = jax.value_and_grad(loss_of, has_aux=True)
        if accum_steps <= 1:
            (loss, new_model_state), grads = grad_fn(
                state.params, state.batch_stats, images, labels)
        else:
            n = images.shape[0]
            if n % accum_steps:
                raise ValueError(
                    f"batch size {n} must divide by accum_steps="
                    f"{accum_steps}")
            m = n // accum_steps
            imgs_mb = images.reshape(accum_steps, m, *images.shape[1:])
            lbls_mb = labels.reshape(accum_steps, m, *labels.shape[1:])
            if mesh is not None:
                # keep each microbatch dp-sharded: without the constraint
                # GSPMD all-gathers the split batch inside the scan,
                # growing memory+comms instead of shrinking them
                mb_axes = batch_axes if len(batch_axes) > 1 \
                    else (batch_axes[0],)
                imgs_mb = _compat.with_sharding_constraint(
                    imgs_mb, NamedSharding(mesh, P(None, *mb_axes)))
                lbls_mb = _compat.with_sharding_constraint(
                    lbls_mb, NamedSharding(mesh, P(None, *mb_axes)))

            def accum(carry, mb):
                g_acc, l_acc, stats = carry
                imgs, lbls = mb
                (loss_i, mstate), g_i = grad_fn(state.params, stats,
                                                imgs, lbls)
                g_acc = jax.tree.map(jnp.add, g_acc, g_i)
                stats = mstate.get("batch_stats", stats)
                return (g_acc, l_acc + loss_i, stats), None

            g0 = jax.tree.map(jnp.zeros_like, state.params)
            (grads, loss, stats), _ = jax.lax.scan(
                accum, (g0, jnp.float32(0.0), state.batch_stats),
                (imgs_mb, lbls_mb))
            inv = 1.0 / accum_steps
            grads = jax.tree.map(lambda g: g * inv, grads)
            loss = loss * inv
            new_model_state = {"batch_stats": stats} if stats else {}
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        if mesh is not None:
            # pin output placements to the annotated layout: without the
            # constraint GSPMD may re-shard leaves it considers
            # profitable, so the returned state's placements drift from
            # shard_train_state's and every subsequent step recompiles
            tp = mesh.shape.get("tp", 1)
            new_params = jax.tree_util.tree_map_with_path(
                lambda path, leaf: _compat.with_sharding_constraint(
                    leaf, NamedSharding(
                        mesh, param_spec(path, leaf, tp) if tp > 1
                        else P())),
                new_params)
            # optimizer state is placed replicated by shard_train_state —
            # pin it too, or the drift problem just moves into opt_state
            new_opt = jax.tree.map(
                lambda leaf: _compat.with_sharding_constraint(
                    leaf, NamedSharding(mesh, P())),
                new_opt)
        new_state = TrainState(
            params=new_params,
            batch_stats=new_model_state.get("batch_stats",
                                            state.batch_stats),
            opt_state=new_opt, step=state.step + 1)
        return new_state, loss

    # compat.jit = jax.jit + the obs CompileTracker: a train step that
    # recompiles mid-run (shape drift, sharding drift) shows up in
    # profile_compiles_total{fn="train_step"} instead of as silent
    # multi-second stalls
    return _compat.jit(step, name="train_step", donate_argnums=(0,))


def partition_train_state(state: TrainState, mesh, rules, *,
                          dtype_policy=None, on_unmatched="replicate"):
    """Place a TrainState onto a mesh per a model's partition rules.

    The rules match over the FULL state pytree: optax optimizer states
    nest the param tree, so ``.../mu/block0/qkv/kernel`` hits the same
    rule as the param and the moments co-locate with their weights (the
    fmengine TrainState pattern, SNIPPETS.md [2]). Scalars (``step``,
    adam ``count``) replicate automatically; BatchNorm ``batch_stats``
    need their own rules (the ResNet set carries them).

    Returns ``(sharded_state, state_shardings)`` — feed the shardings
    to :func:`make_partitioned_train_step` so the compiled step's
    in/out layouts pin to this placement.
    """
    from ..parallel.partition import match_partition_rules, shard_params
    specs = match_partition_rules(rules, state,
                                  on_unmatched=on_unmatched)
    state = jax.tree.map(jnp.asarray, state)
    if dtype_policy is not None:
        # params and their optimizer moments share the storage dtype;
        # batch_stats ride along (float running stats), step/count are
        # ints and pass through untouched
        state = dtype_policy.cast_params(state)
    return shard_params(mesh, state, specs)


def make_partitioned_train_step(module, tx, mesh, state_shardings, *,
                                loss_fn: Callable = softmax_xent,
                                fetch: str = "logits",
                                batch_axes: tuple[str, ...] = ("dp",),
                                accum_steps: int = 1,
                                dtype_policy=None):
    """The pjit'd twin of :func:`make_train_step`: one SPMD train step
    over a dp×tp mesh, driven by rule-derived shardings instead of the
    per-leaf heuristic.

    ``state_shardings`` (from :func:`partition_train_state`) become the
    step's in/out shardings, so GSPMD can never drift the state layout
    between steps, and the input state buffer is DONATED — at tp>1 the
    param shards update in place. Batches shard over ``batch_axes``;
    gradients reduce over the batch axes by sharding propagation (the
    psum GSPMD inserts), exactly as the heuristic step.

    Math is :func:`_make_loss_of` + the same optax update as
    ``make_train_step`` — on a 1-device mesh the two produce the same
    loss trajectory to float tolerance (pinned by test).

    ``dtype_policy``: float inputs cast to ``compute_dtype`` on entry;
    with ``accum_steps > 1`` the gradient accumulator carries
    ``grad_accum_dtype`` (the arXiv:2008.01040 mixed-precision knob —
    bf16 grads accumulate badly over many microbatches; f32 costs HBM).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    bspec = P(batch_axes if len(batch_axes) > 1 else batch_axes[0])
    batch_sh = NamedSharding(mesh, bspec)
    repl = NamedSharding(mesh, P())

    def step(state: TrainState, images, labels):
        # mesh context for the whole traced body: model-internal
        # block-boundary constraints (partition.constrain_activation)
        # resolve against the step's mesh
        with mesh:
            return _body(state, images, labels)

    def _body(state: TrainState, images, labels):
        if dtype_policy is not None and jnp.issubdtype(
                images.dtype, jnp.floating):
            images = dtype_policy.cast_compute(images)
        loss_of = _make_loss_of(module, loss_fn, fetch)
        grad_fn = jax.value_and_grad(loss_of, has_aux=True)
        if accum_steps <= 1:
            (loss, new_model_state), grads = grad_fn(
                state.params, state.batch_stats, images, labels)
        else:
            n = images.shape[0]
            if n % accum_steps:
                raise ValueError(
                    f"batch size {n} must divide by accum_steps="
                    f"{accum_steps}")
            m = n // accum_steps
            imgs_mb = images.reshape(accum_steps, m, *images.shape[1:])
            lbls_mb = labels.reshape(accum_steps, m, *labels.shape[1:])
            # keep each microbatch batch-sharded inside the scan (the
            # same GSPMD gather hazard make_train_step documents)
            mb_sh = NamedSharding(mesh, P(None, *bspec))
            imgs_mb = _compat.with_sharding_constraint(imgs_mb, mb_sh)
            lbls_mb = _compat.with_sharding_constraint(lbls_mb, mb_sh)

            def accum(carry, mb):
                g_acc, l_acc, stats = carry
                imgs, lbls = mb
                (loss_i, mstate), g_i = grad_fn(state.params, stats,
                                                imgs, lbls)
                # cast INTO the accumulator dtype: with a lower-precision
                # grad_accum_dtype the bare add would promote the scan
                # carry and lax.scan rejects the carry-dtype drift
                g_acc = jax.tree.map(lambda a, g: a + g.astype(a.dtype),
                                     g_acc, g_i)
                stats = mstate.get("batch_stats", stats)
                return (g_acc, l_acc + loss_i, stats), None

            def zeros_accum(p):
                if dtype_policy is not None and \
                        dtype_policy.grad_accum_dtype is not None and \
                        jnp.issubdtype(p.dtype, jnp.floating):
                    return jnp.zeros(
                        p.shape, jnp.dtype(dtype_policy.grad_accum_dtype))
                return jnp.zeros_like(p)

            g0 = jax.tree.map(zeros_accum, state.params)
            (grads, loss, stats), _ = jax.lax.scan(
                accum, (g0, jnp.float32(0.0), state.batch_stats),
                (imgs_mb, lbls_mb))
            inv = 1.0 / accum_steps
            grads = jax.tree.map(
                lambda g, p: (g * inv).astype(p.dtype),
                grads, state.params)
            loss = loss * inv
            new_model_state = {"batch_stats": stats} if stats else {}
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params,
            batch_stats=new_model_state.get("batch_stats",
                                            state.batch_stats),
            opt_state=new_opt, step=state.step + 1)
        return new_state, loss

    return _compat.jit(step, name="partitioned_train_step",
                       in_shardings=(state_shardings, batch_sh, batch_sh),
                       out_shardings=(state_shardings, repl),
                       donate_argnums=(0,))


def train_epoch(step, state, batches, placement=None):
    """Drive a jitted train step over HOST-resident (x, y) batches,
    overlapping each batch's host→device transfer with the previous
    step's execution: dispatch is asynchronous, so the ``device_put`` of
    batch i+1 runs while step i computes. This is the input-pipeline
    half the resident-buffer benchmarks skip — without it a training
    loop serializes transfer → compute → transfer (the reference hides
    the same cost inside Spark's partition iterator + CNTK minibatch
    pump, ``cntk/CNTKModel.scala:499-541``).

    ``placement``: a Device or Sharding for the batches (defaults to the
    first device; pass a NamedSharding for mesh training). Returns
    (final_state, per-batch losses as floats) — losses are fetched once
    at the end so the loop never blocks on a scalar.

    The input ``state`` is CONSUMED when ``batches`` is non-empty:
    ``make_train_step`` donates its state argument, so the caller must
    use the returned state (keeping a reference to the old one and
    touching it raises a donated-buffer error)."""
    if placement is None:
        placement = jax.devices()[0]
    losses = []
    it = iter(batches)
    try:
        x, y = next(it)
    except StopIteration:
        return state, []
    cur = (jax.device_put(x, placement), jax.device_put(y, placement))
    while cur is not None:
        state, loss = step(state, *cur)     # async dispatch
        try:
            x, y = next(it)                 # transfer overlaps the step
            cur = (jax.device_put(x, placement),
                   jax.device_put(y, placement))
        except StopIteration:
            cur = None
        losses.append(loss)
    return state, [float(l) for l in jax.device_get(losses)]
