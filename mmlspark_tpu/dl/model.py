"""TPUModel — the DL inference transformer.

Reference ``cntk/CNTKModel.scala:145-543``: broadcast a serialized CNTK
graph, minibatch rows, cross JNI per batch, unbatch, coerce to vectors.
TPU-native equivalent:

- the model is a flax module + variables (a :class:`LoadedModel` from the
  zoo or any (module, variables) pair);
- ``feedDict``/``fetchDict`` map dataframe columns to model inputs and named
  endpoints to output columns (reference ``setFeedDict``/``setFetchDict``,
  ``CNTKModel.scala:207-227``);
- batching pads the last partial batch to a fixed shape so ONE compiled
  program serves the whole column (the reference's
  ``FixedMiniBatchTransformer(10)`` default, ``CNTKModel.scala:377``, exists
  to bound JNI churn; here fixed shapes exist to avoid recompilation);
- inference is sharded over the ``dp`` mesh axis when a mesh is supplied.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import ComplexParam, Model, Param, TypeConverters as TC
from ..core.contracts import HasInputCol, HasOutputCol
from ..models.zoo import LoadedModel


class TPUModel(Model, HasInputCol, HasOutputCol):
    """Run a flax model over a feature/image column.

    minibatchSize: device batch; the column is chunked to this size and the
    tail padded (mask-dropped on output), so exactly one XLA program is
    compiled per (model, batch-size).
    """

    model = ComplexParam("model", "LoadedModel or (module, variables)")
    fetchDict = Param("fetchDict", "endpoint name -> output column",
                      TC.identity, default=None, has_default=True)
    minibatchSize = Param("minibatchSize", "device batch size", TC.toInt,
                          default=64, has_default=True)
    outputNode = Param("outputNode", "single endpoint to fetch",
                       TC.toString, default="pooled", has_default=True)
    convertOutputToDenseVector = Param(
        "convertOutputToDenseVector",
        "flatten non-vector outputs to 2-D float vectors", TC.toBoolean,
        default=True, has_default=True)
    inputShape = Param("inputShape", "per-row input shape (tuple), e.g. "
                       "(224, 224, 3) for NHWC images", TC.identity,
                       default=None, has_default=True)
    transferDtype = Param(
        "transferDtype",
        "host->device wire dtype: 'auto' keeps uint8 columns as uint8 "
        "(4x fewer bytes than float32; the model's on-device cast "
        "handles widening), 'uint8' ditto (explicit), 'bfloat16' "
        "additionally halves float transfer — lossless when the "
        "model's first op casts to bf16 anyway — and 'float32' always "
        "widens on host (pre-round-3 behavior)", TC.toString,
        default="auto", has_default=True)
    pipelineDepth = Param(
        "pipelineDepth",
        "max in-flight dispatched batches before draining (>= 2). The "
        "default keeps one batch computing while one drains; raise it "
        "when the device sits behind a high-latency link (e.g. a "
        "tunnel) so more transfers overlap each round trip — at the "
        "cost of holding that many batches' outputs in device memory",
        TC.toInt, default=2, has_default=True)

    # class-level fallback: the serializer reconstructs instances
    # without running __init__
    _run_cache = None
    # per-transform timing breakdown (VERDICT r3 Weak #6: without it,
    # tunnel RTT masks framework overhead in e2e numbers). Keys:
    # prep_ms (host coercion), dispatch_ms (batch slicing + async
    # submit incl. transfer enqueue), drain_ms (waiting on device
    # compute + output pull), total_ms. Overwritten by every transform.
    last_stats: dict | None = None

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="features", outputCol="output")
        self._run_cache = None
        self.last_stats = None

    # ------------------------------------------------------------------
    def _loaded(self) -> tuple:
        m = self.get("model")
        if isinstance(m, LoadedModel):
            return m.module, m.variables
        return m  # (module, variables)

    def _apply_fn(self):
        """The jitted apply, cached per (module, variables) identity: a
        fresh closure per transform would RETRACE the model every call —
        through a remote compiler that is the whole latency budget.

        Identity keying means weight UPDATES must arrive by reassignment
        (``set("model", ...)`` / a new LoadedModel), never by mutating
        the cached variables pytree in place — in-place writes would
        silently serve the stale compiled weights."""
        module, variables = self._loaded()
        key = (id(module), id(variables))
        if self._run_cache is None or self._run_cache[0] != key:
            @jax.jit
            def run(batch):
                return module.apply(variables, batch, False)
            self._run_cache = (key, run)
        return self._run_cache[1]

    def _transform(self, df):
        import time
        t_start = time.perf_counter()
        col = df[self.getInputCol()]
        x = self._coerce_input(col)
        prep_ms = (time.perf_counter() - t_start) * 1e3
        n = x.shape[0]
        bs = self.get("minibatchSize")
        run = self._apply_fn()

        fetch = self.get("fetchDict") or {
            self.get("outputNode"): self.getOutputCol()}

        chunks: dict[str, list[np.ndarray]] = {k: [] for k in fetch}
        dispatch_ms = drain_ms = 0.0

        def drain(entry):
            nonlocal drain_ms
            t0 = time.perf_counter()
            real, out = entry
            for endpoint in fetch:
                chunks[endpoint].append(np.asarray(out[endpoint])[:real])
            drain_ms += (time.perf_counter() - t0) * 1e3

        # pipelined dispatch: pulling a batch's outputs blocks the
        # host, so keep the next batch(es) already dispatched before
        # pulling — device compute overlaps the host-side pull + prep
        # (the input-pipeline overlap a per-batch sync loop forfeits)
        depth = int(self.get("pipelineDepth"))
        if depth < 2:
            raise ValueError(
                f"pipelineDepth={depth} must be >= 2 (one batch "
                "computing while one drains); there is no synchronous "
                "mode")
        inflight: list[tuple[int, dict]] = []
        for start in range(0, n, bs):
            t0 = time.perf_counter()
            piece = x[start:start + bs]
            real = piece.shape[0]
            if real < bs:  # pad tail to the compiled shape
                pad = np.zeros((bs - real,) + piece.shape[1:], piece.dtype)
                piece = np.concatenate([piece, pad])
            out = run(jnp.asarray(piece))
            if not isinstance(out, dict):
                out = {self.get("outputNode"): out}
            for endpoint in fetch:
                if endpoint not in out:
                    raise KeyError(
                        f"endpoint {endpoint!r} not in model outputs "
                        f"{sorted(out)}")
            inflight.append((real, out))
            dispatch_ms += (time.perf_counter() - t0) * 1e3
            if len(inflight) >= depth:
                drain(inflight.pop(0))
        for entry in inflight:
            drain(entry)

        for endpoint, out_col in fetch.items():
            val = np.concatenate(chunks[endpoint])
            if self.get("convertOutputToDenseVector") and val.ndim > 2:
                val = val.reshape(val.shape[0], -1)
            df = df.with_column(out_col, val.astype(np.float32))
        self.last_stats = {
            "prep_ms": round(prep_ms, 3),
            "dispatch_ms": round(dispatch_ms, 3),
            "drain_ms": round(drain_ms, 3),
            "total_ms": round((time.perf_counter() - t_start) * 1e3, 3),
        }
        return df

    def _coerce_input(self, col) -> np.ndarray:
        mode = self.get("transferDtype")
        if mode not in ("auto", "uint8", "bfloat16", "float32"):
            raise ValueError(
                f"unknown transferDtype {mode!r}; expected "
                "auto|uint8|bfloat16|float32")
        if isinstance(col, np.ndarray) and col.dtype != object:
            # uint8 survives every narrowing mode: bfloat16 would DOUBLE
            # a uint8 column's wire bytes if it forced the float path
            keep_u8 = mode in ("auto", "uint8", "bfloat16") \
                and col.dtype == np.uint8
            x = col if keep_u8 else np.asarray(col, np.float32)
        else:
            x = np.stack([np.asarray(a, np.float32) for a in col])
        if mode == "bfloat16" and x.dtype == np.float32:
            # device compute is bf16 in every zoo model, so narrowing on
            # the host wire loses nothing the MXU would have kept — and
            # host->device (worse, host->tunnel->device) bytes halve
            import ml_dtypes
            x = x.astype(ml_dtypes.bfloat16)
        shape = self.get("inputShape")
        if shape is not None and x.ndim == 2:
            # unrolled CHW vectors → NHWC images (undo UnrollImage)
            H, W, C = shape
            x = x.reshape(x.shape[0], C, H, W).transpose(0, 2, 3, 1)
        return x
