"""Speculative decoding: a cheap draft proposes, the target verifies.

Single-stream autoregressive decode is launch-latency-bound on TPU —
each step is a [1, W]-shaped forward whose matmuls can't feed the MXU
(``bench.py`` gen rows: B=1 decodes ~40x slower per chip-second than
B=32). Speculation converts k sequential target steps into ONE
k+1-position cached window forward (``MaskedLMModel.decode_window``):
a draft model proposes k tokens by ordinary cached decode, the target
scores all of them in one pass, and the longest agreeing prefix is
accepted plus the target's own next token — so every round advances by
at least one token and the output is EXACTLY the target's greedy
decode, no matter how bad the draft is (asserted by test). Gains scale
with draft acceptance; a same-family smaller/distilled draft is the
intended pairing.

Temperature 0 uses greedy acceptance (longest agreeing prefix — output
EXACTLY the target's greedy decode); temperature > 0 uses the
rejection-sampling correction (:func:`_acceptance`), which makes the
emitted tokens an EXACT sample from the target's autoregressive
distribution regardless of the draft — the acceptance math is a pure
function pinned by a Monte-Carlo distribution test.

Both modes decode BATCHES: rows synchronize on the minimum per-row
acceptance each round — the committed token at the sync slot is the
limiting row's divergence bonus/replacement and the other rows'
already-accepted draft, so per-row output semantics are unchanged at a
tokens-per-pass rate set by the slowest row. Every random draw is
keyed by ABSOLUTE POSITION (never by round), so a row that accepted
beyond the sync point redraws identical decisions when it retries
those positions next round — the property that keeps batched sampled
decoding distribution-exact per row.

No reference counterpart (text generation is the framework's extension
axis, SURVEY §5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .generate import (_CACHE_LOCK, _CAUSAL_OK, _RUN_CACHE,
                       _RUN_CACHE_MAX)


def _acceptance(p_d, p_t, d, u):
    """Rejection-sampling acceptance (Leviathan et al.'s rule): accept
    draft token ``d[j] ~ p_d[j]`` when ``u[j] < p_t[j][d_j]/p_d[j][d_j]``;
    the round ends at the first rejection, whose replacement must be
    drawn from the RESIDUAL ``norm(relu(p_t[j*] - p_d[j*]))`` — the
    correction that makes each emitted token an exact sample from p_t.

    Pure function so the math is testable without models:
    ``p_d [k, V]``, ``p_t [k+1, V]`` (row k = the bonus distribution),
    ``d [k]`` draft tokens, ``u [k]`` uniforms. Returns
    ``(n_acc, replacement_dist [V])`` where replacement_dist is the
    residual at the rejection row, or ``p_t[k]`` (the plain bonus
    distribution) when every draft token was accepted."""
    k = d.shape[0]
    pd_tok = jnp.take_along_axis(p_d, d[:, None], axis=1)[:, 0]
    pt_tok = jnp.take_along_axis(p_t[:k], d[:, None], axis=1)[:, 0]
    ratio = pt_tok / jnp.maximum(pd_tok, 1e-20)
    accept = u < jnp.minimum(ratio, 1.0)
    n_acc = jnp.cumprod(accept.astype(jnp.int32)).sum()
    j_star = jnp.minimum(n_acc, k - 1)
    residual = jnp.maximum(p_t[j_star] - p_d[j_star], 0.0)
    residual = residual / jnp.maximum(residual.sum(), 1e-20)
    replacement = jnp.where(n_acc == k, p_t[k], residual)
    return n_acc, replacement


def _make_spec_run(module, draft_module, max_new_tokens: int,
                   pad_id: int, k: int, prefill_len: int,
                   temperature: float):
    """One jitted speculative decode program per (modules, config)."""

    def init_caches(mod, B, L):
        enc = mod.encoder
        hd = enc.width // enc.heads
        return tuple(
            (jnp.zeros((B, enc.heads, L, hd), enc.dtype),
             jnp.zeros((B, enc.heads, L, hd), enc.dtype))
            for _ in range(enc.depth))

    @jax.jit
    def run(params, draft_params, buf, ptr0, key):
        B, L = buf.shape
        caches_t = init_caches(module, B, L)
        caches_d = init_caches(draft_module, B, L)
        if prefill_len > 0:
            caches_t = module.apply(
                {"params": params}, buf[:, :prefill_len], caches_t,
                method="prefill")
            caches_d = draft_module.apply(
                {"params": draft_params}, buf[:, :prefill_len],
                caches_d, method="prefill")
        end = ptr0 + max_new_tokens

        def cond(carry):
            buf, ptr, *_ = carry
            return ptr < end

        def body(carry):
            buf, ptr, rounds, caches_t, caches_d = carry
            # --- draft: k ordinary cached steps from the last token --
            tok = jax.lax.dynamic_slice_in_dim(buf, ptr - 1, 1,
                                               axis=1)[:, 0]
            drafts, p_d_rows = [], []
            for j in range(k):
                logits_d, caches_d = draft_module.apply(
                    {"params": draft_params}, tok, caches_d,
                    ptr - 1 + j, method="decode_step")
                logits_d = logits_d.at[:, pad_id].set(-jnp.inf)
                if temperature > 0:
                    # per-POSITION fold_in, the same key schedule as
                    # dl.generate's cached path (a token at absolute
                    # position q samples with fold_in(key, q - 1)) —
                    # so self-draft full acceptance reproduces
                    # generate()'s sampled stream
                    scaled = logits_d / temperature
                    p_d_rows.append(jax.nn.softmax(scaled, -1))
                    tok = jax.random.categorical(
                        jax.random.fold_in(key, ptr - 1 + j), scaled,
                        axis=-1).astype(jnp.int32)
                else:
                    tok = jnp.argmax(logits_d,
                                     axis=-1).astype(jnp.int32)
                drafts.append(tok)
            # one extra CACHE-FILL step (logits discarded): the loop
            # above wrote kv for positions ptr-1..ptr+k-2, but d_k's
            # position would stay a zero-filled hole the NEXT round's
            # draft attends over after full acceptance — which silently
            # halved the self-draft acceptance rate
            _, caches_d = draft_module.apply(
                {"params": draft_params}, tok, caches_d,
                ptr - 1 + k, method="decode_step")
            d = jnp.stack(drafts, axis=1)                 # [B, k]

            # --- target: verify the whole window in ONE pass --------
            last = jax.lax.dynamic_slice_in_dim(buf, ptr - 1, 1,
                                                axis=1)[:, 0]
            window = jnp.concatenate([last[:, None], d], 1)  # [B,k+1]
            logits_t, caches_t = module.apply(
                {"params": params}, window, caches_t, ptr - 1,
                method="decode_window")                # [B, k+1, V]
            logits_t = logits_t.at[:, :, pad_id].set(-jnp.inf)

            if temperature > 0:
                # --- rejection-sampling acceptance (_acceptance) ----
                p_t = jax.nn.softmax(logits_t / temperature,
                                     -1)                  # [B, k+1, V]
                p_d = jnp.stack(p_d_rows, axis=1)         # [B, k, V]
                # acceptance uniforms: a DISTINCT stream from the
                # token-sampling keys, keyed PER ABSOLUTE POSITION
                # (not per round) — a batched row that accepted beyond
                # the sync point retries the same positions next round
                # and must redraw the SAME decisions, or exactness
                # breaks. Keys are shared across rows with per-row
                # noise coming from the batch dimension (the same
                # semantics as generate()'s batched sampling, pinned
                # by test) — note row i > 0 of a batch therefore does
                # NOT reproduce a single-row run of the same prompt,
                # exactly like generate().
                ukey = jax.random.fold_in(key, 0x5bd1)
                u = jax.vmap(lambda j: jax.random.uniform(
                    jax.random.fold_in(ukey, ptr + j), (B,)))(
                    jnp.arange(k)).T                       # [B, k]
                n_rows, repl_rows = jax.vmap(_acceptance)(p_d, p_t, d,
                                                          u)
                # batched sync-on-min (see the greedy branch): rows
                # past n_min commit their already-accepted d[n_min];
                # rows AT n_min commit their replacement sample
                n_acc = jnp.min(n_rows)
                # replacement/bonus key: on FULL acceptance the bonus
                # samples with that position's generate-matching key
                # (fresh — the draft loop never folded ptr-1+k). On a
                # REJECTION the residual draw must be INDEPENDENT of
                # the rejected draft token, whose key was exactly
                # fold_in(key, ptr-1+n_acc) — same Gumbel noise would
                # correlate the two draws and skew the distribution
                # (Monte-Carlo-pinned) — so rejections route through a
                # distinct fold, still position-keyed for retry
                # determinism.
                acc_key = jax.random.fold_in(key, ptr - 1 + k)
                rej_key = jax.random.fold_in(
                    jax.random.fold_in(key, 0x9e37), ptr - 1 + n_acc)
                bkey = jnp.where(n_acc == k, acc_key, rej_key)
                # rows AT the sync point sample from their own
                # replacement distribution (repl_rows[i] was computed
                # at that row's j* == n_min); rows past it never use
                # it — they commit their already-accepted d[n_min]
                sampled = jax.random.categorical(
                    bkey, jnp.log(jnp.maximum(repl_rows, 1e-20)),
                    axis=-1).astype(jnp.int32)             # [B]
                bonus = jnp.where(
                    n_rows > n_acc,
                    d[:, jnp.minimum(n_acc, k - 1)], sampled)
            else:
                # --- greedy: accept the longest agreeing prefix -----
                t = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)
                # d[:, j] accepted iff all d[:, :j+1] == t[:, :j+1]
                agree = jnp.cumprod(
                    (d == t[:, :k]).astype(jnp.int32), axis=1)
                # batched rows synchronize on the MINIMUM acceptance:
                # every row's first n_min draft tokens are
                # target-approved, and t[:, n_min] is each row's
                # correct next token either way — for a row whose
                # acceptance ended AT n_min it is the divergence
                # bonus; for a row that accepted further,
                # d[n_min+1] == t[n_min] by that very acceptance. Rows
                # beyond n_min re-propose the same (deterministic)
                # drafts next round, so output stays exactly greedy
                # per row; only the tokens-per-pass rate pays for the
                # sync.
                n_acc = jnp.min(agree.sum(axis=1))
                bonus = jnp.take_along_axis(
                    t, jnp.full((B, 1), n_acc, jnp.int32),
                    axis=1)[:, 0]
            # emit d_1..d_n then the replacement/bonus token at the
            # divergence point — always >= 1 new token
            emit = jnp.concatenate(
                [d, jnp.zeros((B, 1), jnp.int32)], axis=1)   # [B,k+1]
            emit = jax.lax.dynamic_update_slice(
                emit, bonus[:, None], (0, n_acc))
            n_new = jnp.minimum(n_acc + 1, end - ptr)
            # masked window write: positions beyond n_new keep buf
            old = jax.lax.dynamic_slice(buf, (0, ptr), (B, k + 1))
            write = jnp.where(jnp.arange(k + 1)[None] < n_new,
                              emit, old)
            buf = jax.lax.dynamic_update_slice(buf, write, (0, ptr))
            return buf, ptr + n_new, rounds + 1, caches_t, caches_d

        # the buffer is padded with k+1 slack positions so the window
        # write near the end never clips
        buf, ptr, rounds, _, _ = jax.lax.while_loop(
            cond, body,
            (buf, ptr0, jnp.zeros((), jnp.int32), caches_t, caches_d))
        return buf, ptr, rounds

    return run


def generate_speculative(module, variables, draft_module,
                         draft_variables, prompt_ids, *,
                         max_new_tokens: int, k: int = 4,
                         pad_id: int = 0, temperature: float = 0.0,
                         seed: int = 0):
    """Speculative decode.

    ``prompt_ids`` [B, Tp] int32 (no pad holes; rows synchronize on
    the minimum per-row acceptance — exact per-row output at a rate
    set by the slowest row); returns
    ``(ids [B, Tp + max_new_tokens], tokens_per_pass)`` where
    ``tokens_per_pass`` is generated-tokens / target-verify-passes —
    the speedup knob (k+1 when the draft always agrees, 1 when it
    never does).

    ``temperature=0`` (default): greedy acceptance — output identical
    to ``generate(module, ..., temperature=0)`` regardless of the
    draft. ``temperature > 0``: rejection-sampling acceptance
    (:func:`_acceptance`) — each emitted token is an EXACT sample from
    the target's distribution at that temperature regardless of the
    draft; with draft == target the stream reproduces ``generate``'s
    sampled output (same per-position key schedule)."""
    from .pretrain import assert_causal

    prompt_ids = np.asarray(prompt_ids, np.int32)
    if k < 1:
        raise ValueError(f"k={k}: the draft must propose at least one "
                         "token per round")
    if prompt_ids.ndim != 2:
        raise ValueError("prompt_ids must be [B, Tp]")
    if (prompt_ids == pad_id).any():
        raise ValueError("speculative decode needs a dense prompt "
                         "row (no pad)")
    if module.encoder.vocab != draft_module.encoder.vocab:
        raise ValueError("draft and target must share a vocabulary")
    Tp = prompt_ids.shape[1]
    if Tp < 1:
        raise ValueError("empty prompt")
    # causality probes memoized per module (same pattern and cache as
    # generate(): two eager forwards per probe must not recur per call
    # — they would land inside the bench's timing window and on every
    # serving request)
    for mod, var in ((module, variables),
                     (draft_module, draft_variables)):
        with _CACHE_LOCK:
            probed = mod in _CAUSAL_OK
        if not probed:
            assert_causal(mod, {"params": var["params"]},
                          prompt_ids if Tp >= 2
                          else np.repeat(prompt_ids, 2, axis=1),
                          mod.encoder.vocab)
            with _CACHE_LOCK:
                _CAUSAL_OK[mod] = True
                while len(_CAUSAL_OK) > _RUN_CACHE_MAX:
                    _CAUSAL_OK.popitem(last=False)

    total = Tp + max_new_tokens
    prefill_len = Tp - 1
    cache_key = (module, draft_module, max_new_tokens, pad_id, int(k),
                 prefill_len, float(temperature), "spec")
    with _CACHE_LOCK:
        run = _RUN_CACHE.get(cache_key)
        if run is not None:
            _RUN_CACHE.move_to_end(cache_key)
    if run is None:
        run = _make_spec_run(module, draft_module, max_new_tokens,
                             pad_id, int(k), prefill_len,
                             float(temperature))
        with _CACHE_LOCK:
            _RUN_CACHE[cache_key] = run
            while len(_RUN_CACHE) > _RUN_CACHE_MAX:
                _RUN_CACHE.popitem(last=False)

    buf = np.full((prompt_ids.shape[0], total + k + 1), pad_id,
                  np.int32)
    buf[:, :Tp] = prompt_ids
    out, ptr, rounds = run(variables["params"],
                           draft_variables["params"],
                           jnp.asarray(buf), Tp,
                           jax.random.PRNGKey(seed))
    return (np.asarray(out[:, :total]),
            float(ptr - Tp) / max(float(rounds), 1.0))
