"""Checkpoint / resume for training state.

Reference checkpoint story (SURVEY §5): LightGBM batch training carries the
model string across batches (``LightGBMBase.scala:34-51``), VW warm-starts
from ``initialModel`` bytes, streaming queries use ``checkpointLocation``.
The DL path adds real training, so it gets real checkpoints: orbax-backed
save/restore of :class:`TrainState` with step-numbered directories and
retention.
"""

from __future__ import annotations

import os
import re

import jax
import numpy as np

from .train import TrainState


class CheckpointManager:
    """Step-numbered orbax checkpoints with retention."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:010d}")

    def all_steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            m = re.fullmatch(r"step_(\d+)", name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, state: TrainState, step: int | None = None) -> str:
        import orbax.checkpoint as ocp
        step = int(state.step) if step is None else step
        path = self._step_dir(step)
        with ocp.PyTreeCheckpointer() as ck:
            ck.save(path, jax.tree.map(np.asarray, {
                "params": state.params,
                "batch_stats": state.batch_stats,
                "opt_state": state.opt_state,
                "step": state.step,
            }), force=True)
        self._retain()
        return path

    def restore(self, step: int | None = None,
                target: TrainState | None = None) -> TrainState:
        """Restore a checkpoint.

        ``target`` is a reference TrainState (e.g. a freshly initialized
        one) whose pytree STRUCTURE the restored arrays are poured into.
        Without it, orbax returns plain dicts/lists — fine for params and
        batch_stats, but optax opt_states are namedtuples (e.g.
        ``ScaleByAdamState``), so resuming adam/momentum without a target
        would silently hand the optimizer the wrong container types. Pass
        the live state for anything beyond stateless optimizers.
        """
        import orbax.checkpoint as ocp
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        with ocp.PyTreeCheckpointer() as ck:
            if target is None:
                tree = ck.restore(self._step_dir(step))
            else:
                # read shape/dtype without np.asarray: that would pull
                # every device array to host just to inspect it
                abstract = jax.tree.map(
                    lambda x: jax.ShapeDtypeStruct(
                        np.shape(x),
                        getattr(x, "dtype", None) or np.asarray(x).dtype),
                    {"params": target.params,
                     "batch_stats": target.batch_stats,
                     "opt_state": target.opt_state,
                     "step": target.step})
                tree = ck.restore(self._step_dir(step), item=abstract)
        return TrainState(params=tree["params"],
                          batch_stats=tree["batch_stats"],
                          opt_state=tree["opt_state"], step=tree["step"])

    def _retain(self) -> None:
        import shutil
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
