"""Checkpoint / resume for training state.

Reference checkpoint story (SURVEY §5): LightGBM batch training carries the
model string across batches (``LightGBMBase.scala:34-51``), VW warm-starts
from ``initialModel`` bytes, streaming queries use ``checkpointLocation``.
The DL path adds real training, so it gets real checkpoints: orbax-backed
save/restore of :class:`TrainState` with step-numbered directories and
retention.

Crash safety (resilience subsystem): a save writes into a temp directory
and ``os.replace``-renames it into ``step_NNN`` — a crash mid-write
(exercised by the ``checkpoint.write`` fault-injection point) leaves an
invisible ``.tmp-*`` orphan, never a half-written step. ``all_steps`` /
``restore`` additionally skip — and count, via
``resilience_checkpoint_skipped_total`` — partially-written or corrupt
step dirs instead of crashing mid-resume: a torn copy from an older
non-atomic writer costs one older checkpoint, not the training run.
"""

from __future__ import annotations

import logging
import os
import re
import shutil
import uuid

import jax
import numpy as np

from ..obs import registry as _obs
from ..resilience.faults import injector as _faults
from .train import TrainState

_LOG = logging.getLogger("mmlspark_tpu.dl.checkpoint")

_m_skipped = _obs.counter(
    "resilience_checkpoint_skipped_total",
    "checkpoint step dirs skipped at restore/listing, by reason "
    "(partial | corrupt)")


class CheckpointManager:
    """Step-numbered orbax checkpoints with retention."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        # partial dirs already counted+warned about: the skip counter
        # measures skipped checkpoints, not how often the store was
        # listed (all_steps runs on every save via _retain)
        self._partial_counted: set[str] = set()
        os.makedirs(self.directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:010d}")

    def all_steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            m = re.fullmatch(r"step_(\d+)", name)
            if not m:
                continue
            # an empty step dir is a torn write from a non-atomic
            # writer (or a crash between mkdir and content): listing it
            # would make latest_step()/restore() chase a ghost
            path = os.path.join(self.directory, name)
            if os.path.isdir(path) and not os.listdir(path):
                if name not in self._partial_counted:
                    self._partial_counted.add(name)
                    _m_skipped.inc(1, reason="partial")
                    _LOG.warning("checkpoint %s is empty (torn write) — "
                                 "skipped", path)
                continue
            out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, state: TrainState, step: int | None = None) -> str:
        """Atomic save: the tree is written into a ``.tmp-*`` sibling
        and renamed into ``step_NNN`` in one ``os.replace`` — readers
        (and a resume after a crash here) only ever see complete
        checkpoints. The ``checkpoint.write`` injection point sits
        between write and rename: exactly where a real crash tears a
        non-atomic writer."""
        import orbax.checkpoint as ocp
        step = int(state.step) if step is None else step
        final = self._step_dir(step)
        tmp = os.path.join(
            self.directory,
            f".tmp-step_{step:010d}-{uuid.uuid4().hex[:8]}")
        try:
            with ocp.PyTreeCheckpointer() as ck:
                ck.save(tmp, jax.tree.map(np.asarray, {
                    "params": state.params,
                    "batch_stats": state.batch_stats,
                    "opt_state": state.opt_state,
                    "step": state.step,
                }), force=True)
            _faults.apply("checkpoint.write", key=str(step))
            if os.path.isdir(final):
                shutil.rmtree(final)
            os.replace(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._retain()
        return final

    def restore(self, step: int | None = None,
                target: TrainState | None = None) -> TrainState:
        """Restore a checkpoint.

        ``target`` is a reference TrainState (e.g. a freshly initialized
        one) whose pytree STRUCTURE the restored arrays are poured into.
        Without it, orbax returns plain dicts/lists — fine for params and
        batch_stats, but optax opt_states are namedtuples (e.g.
        ``ScaleByAdamState``), so resuming adam/momentum without a target
        would silently hand the optimizer the wrong container types. Pass
        the live state for anything beyond stateless optimizers.

        With ``step=None`` (resume-latest), a corrupt checkpoint is
        skipped — counted in ``resilience_checkpoint_skipped_total`` —
        and the next older step is tried; an EXPLICIT step that fails
        to load raises (the caller asked for that one)."""
        if step is not None:
            return self._restore_one(step, target)
        candidates = self.all_steps()
        if not candidates:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        last_err: Exception | None = None
        for s in reversed(candidates):
            try:
                return self._restore_one(s, target)
            except Exception as e:  # unreadable content: fall back
                last_err = e
                _m_skipped.inc(1, reason="corrupt")
                # loud, with the real exception: a structural mismatch
                # or transient IO error looks identical to corruption
                # from here, and silently resuming from an OLDER step
                # must leave a visible trail, not just a metric
                _LOG.warning("checkpoint step %d failed to restore "
                             "(%s: %s) — falling back to an older step",
                             s, type(e).__name__, e)
        raise FileNotFoundError(
            f"no restorable checkpoint under {self.directory} "
            f"({len(candidates)} corrupt)") from last_err

    def _restore_one(self, step: int,
                     target: TrainState | None) -> TrainState:
        import orbax.checkpoint as ocp
        with ocp.PyTreeCheckpointer() as ck:
            if target is None:
                tree = ck.restore(self._step_dir(step))
            else:
                # read shape/dtype without np.asarray: that would pull
                # every device array to host just to inspect it
                abstract = jax.tree.map(
                    lambda x: jax.ShapeDtypeStruct(
                        np.shape(x),
                        getattr(x, "dtype", None) or np.asarray(x).dtype),
                    {"params": target.params,
                     "batch_stats": target.batch_stats,
                     "opt_state": target.opt_state,
                     "step": target.step})
                tree = ck.restore(self._step_dir(step), item=abstract)
        return TrainState(params=tree["params"],
                          batch_stats=tree["batch_stats"],
                          opt_state=tree["opt_state"], step=tree["step"])

    def _retain(self) -> None:
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
        # sweep .tmp-* orphans from crashed saves (invisible to
        # all_steps, but they hold disk until someone collects them)
        for name in os.listdir(self.directory):
            if name.startswith(".tmp-step_"):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)
