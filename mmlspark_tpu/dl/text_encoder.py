"""Long-context transformer text encoder — the user-facing surface of
the sequence-parallel machinery.

The reference has no attention models (SURVEY §5: long-context is
"absent in the reference"); this is the first-class TPU-native extension
the framework owes its DL path. A compact pre-LN transformer encoder
whose attention implementation is pluggable:

- ``dense``    — standard softmax attention (short inputs);
- ``blockwise``— single-device flash-style blocks, O(T) memory;
- ``ring``     — exact attention with Q/K/V sequence-sharded over an
  ``sp`` mesh axis, K/V rotating via ``ppermute``
  (``parallel/ring_attention.py``);
- ``ulysses``  — all-to-all head/sequence reshard
  (``parallel/ulysses.py``);
- ``ring_flash`` / ``ulysses_flash`` — the sharded impls with the fused
  Pallas kernel (``pallas_attention.py``) as each device's local
  attention (non-causal).

``TextEncoderFeaturizer`` wraps it as a pipeline stage: token-id rows →
mean-pooled embeddings, the text counterpart of ``ImageFeaturizer``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from ..core.contracts import HasInputCol, HasOutputCol
from ..core.logging import BasicLogging
from ..core.param import ComplexParam, Param, TypeConverters as TC
from ..core.pipeline import Transformer


def _dense_attention(q, k, v, key_mask=None, causal: bool = False):
    D = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * (D ** -0.5)
    if causal:
        T = q.shape[2]
        tri = jnp.arange(T)[None, :] <= jnp.arange(T)[:, None]
        s = jnp.where(tri[None, None], s, -jnp.inf)
    if key_mask is not None:
        s = s + jnp.where(key_mask, 0.0, -jnp.inf)[:, None, None, :]
    p = jax.nn.softmax(s, axis=-1)
    if key_mask is not None or causal:
        # a fully-masked row (empty document / a causal row whose own
        # position is padded): softmax over -inf is NaN; emit zeros
        # like the blockwise/ring accumulators
        p = jnp.where(jnp.isnan(p), 0.0, p)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


class EncoderBlock(nn.Module):
    """Pre-LN block over an externally supplied attention fn
    (``fn(q, k, v, key_mask)``, [B,H,T,D]³ → [B,H,T,D]) — the block is
    agnostic to whether the sequence axis is sharded. ``key_mask``
    excludes padding keys from every softmax, so a row's output never
    depends on how far the batch was padded.

    Setup-style with the attention residual (``attend``) and the
    feed-forward residual (``ffn``) callable separately: the MoE encoder
    (``models.moe.make_moe_text_encoder``) keeps the attention trunk and
    swaps ``ffn`` for an expert-parallel mixture."""
    heads: int
    mlp_dim: int
    width: int
    attention_fn: Callable = _dense_attention
    dtype: Any = jnp.bfloat16

    def setup(self):
        W = self.width
        self.ln_1 = nn.LayerNorm(dtype=jnp.float32, name="ln_1")
        self.qkv_proj = nn.Dense(3 * W, dtype=self.dtype, name="qkv")
        self.out_proj = nn.Dense(W, dtype=self.dtype, name="out")
        self.ln_2 = nn.LayerNorm(dtype=jnp.float32, name="ln_2")
        self.mlp_in = nn.Dense(self.mlp_dim, dtype=self.dtype,
                               name="mlp_1")
        self.mlp_out = nn.Dense(W, dtype=self.dtype, name="mlp_2")

    def _project_qkv(self, x):
        """ln_1 → fused qkv projection → per-head split: the ONE copy
        of the pipeline ``attend``/``decode_step``/``prefill`` all run —
        they must stay numerically in lockstep or cached decode drifts
        from the re-encode reference. [B, T, W] → q, k, v [B, H, T, hd]."""
        hd = self.width // self.heads
        h = self.ln_1(x).astype(self.dtype)
        qkv = self.qkv_proj(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def split(a):
            B, T = a.shape[:2]
            return a.reshape(B, T, self.heads, hd).transpose(0, 2, 1, 3)

        return split(q), split(k), split(v)

    def _merge_out(self, o):
        """Head merge + output projection ([B, H, T, hd] → [B, T, W])."""
        B, H, T, D = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(B, T, self.width)
        return self.out_proj(o.astype(self.dtype))

    def attend(self, x, key_mask=None):
        """The attention residual: x + out_proj(attention(qkv(ln_1 x)))."""
        q, k, v = self._project_qkv(x)
        return x + self._merge_out(self.attention_fn(q, k, v, key_mask))

    def pre_ffn_norm(self, x):
        """ln_2 alone — the MoE variant normalizes before its experts."""
        return self.ln_2(x)

    def ffn(self, x):
        """The dense feed-forward residual."""
        h = self.ln_2(x)
        h = self.mlp_in(h.astype(self.dtype))
        h = nn.gelu(h)
        return x + self.mlp_out(h)

    def __call__(self, x, key_mask=None):
        return self.ffn(self.attend(x, key_mask))

    def decode_step(self, x_tok, k_cache, v_cache, pos):
        """One autoregressive decode step through this block.

        ``x_tok`` [B, 1, W] is the current position's activation;
        ``k_cache``/``v_cache`` [B, H, L, hd] hold every previous
        position's projections; ``pos`` (traced scalar) is the current
        write index. Returns ``(y [B, 1, W], k_cache, v_cache)`` with
        this position's k/v written. Same params, same math as the full
        forward — attention reduces over cache entries ≤ pos (equal to
        the causal row), so cached decode is equivalent to re-encoding
        the whole prefix (pinned by test)."""
        B = x_tok.shape[0]
        q, k, v = self._project_qkv(x_tok)           # [B, H, 1, hd]
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, pos, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, pos, 0))
        L = k_cache.shape[2]
        # ONE attention implementation: the dense path with the causal
        # row as its key mask (keeps scale/dtype/masking in one place)
        valid = jnp.broadcast_to((jnp.arange(L) <= pos)[None], (B, L))
        o = _dense_attention(q, k_cache, v_cache, key_mask=valid)
        x = x_tok + self._merge_out(o)
        return self.ffn(x), k_cache, v_cache

    def prefill(self, x):
        """Batched cache fill: the whole prompt prefix [B, P, W] in ONE
        causal forward — the k/v the MXU computes as a single batched
        matmul here are exactly what ``decode_step`` would have written
        one position at a time (same projections, attention over keys
        ≤ own position). Runs the block's OWN ``attention_fn`` (causal
        for any LM that reaches decoding — ``dl.generate`` probes
        this), so a flash/blockwise-configured model prefills at its
        own O(T) memory profile instead of materializing dense scores.
        Returns ``(y [B, P, W], k, v [B, H, P, hd])`` so the caller can
        seed the decode caches."""
        q, k, v = self._project_qkv(x)
        o = self.attention_fn(q, k, v, None)
        return self.ffn(x + self._merge_out(o)), k, v

    def decode_window(self, x_win, k_cache, v_cache, pos):
        """``decode_step`` generalized to a w-position WINDOW: x_win
        [B, w, W] holds activations for global positions
        ``[pos, pos+w)``; caches hold every earlier position. Writes
        the window's k/v, attends each window row over cache entries
        ≤ its own global position (one [w, L] mask — the multi-row
        causal slice), returns ``(y [B, w, W], k_cache, v_cache)``.
        Speculative verification's workhorse: the target model scores
        k+1 draft positions in ONE pass instead of k+1 scans."""
        B, w = x_win.shape[:2]
        q, k, v = self._project_qkv(x_win)            # [B, H, w, hd]
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, pos, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, pos, 0))
        L = k_cache.shape[2]
        scale = (self.width // self.heads) ** -0.5
        # same formulation as _dense_attention (bf16 operands, f32 MXU
        # accumulation, -inf masking, NaN guard) so windowed decode
        # stays numerically in lockstep with decode_step/prefill
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cache,
                       preferred_element_type=jnp.float32) * scale
        allowed = (jnp.arange(L)[None, :]
                   <= (pos + jnp.arange(w))[:, None])  # [w, L]
        s = jnp.where(allowed[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        p = jnp.where(jnp.isnan(p), 0.0, p)
        o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v_cache.dtype),
                       v_cache)
        x = x_win + self._merge_out(o)
        return self.ffn(x), k_cache, v_cache


class TextEncoder(nn.Module):
    """Token ids [N, T] → ``{"tokens": [N, T, W], "pooled": [N, W]}``.

    ``pooled`` is the masked mean over non-pad tokens (pad id 0) — the
    transfer-learning feature vector. Setup-style (not compact) so the
    prologue (``embed_ids``) and epilogue (``finalize``) are callable on
    their own — ``pipeline_encode`` runs them replicated around the
    pipeline-parallel block stack."""
    vocab: int = 32768
    width: int = 256
    depth: int = 4
    heads: int = 8
    mlp_dim: int = 1024
    max_len: int = 65536
    attention_fn: Callable = _dense_attention
    dtype: Any = jnp.bfloat16
    # rematerialize each block in the backward (jax.checkpoint): block
    # activations are recomputed instead of stored, cutting training
    # memory from O(depth·B·T·W) residuals to O(B·T·W) at ~1/3 extra
    # FLOPs — the standard long-context training trade
    remat: bool = False

    def setup(self):
        self.embed_layer = nn.Embed(self.vocab, self.width,
                                    dtype=self.dtype, name="embed")
        block_cls = nn.remat(EncoderBlock) if self.remat else EncoderBlock
        self.blocks = [block_cls(self.heads, self.mlp_dim, self.width,
                                 attention_fn=self.attention_fn,
                                 dtype=self.dtype, name=f"block{i}")
                       for i in range(self.depth)]
        self.final_ln = nn.LayerNorm(dtype=jnp.float32, name="ln")

    def embed_ids(self, ids):
        """Embedding + fixed sinusoidal positions (length-extrapolable,
        nothing to shard or convert) → [N, T, W] block input."""
        T = ids.shape[1]
        x = self.embed_layer(ids)
        pos = jnp.arange(T)[:, None]
        dim = jnp.arange(self.width // 2)[None, :]
        ang = pos / (10000.0 ** (2 * dim / self.width))
        pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
        return x + pe[None].astype(self.dtype)

    def embed_token(self, tok, pos):
        """Single-position prologue for cached decoding: embed [B]
        token ids + the sinusoidal position encoding at (traced) scalar
        ``pos`` → [B, 1, W]. Same constants as ``embed_ids``."""
        x = self.embed_layer(tok[:, None])           # [B, 1, W]
        dim = jnp.arange(self.width // 2)
        ang = pos.astype(jnp.float32) / (10000.0
                                         ** (2 * dim / self.width))
        pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)])
        return x + pe[None, None].astype(self.dtype)

    def decode_blocks(self, x_tok, caches, pos):
        """Run one position through every block with KV caches.
        ``caches``: tuple of (k, v) per block. Returns (final-LN'd
        [B, 1, W] activation, updated caches)."""
        new_caches = []
        for block, (kc, vc) in zip(self.blocks, caches):
            x_tok, kc, vc = block.decode_step(x_tok, kc, vc, pos)
            new_caches.append((kc, vc))
        return self.final_ln(x_tok), tuple(new_caches)

    def embed_window(self, toks, pos):
        """Prologue for a w-position decode window: embed [B, w] token
        ids at (traced) global positions ``[pos, pos+w)`` — same
        constants as ``embed_ids``/``embed_token``."""
        x = self.embed_layer(toks)                    # [B, w, W]
        w = toks.shape[1]
        dim = jnp.arange(self.width // 2)[None, :]
        p = (pos + jnp.arange(w))[:, None].astype(jnp.float32)
        ang = p / (10000.0 ** (2 * dim / self.width))
        pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
        return x + pe[None].astype(self.dtype)

    def decode_window_blocks(self, x_win, caches, pos):
        """Run a w-position window through every block with KV caches
        (``EncoderBlock.decode_window``). Returns (final-LN'd
        [B, w, W], updated caches)."""
        new_caches = []
        for block, (kc, vc) in zip(self.blocks, caches):
            x_win, kc, vc = block.decode_window(x_win, kc, vc, pos)
            new_caches.append((kc, vc))
        return self.final_ln(x_win), tuple(new_caches)

    def prefill_caches(self, ids_prefix, caches):
        """Seed the decode caches for positions ``[0, P)`` with ONE
        batched causal forward over the prompt prefix instead of P
        sequential ``decode_blocks`` steps — prefill becomes large MXU
        matmuls (O(P) parallel) rather than an O(P)-step scan of
        [B, 1]-shaped work. ``ids_prefix`` must contain only real
        tokens for every row (the caller prefixes at most
        ``min(prompt_len) - 1`` positions). Returns the updated
        caches."""
        x = self.embed_ids(ids_prefix)
        new_caches = []
        for block, (kc, vc) in zip(self.blocks, caches):
            x, k, v = block.prefill(x)
            kc = jax.lax.dynamic_update_slice(
                kc, k.astype(kc.dtype), (0, 0, 0, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, v.astype(vc.dtype), (0, 0, 0, 0))
            new_caches.append((kc, vc))
        return tuple(new_caches)

    def finalize(self, x, ids):
        """Final LN + masked mean pool over non-pad tokens."""
        x = self.final_ln(x)
        mask = (ids != 0).astype(jnp.float32)[..., None]
        pooled = (x * mask).sum(1) / jnp.maximum(mask.sum(1), 1.0)
        return {"tokens": x, "pooled": pooled.astype(jnp.float32)}

    def __call__(self, ids, train: bool = False):
        from ..parallel.partition import constrain_activation
        # block-boundary activation sharding (batch over dp per the
        # registered activation spec) — identity with no mesh in scope
        x = constrain_activation(self.embed_ids(ids), "TextEncoder")
        key_mask = ids != 0
        for block in self.blocks:
            x = constrain_activation(block(x, key_mask), "TextEncoder")
        return self.finalize(x, ids)


# Partition rules for the native TextEncoder: vocab-sharded embedding,
# fused qkv projection column-parallel (its [W, 3W] kernel's output dim
# concatenates q|k|v, each head-aligned, so sharding the last dim over
# tp keeps whole heads on one shard as long as tp divides heads), out
# and mlp_2 row-parallel. Specs right-align (parallel/partition.py).
from ..parallel.partition import DtypePolicy as _DtypePolicy, \
    register_partition_rules as _register_partition_rules

_register_partition_rules("TextEncoder", [
    (r"embed/embedding", ("tp", None)),
    (r"(ln_1|ln_2)/(scale|bias)", ()),
    (r"(^|/)ln/(scale|bias)", ()),
    (r"qkv/kernel", (None, "tp")),
    (r"qkv/bias", ("tp",)),
    (r"out/kernel", ("tp", None)),
    (r"out/bias", ()),
    (r"mlp_1/kernel", (None, "tp")),
    (r"mlp_1/bias", ("tp",)),
    (r"mlp_2/kernel", ("tp", None)),
    (r"mlp_2/bias", ()),
],
    # bf16 compute / fp32 storage+accum, batch-sharded activations at
    # block boundaries (same chip defaults as the BertEncoder set)
    dtype_policy=_DtypePolicy(param_dtype="float32",
                              compute_dtype="bfloat16",
                              grad_accum_dtype="float32"),
    activation_spec=("dp",))


def make_attention_fn(impl: str = "dense", mesh=None, axis: str = "sp",
                      block_size: int | None = None,
                      causal: bool = False) -> Callable:
    """Resolve an attention implementation by name.

    ``ring``/``ulysses`` need a mesh whose ``axis`` shards the sequence;
    the returned fn expects its [B, H, T, D] inputs sharded accordingly
    (shard with ``NamedSharding(mesh, P(None, None, axis, None))``).

    ``causal``: lower-triangular masking (the LM/decoder pattern),
    supported by every implementation — the sharded flash variants
    pass their shards' (traced) global position offsets into the
    kernel's position mask."""
    if impl == "dense":
        return functools.partial(_dense_attention, causal=causal)
    if impl == "pallas":
        from .pallas_attention import flash_attention
        return lambda q, k, v, m=None: flash_attention(
            q, k, v, key_mask=m, block_k=block_size, causal=causal)
    if impl == "blockwise":
        from ..parallel.ring_attention import blockwise_attention
        return lambda q, k, v, m=None: blockwise_attention(
            q, k, v, block_size=block_size or 512, key_mask=m,
            causal=causal)
    if impl in ("ring", "ring_flash"):
        from ..parallel.ring_attention import make_ring_attention
        if mesh is None:
            raise ValueError("ring attention needs a mesh")
        return make_ring_attention(
            mesh, causal=causal, axis=axis,
            local_impl="flash" if impl == "ring_flash" else "blockwise")
    if impl in ("ulysses", "ulysses_flash"):
        from ..parallel.ulysses import make_ulysses_attention
        if mesh is None:
            raise ValueError("ulysses attention needs a mesh")
        return make_ulysses_attention(
            mesh, axis=axis, causal=causal,
            local_impl="flash" if impl == "ulysses_flash"
            else "blockwise")
    raise ValueError(f"unknown attention impl {impl!r}; expected "
                     "dense|pallas|blockwise|ring|ring_flash|ulysses|"
                     "ulysses_flash")


class TextEncoderFeaturizer(Transformer, HasInputCol, HasOutputCol,
                            BasicLogging):
    """Pipeline stage: tokenized text → pooled transformer embeddings.

    The text counterpart of ``ImageFeaturizer`` (reference
    ``image/ImageFeaturizer.scala:40-197`` — there is no reference text
    transformer; SURVEY §5 marks this the framework's long-context
    extension). Rows are token-id sequences; they are padded to the
    batch max (pad id 0 is masked out of the mean-pool). For sequences
    beyond one device's memory, pass ``attentionImpl="ring"`` (or
    ``"ulysses"``) and a mesh.
    """

    attentionImpl = Param("attentionImpl",
                          "dense|pallas|blockwise|ring|ring_flash|ulysses|"
                          "ulysses_flash",
                          TC.toString, default="dense", has_default=True)
    seqChunk = Param("seqChunk", "pad sequence length to a multiple of "
                     "this (ring/ulysses need the sp-axis size to "
                     "divide T)", TC.toInt, default=128, has_default=True)
    vocabSize = Param("vocabSize", "embedding vocabulary", TC.toInt,
                      default=32768, has_default=True)
    width = Param("width", "model width", TC.toInt, default=256,
                  has_default=True)
    depth = Param("depth", "encoder blocks", TC.toInt, default=4,
                  has_default=True)
    heads = Param("heads", "attention heads (must divide width)",
                  TC.toInt, default=8, has_default=True)
    seed = Param("seed", "init seed", TC.toInt, default=0,
                 has_default=True)
    model = ComplexParam(
        "model", "explicit LoadedModel text encoder — PRETRAINED "
        "weights (e.g. dl.pretrain + the zoo); overrides the "
        "width/depth/… params with the loaded architecture",
        default=None, has_default=True)
    modelName = Param(
        "modelName", "zoo text-model name to resolve through "
        "ModelDownloader (empty = random init from the width/depth "
        "params)", TC.toString, default="", has_default=True)
    quantize = Param(
        "quantize", "embed through the int8 post-training-quantized "
        "path (models.quantize_text_encoder: dense layers int8, "
        "attention bf16/f32 — 2x MXU rate on v5e); plain TextEncoder "
        "with dense attention only", TC.toBoolean, default=False,
        has_default=True)

    # class-level fallbacks: the serializer reconstructs stages without
    # running __init__ (meshes are runtime wiring, not persisted state)
    _mesh = None
    _cache = None

    def __init__(self, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(inputCol="tokens", outputCol="features")
        self._mesh = mesh
        self._cache = None

    def _encoder(self):
        if self._cache is None:
            attn = make_attention_fn(self.get("attentionImpl"),
                                     mesh=self._mesh)
            loaded = self.get("model")
            if loaded is None and self.get("modelName"):
                from ..models import ModelDownloader
                # an explicitly named zoo model must fail loud when its
                # checkpoint is missing — silently substituting random
                # weights behind a "pretrained" param would quietly
                # drop quality to the random-init floor
                loaded = ModelDownloader().download_by_name(
                    self.get("modelName"), allow_random_init=False)
            if loaded is not None:
                # pretrained path (the ImageFeaturizer pattern,
                # ``ImageFeaturizer.scala:81-85``): rebuild the loaded
                # architecture with the REQUESTED attention impl —
                # attention has no params, so the weights are identical
                lm = loaded.module
                if not hasattr(lm, "vocab"):
                    raise TypeError(
                        f"model {getattr(loaded.schema, 'name', '?')!r} "
                        "is not a text encoder (register text entries "
                        "with models.register_text_encoder)")
                kw = dict(vocab=lm.vocab, width=lm.width,
                          depth=lm.depth, heads=lm.heads,
                          mlp_dim=lm.mlp_dim, max_len=lm.max_len,
                          dtype=lm.dtype, attention_fn=attn)
                if hasattr(lm, "type_vocab"):   # ingested BertEncoder
                    kw.update(type_vocab=lm.type_vocab,
                              pooler=lm.pooler)
                # rebuild the SAME architecture (TextEncoder or an
                # ingested BertEncoder) with the requested attention
                module = type(lm)(**kw)
                variables = loaded.variables
            else:
                width, heads = self.get("width"), self.get("heads")
                if width % (2 * heads) != 0:
                    raise ValueError(
                        f"width={width} must be a multiple of 2*heads "
                        f"(heads={heads}): heads split the width and the "
                        "sinusoidal position encoding needs an even "
                        "width")
                module = TextEncoder(vocab=self.get("vocabSize"),
                                     width=width, heads=heads,
                                     depth=self.get("depth"),
                                     attention_fn=attn)
                rng = jax.random.PRNGKey(self.get("seed"))
                dummy = jnp.zeros((1, self.get("seqChunk")), jnp.int32)
                variables = module.init(rng, dummy, False)
            if self.get("quantize"):
                from ..models.quantize import quantize_text_encoder
                if type(module) is not TextEncoder:
                    raise ValueError(
                        "quantize=True supports plain TextEncoder "
                        f"models only (got {type(module).__name__})")
                qf, qp = quantize_text_encoder(
                    module, {"params": variables["params"]})
                apply = jax.jit(lambda v, x: qf(v["params"], x))
                variables = {"params": qp}
            else:
                apply = jax.jit(
                    lambda v, x: module.apply(v, x, False)["pooled"])
            self._cache = (apply, variables)
        return self._cache

    def _transform(self, df):
        with self.log_call("transform"):
            return self._transform_impl(df)

    def _transform_impl(self, df):
        apply, variables = self._encoder()
        rows = list(df[self.get("inputCol")])
        chunk = self.get("seqChunk")
        T = max((len(r) for r in rows), default=1)
        T = -(-T // chunk) * chunk
        ids = np.zeros((len(rows), T), np.int32)
        for i, r in enumerate(rows):
            ids[i, :len(r)] = np.asarray(r, np.int32)

        n_real = len(rows)
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            axes = dict(self._mesh.shape)
            dp = int(axes.get("dp", 1))
            if dp > 1:
                # data-parallel embedding: rows pad to the dp shard
                # count (pad rows are all-pad-id, masked out of
                # attention and the mean pool anyway) and split over
                # the dp axis — every local device embeds its slice of
                # the batch. pad_rows preserves the int32 id dtype.
                from ..parallel.sharding import pad_rows
                ids, _ = pad_rows(ids, dp, pad_value=0)
            # sequence stays sharded over sp when the mesh carries that
            # axis (the ring/ulysses long-context contract); a dp-only
            # mesh replicates the sequence dim
            sp = "sp" if int(axes.get("sp", 1)) > 1 else None
            spec = P("dp" if dp > 1 else None, sp)
            ids_dev = jax.device_put(
                jnp.asarray(ids), NamedSharding(self._mesh, spec))
        else:
            ids_dev = jnp.asarray(ids)
        pooled = np.asarray(apply(variables, ids_dev))[:n_real]
        # [n, W] numeric matrix, like ImageFeaturizer — feeds
        # TrainClassifier / Featurize without an object-column detour
        return df.with_column(self.get("outputCol"), pooled)
