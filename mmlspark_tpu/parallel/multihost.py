"""Pod-scale SPMD harness: N processes over DCN, one global mesh.

Everything below ``distributed_init`` in this package was built
single-process; this module is the data plane that makes the mesh span
hosts. It has two halves:

- the LAUNCHER (:func:`launch_pod`): spawn N scrubbed worker processes
  on this machine — each pinned to the CPU platform with a fixed count
  of virtual local devices, gloo CPU collectives enabled, and the
  ``MMLSPARK_TPU_COORDINATOR``/``NUM_PROCESSES``/``PROCESS_ID`` env
  triple set so :func:`~.mesh.distributed_init` wires the coordination
  service. This is the DCN-style test/bench topology: process
  boundaries are real (separate runtimes, cross-process collectives
  over gloo), only the wire is loopback. On a real pod the same worker
  body runs under the cluster launcher and the coordinator address is
  a real host:port.

- the WORKER surface (:func:`pod_mesh`, :func:`feed_process_local`,
  :func:`this_process`): build the dcn×ici global mesh and feed it
  per-host rows. The mesh convention: the OUTER axis spans processes
  (slow DCN hops — data parallelism lives here, gradients cross hosts
  once per step) and the INNER axis spans each process's local devices
  (fast ICI — tensor parallelism's per-matmul collectives stay
  on-host). Axes keep the framework-wide ``dp``/``tp`` NAMES so every
  registered partition rule applies unchanged; the dcn/ici split is
  the device LAYOUT under those names.

JAX-free at import (CI smoke-checks this) like the rest of the
package's light surface: the launcher is subprocess plumbing, and the
worker helpers import jax inside the call.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

RESULT_MARK = "MULTIHOST_RESULT "

DCN_AXIS = "dp"   # outer mesh axis: spans processes (DCN)
ICI_AXIS = "tp"   # inner mesh axis: spans local devices (ICI)


def free_port() -> int:
    """An OS-assigned free TCP port for the coordinator (the usual
    bind-to-0 race: good enough for a single-machine pod, where the
    window between close and the coordinator's bind is microseconds)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def worker_env(process_id: int, num_processes: int, coordinator: str,
               local_devices: int, extra_path: str | None = None) -> dict:
    """One pod worker's environment: the accelerator-tunnel scrub +
    CPU pin + virtual device count from ``core.utils.scrubbed_cpu_env``
    (a wedged tunnel hook would hang ``jax.devices()`` in every
    worker), plus the coordination triple ``distributed_init`` reads
    and the gloo CPU-collectives switch (belt to the config-level
    braces in ``compat.enable_cpu_multiprocess_collectives`` — either
    alone suffices, both together survive config-API drift)."""
    from ..core.utils import scrubbed_cpu_env
    env = scrubbed_cpu_env(local_devices, extra_path)
    env["MMLSPARK_TPU_COORDINATOR"] = coordinator
    env["MMLSPARK_TPU_NUM_PROCESSES"] = str(num_processes)
    env["MMLSPARK_TPU_PROCESS_ID"] = str(process_id)
    env["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = "gloo"
    # The persistent XLA compile cache is poison on a multi-process CPU
    # pod: a worker that HITS the cache and deserializes an executable
    # whose program embeds gloo collectives segfaults at boot (observed
    # deterministically: rank 0 SIGSEGV on every cache-hit run of a
    # program a previous pod compiled; cold compiles of the same
    # program always pass). Workers always compile fresh — the AOT
    # store (core/aot.py), not the jax cache, is the sanctioned warm
    # path on a pod.
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env["JAX_ENABLE_COMPILATION_CACHE"] = "false"
    return env


def launch_pod(target: str, *, num_processes: int = 2,
               local_devices: int = 4, args: dict | None = None,
               timeout: float = 300.0,
               extra_path: str | None = None) -> list[dict]:
    """Run ``target`` (a ``"pkg.module:function"`` dotted path) in
    ``num_processes`` scrubbed workers over a loopback coordinator.

    Each worker boots jax, calls ``distributed_init`` (env-driven),
    invokes the target with ``args`` (one JSON-serializable dict), and
    prints its returned dict on a ``MULTIHOST_RESULT`` line; the
    launcher collects them rank-ordered. Any worker failing (or the
    pod exceeding ``timeout`` — everything is killed, no orphan
    coordinator) raises RuntimeError carrying every worker's log tail,
    so a wedged collective reports a cause instead of hanging CI.
    """
    if ":" not in target:
        raise ValueError(
            f"target must be 'module:function', got {target!r}")
    coordinator = f"127.0.0.1:{free_port()}"
    payload = json.dumps(args or {})
    procs: list[subprocess.Popen] = []
    deadline = time.monotonic() + timeout
    try:
        for rank in range(num_processes):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "mmlspark_tpu.parallel.multihost",
                 target, payload],
                env=worker_env(rank, num_processes, coordinator,
                               local_devices, extra_path),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs: list[str] = []
        for proc in procs:
            left = deadline - time.monotonic()
            try:
                out, _ = proc.communicate(timeout=max(left, 0.1))
            except subprocess.TimeoutExpired:
                for p in procs:
                    p.kill()
                out, _ = proc.communicate()
                raise RuntimeError(
                    f"multihost pod timed out after {timeout:.0f}s; "
                    f"rank {len(outs)} tail:\n{out[-2000:]}")
            outs.append(out or "")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    results: list[dict] = []
    for rank, (proc, out) in enumerate(zip(procs, outs)):
        parsed = None
        for line in reversed(out.splitlines()):
            if line.startswith(RESULT_MARK):
                parsed = json.loads(line[len(RESULT_MARK):])
                break
        if proc.returncode != 0 or parsed is None:
            tails = "\n".join(
                f"--- rank {r} (rc={p.returncode}) ---\n{o[-2000:]}"
                for r, (p, o) in enumerate(zip(procs, outs)))
            raise RuntimeError(
                f"multihost worker rank {rank} failed "
                f"(rc={proc.returncode}, "
                f"result={'present' if parsed else 'missing'}):\n{tails}")
        results.append(parsed)
    return results


# ------------------------------------------------------ worker surface

def this_process() -> tuple[int, int]:
    """(process_index, process_count) of the live runtime."""
    import jax
    return int(jax.process_index()), int(jax.process_count())


def pod_mesh(data_axis: str = DCN_AXIS, model_axis: str = ICI_AXIS,
             devices=None):
    """The dcn×ici global mesh: ``(process_count, local_device_count)``
    with the OUTER axis walking processes (DCN) and the INNER axis
    walking each process's devices (ICI). Devices sort process-major
    explicitly rather than trusting enumeration order — the outer axis
    spanning DCN is the whole point, and a device order that
    interleaved processes would silently put per-matmul tp collectives
    on the slow links."""
    import jax
    import numpy as np
    from jax.sharding import Mesh
    devices = list(jax.devices() if devices is None else devices)
    devices.sort(key=lambda d: (getattr(d, "process_index", 0), d.id))
    nproc = len({getattr(d, "process_index", 0) for d in devices})
    if len(devices) % nproc:
        raise ValueError(
            f"{len(devices)} devices over {nproc} processes is ragged "
            "— every pod worker must contribute the same device count")
    arr = np.asarray(devices).reshape(nproc, len(devices) // nproc)
    return Mesh(arr, (data_axis, model_axis))


def feed_process_local(mesh, local_rows, axis: str = DCN_AXIS):
    """This process's rows → one global array batch-sharded over
    ``axis``. Every process calls this with ITS shard of the global
    batch (rank-ordered: global row ``i`` lives on the process whose
    slice covers it); the result is what the pjit'd train step and the
    dp-sharded fused serving segment take as input. Thin sugar over
    ``compat.make_array_from_process_local_data`` with the pod's
    batch-over-DCN convention baked in."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from .compat import make_array_from_process_local_data
    return make_array_from_process_local_data(
        NamedSharding(mesh, P(axis)), local_rows)


def fleet_result(extra: dict | None = None) -> dict:
    """The standard MULTIHOST_RESULT fleet envelope: this rank's index
    plus its prefix-filtered registry snapshot (and device-memory
    stats when the backend reports them), ready for
    ``obs.fleet.ingest_pod_results`` on the launcher side — the push
    half of pod-scale metric federation rides the result channel the
    harness already has."""
    from ..obs.fleet import local_fleet_snapshot
    from ..obs.memory import memory_profiler
    memory_profiler.update()      # mem_hbm_* into the snapshot, if any
    idx, _ = this_process()
    out = {"process": idx, "snapshot": local_fleet_snapshot()}
    if extra:
        out.update(extra)
    return out


def _worker_main(argv: list[str]) -> int:
    """``python -m mmlspark_tpu.parallel.multihost module:fn json`` —
    the body every :func:`launch_pod` worker runs."""
    target, payload = argv[0], json.loads(argv[1] if len(argv) > 1
                                          else "{}")
    mod_name, fn_name = target.split(":", 1)
    from .compat import enable_cpu_multiprocess_collectives
    if (os.environ.get("JAX_PLATFORMS") or "").startswith("cpu"):
        enable_cpu_multiprocess_collectives()
    from .mesh import distributed_init
    distributed_init()
    import importlib
    fn = getattr(importlib.import_module(mod_name), fn_name)
    out = fn(payload) or {}
    print(RESULT_MARK + json.dumps(out), flush=True)
    import jax
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.exit(_worker_main(sys.argv[1:]))
