"""Partition-rule engine: regex rules over named parameters → PartitionSpec.

The fmengine ``match_partition_rules`` pattern (SNIPPETS.md [2]) made
TPU-native: a model ships a SMALL ordered list of ``(regex, spec)``
rules instead of hand-annotating every leaf, and the engine walks any
pytree of named parameters — a bare flax params dict, a full
``dl.train.TrainState`` (optax optimizer states nest the param tree, so
the same rules match ``.../mu/block0/qkv/kernel``), or anything else
with string-keyed paths — producing the spec pytree that ``jax.jit``'s
``in_shardings``/``out_shardings`` and :func:`shard_params` consume.

Semantics:

- **first match wins** — rules are ordered, ``re.search`` over the
  ``/``-joined leaf path; put specific rules before general ones.
- **scalars replicate** — 0-d and single-element leaves never match a
  rule (nothing to shard).
- **specs are right-aligned** — a rule spec ``("tp",)`` places ``tp``
  on the LAST dim, left-padding with ``None`` to the leaf's rank. Scan
  stacking and microbatching PREPEND axes, so one rule written for the
  unstacked layer also covers its ``lax.scan``-stacked twin
  ``[L, in, out]``.
- **unmatched leaves replicate LOUDLY** — counted in the process-wide
  obs registry (``parallel_unmatched_leaves_total``) and warned once
  per path; pass ``on_unmatched="error"`` to make it fatal (what the
  per-model rule-set tests do).
- matched rules are counted per-pattern in
  ``parallel_rule_match_total{rule=...}``.

Sharding decisions and dtype decisions are the same knob seen from two
sides (mixed-precision findings of arXiv:2008.01040), so the dtype half
lives here too: a :class:`DtypePolicy` names the param / compute /
grad-accumulation dtypes and is applied by :func:`shard_params` in the
same pass that places the leaves.

This module imports NO JAX at module scope (CI smoke-checks that): rule
sets register at model-definition import time on machines with no
device, and specs are plain tuples until a function that actually
needs ``jax.sharding`` runs.
"""

from __future__ import annotations

import dataclasses
import re
import warnings
from typing import Any, Sequence

from ..obs import registry as _obs

_m_rule_match = _obs.counter(
    "parallel_rule_match_total",
    "partition-rule hits while matching param trees, by rule pattern")
_m_unmatched = _obs.counter(
    "parallel_unmatched_leaves_total",
    "param leaves no partition rule matched (loud replicated fallback)")
_m_demoted = _obs.counter(
    "parallel_spec_demoted_total",
    "matched specs demoted to fewer axes because a dim does not divide "
    "the mesh axis, by axis")

# rule: (regex over the /-joined leaf path, spec entries right-aligned
# to the leaf's trailing dims; each entry None | axis name | tuple of
# axis names)
PartitionRule = tuple[str, tuple]


@dataclasses.dataclass(frozen=True)
class DtypePolicy:
    """Param / compute / grad-accumulation dtypes, named as strings so
    the policy (like the rules it rides beside) is constructible with
    no JAX import. ``None`` entries mean "leave as is". Casts apply to
    floating leaves ONLY — integer ids, bin indices, bool masks and
    step counters pass through untouched (the ``pad_rows`` dtype
    contract, applied to casting)."""
    param_dtype: str | None = "float32"
    compute_dtype: str | None = "bfloat16"
    grad_accum_dtype: str | None = "float32"

    def _cast(self, tree, dtype_name: str | None):
        if dtype_name is None:
            return tree
        import jax
        import jax.numpy as jnp
        dtype = jnp.dtype(dtype_name)

        def one(leaf):
            arr = jnp.asarray(leaf)
            if jnp.issubdtype(arr.dtype, jnp.floating):
                return arr.astype(dtype)
            return arr
        return jax.tree.map(one, tree)

    def cast_params(self, tree):
        """Storage dtype for parameters (and optimizer moments)."""
        return self._cast(tree, self.param_dtype)

    def cast_compute(self, tree):
        """Activation/input dtype for the forward/backward."""
        return self._cast(tree, self.compute_dtype)

    def cast_grad_accum(self, tree):
        """Dtype of the gradient accumulator under microbatching."""
        return self._cast(tree, self.grad_accum_dtype)


# ---------------------------------------------------------------- paths

def _key_str(key) -> str:
    """One path component as a bare name (no brackets/dots), so rules
    read ``block0/qkv/kernel`` whatever node types the tree mixes."""
    for attr in ("key", "name", "idx"):
        if hasattr(key, attr):
            return str(getattr(key, attr))
    return str(key)


def named_leaves(tree, sep: str = "/"):
    """``[(path, leaf), ...]`` with ``sep``-joined string paths — dict
    keys, dataclass/NamedTuple fields and sequence indices all render
    as bare names (``0/mu/block0/qkv/kernel``)."""
    from jax.tree_util import tree_flatten_with_path
    flat, _ = tree_flatten_with_path(tree)
    return [(sep.join(_key_str(k) for k in path), leaf)
            for path, leaf in flat]


def _tree_map_with_name(fn, tree, sep: str = "/"):
    """tree_map whose fn receives (path_name, leaf)."""
    import jax
    from jax.tree_util import tree_flatten_with_path
    flat, treedef = tree_flatten_with_path(tree)
    out = [fn(sep.join(_key_str(k) for k in path), leaf)
           for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, out)


# ------------------------------------------------------------- matching

def _fit_spec(spec: Sequence, ndim: int, name: str):
    """Right-align a rule spec to a leaf's rank (left-pad with None)."""
    spec = tuple(spec)
    if len(spec) > ndim:
        raise ValueError(
            f"partition rule spec {spec} has more entries than leaf "
            f"{name!r} has dims ({ndim})")
    return (None,) * (ndim - len(spec)) + spec


def match_partition_rules(rules: Sequence[PartitionRule], params, *,
                          on_unmatched: str = "replicate",
                          _count: bool = True):
    """Pytree of ``PartitionSpec`` congruent with ``params``.

    ``rules``: ordered ``(regex, spec)`` pairs — first ``re.search``
    match on the ``/``-joined leaf path wins; the spec right-aligns to
    the leaf's rank. Scalar / single-element leaves always replicate.
    ``on_unmatched``: ``"replicate"`` (loud fallback: warning + the
    ``parallel_unmatched_leaves_total`` counter) or ``"error"``.
    """
    import numpy as np
    from jax.sharding import PartitionSpec as P
    if on_unmatched not in ("replicate", "error"):
        raise ValueError(f"on_unmatched={on_unmatched!r}")
    compiled = [(re.compile(rule), rule, spec) for rule, spec in rules]

    def spec_of(name: str, leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) <= 1:
            return P()
        for rx, rule, spec in compiled:
            if rx.search(name) is not None:
                if _count:
                    _m_rule_match.inc(1, rule=rule)
                return P(*_fit_spec(spec, len(shape), name))
        if on_unmatched == "error":
            raise ValueError(
                f"no partition rule matched param {name!r} "
                f"(shape {tuple(shape)})")
        if _count:
            _m_unmatched.inc(1)
        warnings.warn(
            f"no partition rule matched param {name!r} "
            f"(shape {tuple(shape)}); replicating it — add a rule "
            "(or register one next to the model) to silence this",
            stacklevel=2)
        return P()

    return _tree_map_with_name(spec_of, params)


def to_shardings(mesh, params, specs):
    """Spec pytree → ``NamedSharding`` pytree for a CONCRETE mesh.

    ``jax.device_put`` (unlike a jit-internal sharding constraint)
    refuses dims that don't divide their mesh axes, so any spec entry
    whose axis product does not divide the leaf dim is demoted to
    ``None`` here — counted per-axis in
    ``parallel_spec_demoted_total{axis=...}`` so a silently-replicated
    embedding table shows up on the scrape, not in an OOM.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def one(leaf, spec):
        shape = getattr(leaf, "shape", ())
        if len(tuple(spec)) > len(shape):
            # same loud contract _fit_spec gives the rules path — a
            # mis-ranked hand spec must name itself, not IndexError
            raise ValueError(
                f"spec {tuple(spec)} has more entries than the leaf "
                f"has dims (shape {tuple(shape)})")
        # right-align short specs, the same convention _fit_spec gives
        # rule specs (scan stacking prepends axes; a hand-written short
        # spec must not silently mean something different here)
        entries = [None] * (len(shape) - len(tuple(spec))) + list(spec)
        for i, entry in enumerate(entries):
            if entry is None:
                continue
            axes = (entry,) if isinstance(entry, str) else tuple(entry)
            # an axis the mesh does not carry (e.g. a tp rule against a
            # dp-only local_mesh) demotes exactly like a non-divisible
            # dim — replicate that dim, loudly, instead of KeyError
            if any(a not in mesh.shape for a in axes):
                _m_demoted.inc(1, axis=",".join(axes))
                entries[i] = None
                continue
            size = 1
            for a in axes:
                size *= mesh.shape[a]
            if shape[i] % size:
                _m_demoted.inc(1, axis=",".join(axes))
                entries[i] = None
        return NamedSharding(mesh, P(*entries))

    return jax.tree.map(one, params, specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_params(mesh, params, specs=None, *, rules=None,
                 dtype_policy: DtypePolicy | None = None,
                 on_unmatched: str = "replicate"):
    """Place a param pytree onto ``mesh`` per rules/specs (+ optional
    dtype policy). Returns ``(sharded_params, shardings)`` — the
    shardings are what a pjit'd step passes as in/out_shardings so the
    placement survives updates without re-layout.
    """
    import jax
    if specs is None:
        if rules is None:
            raise ValueError("pass specs= or rules=")
        specs = match_partition_rules(rules, params,
                                      on_unmatched=on_unmatched)
    if dtype_policy is not None:
        params = dtype_policy.cast_params(params)
    shardings = to_shardings(mesh, params, specs)
    import numpy as np
    if len({getattr(d, "process_index", 0)
            for d in np.asarray(mesh.devices).flat}) > 1:
        # multi-process mesh: device_put cannot place a host value onto
        # devices other processes own. Every process holds the same
        # full host value (seeded init — the multihost contract) and
        # make_array_from_callback materializes only the addressable
        # shards from it, per leaf.
        def place(v, s):
            host = np.asarray(v)
            return jax.make_array_from_callback(
                host.shape, s, lambda idx, host=host: host[idx])
        placed = jax.tree.map(place, params, shardings)
    else:
        # ONE batched transfer for the whole pytree: device_put accepts
        # congruent value/sharding trees, and a TrainState has hundreds
        # of leaves (optax moments triple the param count) — per-leaf
        # calls would serialize that many host->device transfers
        placed = jax.device_put(params, shardings)
    return placed, shardings


def gather_params(params):
    """Sharded pytree → fully-gathered HOST numpy pytree (checkpoint
    publication, the zoo's consumption format). The inverse of
    :func:`shard_params` up to dtype policy.

    Single-process only: a leaf whose shards span processes raises
    loudly here — ``device_get`` of a non-addressable array would
    otherwise hang or crash deep inside the runtime. Cross-host
    gathering is a collective; use ``compat.process_allgather`` (every
    process gets the full value) instead."""
    import jax
    import numpy as np

    def one(leaf):
        if not getattr(leaf, "is_fully_addressable", True):
            raise RuntimeError(
                "gather_params on a multi-process array: this leaf's "
                "shards live on devices other processes own, so a "
                "host gather here is a cross-host collective, not a "
                "device_get. Use parallel.compat.process_allgather "
                "(all processes must call it) or keep the state "
                "sharded.")
        return np.asarray(jax.device_get(leaf))
    return jax.tree.map(one, params)


# ------------------------------------------------- per-model rule sets

# name -> (rules, dtype policy, activation spec). The activation spec
# is LEFT-aligned (PartitionSpec semantics: entry i constrains dim i —
# activations are batch-leading, so ("dp",) means "shard the batch
# dim") unlike the right-aligned WEIGHT rules above (weights are
# feature-trailing).
_RULE_SETS: dict[str, tuple[tuple[PartitionRule, ...],
                            DtypePolicy | None,
                            tuple | None]] = {}


def register_partition_rules(name: str, rules: Sequence[PartitionRule],
                             dtype_policy: DtypePolicy | None = None,
                             activation_spec: Sequence | None = None
                             ) -> None:
    """Register a model family's rule set (called next to the model
    definition, at import time — no JAX needed). Re-registration
    overwrites: the model file is the single source of truth.

    ``dtype_policy``: the family's chip-tuned default (bf16 compute,
    fp32 params/accum) — what ``partition_train_state`` /
    ``make_partitioned_train_step`` callers pick up via
    :func:`dtype_policy_for`. ``activation_spec``: the LEFT-aligned
    PartitionSpec entries :func:`constrain_activation` applies at the
    model's block boundaries (``("dp",)`` = batch-shard activations /
    remat buffers; plain data until a mesh is in scope)."""
    _RULE_SETS[name] = (tuple(rules), dtype_policy,
                        tuple(activation_spec)
                        if activation_spec is not None else None)


def partition_rules_for(name: str) -> tuple[PartitionRule, ...]:
    if name not in _RULE_SETS:
        raise KeyError(
            f"no partition rules registered for {name!r}; known: "
            f"{sorted(_RULE_SETS)}")
    return _RULE_SETS[name][0]


def dtype_policy_for(name: str) -> DtypePolicy | None:
    if name not in _RULE_SETS:
        raise KeyError(
            f"no partition rules registered for {name!r}; known: "
            f"{sorted(_RULE_SETS)}")
    return _RULE_SETS[name][1]


def activation_spec_for(name: str) -> tuple | None:
    if name not in _RULE_SETS:
        raise KeyError(
            f"no partition rules registered for {name!r}; known: "
            f"{sorted(_RULE_SETS)}")
    return _RULE_SETS[name][2]


def constrain_activation(x, model: str):
    """Apply ``model``'s registered activation spec to a block-boundary
    value via ``compat.with_sharding_constraint``. No-op when the model
    registers no spec, or when no mesh is in scope (single-device runs
    and un-partitioned tests see the exact unconstrained computation) —
    so model ``__call__`` bodies call this unconditionally without mesh
    plumbing. The partitioned train steps enter ``with mesh:`` around
    their body, which is what puts a mesh in scope here."""
    ent = _RULE_SETS.get(model)
    if ent is None or ent[2] is None:
        return x
    from .compat import with_sharding_constraint
    return with_sharding_constraint(x, ent[2])


def registered_rule_sets() -> list[str]:
    return sorted(_RULE_SETS)
