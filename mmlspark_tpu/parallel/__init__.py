"""Distributed backend: device meshes, collectives, sharding helpers, and
sequence parallelism.

This package is the TPU-native replacement for the reference's entire L3
"distributed coordination / comm" layer (SURVEY §2.13): the driver
ServerSocket rendezvous (``lightgbm/LightGBMUtils.scala:119-188``), the
LightGBM socket allreduce (``lightgbm/TrainUtils.scala:609-625``), and the VW
spanning-tree AllReduce (``vw/VowpalWabbitBase.scala:434-461``) all collapse
into a ``jax.sharding.Mesh`` + XLA collectives over ICI/DCN:

- rendezvous        → :func:`distributed_init` (JAX coordination service)
- socket allreduce  → :func:`allreduce` / ``psum`` inside ``shard_map``
- spanning tree     → the same (XLA picks the reduction topology)
- empty partitions  → padding masks (:func:`pad_rows`), never ragged shards
"""

from .mesh import (MeshSpec, build_mesh, distributed_init, local_mesh,
                   mesh_shape_for)
from .collectives import (allgather, allreduce, barrier, psum_scatter,
                          ring_permute)
from .sharding import (batch_sharding, pad_rows, replicated, shard_batch,
                       unpad_rows)
from .ring_attention import ring_attention, blockwise_attention
from .ulysses import make_ulysses_attention
from .pipeline import (pipeline_apply, pipeline_encode,
                       pipeline_train_1f1b,
                       pipeline_train_encoder_1f1b, make_pipeline_mlp)

__all__ = [
    "make_ulysses_attention",
    "MeshSpec", "build_mesh", "distributed_init", "local_mesh",
    "mesh_shape_for", "allgather", "allreduce", "barrier", "psum_scatter",
    "ring_permute", "batch_sharding", "pad_rows", "replicated",
    "shard_batch", "unpad_rows", "ring_attention", "blockwise_attention",
    "pipeline_apply", "pipeline_encode", "pipeline_train_1f1b",
    "pipeline_train_encoder_1f1b", "make_pipeline_mlp",
]
