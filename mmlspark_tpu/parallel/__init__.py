"""Distributed backend: device meshes, collectives, sharding helpers,
partition rules, and sequence parallelism.

This package is the TPU-native replacement for the reference's entire L3
"distributed coordination / comm" layer (SURVEY §2.13): the driver
ServerSocket rendezvous (``lightgbm/LightGBMUtils.scala:119-188``), the
LightGBM socket allreduce (``lightgbm/TrainUtils.scala:609-625``), and the VW
spanning-tree AllReduce (``vw/VowpalWabbitBase.scala:434-461``) all collapse
into a ``jax.sharding.Mesh`` + XLA collectives over ICI/DCN:

- rendezvous        → :func:`distributed_init` (JAX coordination service)
- socket allreduce  → :func:`allreduce` / ``psum`` inside ``shard_map``
- spanning tree     → the same (XLA picks the reduction topology)
- empty partitions  → padding masks (:func:`pad_rows`), never ragged shards
- per-model layout  → :func:`match_partition_rules` (regex rules →
  ``PartitionSpec``, ``partition.py``) + :func:`shard_params` /
  :func:`gather_params` with a :class:`DtypePolicy`

Import is LIGHT: ``partition`` and ``mesh`` are JAX-free at module
scope (rule sets register at model-import time on device-less
machines; the CI smoke imports ``mmlspark_tpu.parallel.partition``
with no JAX in ``sys.modules``). Everything that needs JAX —
collectives, sharding placement, ring/ulysses attention, pipeline
parallelism — loads lazily on first attribute access (PEP 562).
"""

from .mesh import (MeshSpec, build_mesh, distributed_init, local_mesh,
                   mesh_shape_for)
from .partition import (DtypePolicy, PartitionRule, activation_spec_for,
                        constrain_activation, dtype_policy_for,
                        gather_params, match_partition_rules,
                        named_leaves, partition_rules_for,
                        register_partition_rules, registered_rule_sets,
                        shard_params, to_shardings)

# attribute name → submodule that defines it; resolved (and cached in
# module globals) on first access so `import mmlspark_tpu.parallel`
# never drags in JAX
_LAZY = {
    "allgather": ".collectives", "allreduce": ".collectives",
    "barrier": ".collectives", "psum_scatter": ".collectives",
    "ring_permute": ".collectives",
    "batch_sharding": ".sharding", "pad_rows": ".sharding",
    "replicated": ".sharding", "shard_batch": ".sharding",
    "unpad_rows": ".sharding",
    # NOT "ring_attention": the function shares its submodule's name,
    # and the import system rebinds the package attr to the MODULE on
    # any `import ...parallel.ring_attention` — a lazy attr of that
    # name would be import-order dependent. The package-level name is
    # therefore deterministically the submodule (from-import falls back
    # to the submodule when the attr is absent); use
    # `make_ring_attention` / `ring_attention.ring_attention` for the
    # functions.
    "make_ring_attention": ".ring_attention",
    "blockwise_attention": ".ring_attention",
    # multihost harness: JAX-free at import like mesh/partition, but
    # routed lazily anyway — the harness is pod-bootstrap surface, not
    # something every `import mmlspark_tpu.parallel` needs resident
    "launch_pod": ".multihost", "pod_mesh": ".multihost",
    "feed_process_local": ".multihost", "worker_env": ".multihost",
    "make_ulysses_attention": ".ulysses",
    "pipeline_apply": ".pipeline", "pipeline_encode": ".pipeline",
    "pipeline_train_1f1b": ".pipeline",
    "pipeline_train_encoder_1f1b": ".pipeline",
    "make_pipeline_mlp": ".pipeline",
}

__all__ = [
    "make_ulysses_attention",
    "MeshSpec", "build_mesh", "distributed_init", "local_mesh",
    "mesh_shape_for", "allgather", "allreduce", "barrier", "psum_scatter",
    "ring_permute", "batch_sharding", "pad_rows", "replicated",
    "shard_batch", "unpad_rows", "make_ring_attention",
    "blockwise_attention",
    "pipeline_apply", "pipeline_encode", "pipeline_train_1f1b",
    "pipeline_train_encoder_1f1b", "make_pipeline_mlp",
    "DtypePolicy", "PartitionRule", "match_partition_rules",
    "named_leaves", "shard_params", "gather_params", "to_shardings",
    "register_partition_rules", "partition_rules_for",
    "dtype_policy_for", "activation_spec_for", "constrain_activation",
    "registered_rule_sets",
    "launch_pod", "pod_mesh", "feed_process_local", "worker_env",
]


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib
    value = getattr(importlib.import_module(target, __name__), name)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
