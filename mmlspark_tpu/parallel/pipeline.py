"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pp``
mesh axis.

No reference counterpart (SURVEY §2.14: PP absent there) — this is part of
the TPU-native extension that makes large in-framework models trainable.
Each device holds ONE stage's parameters; microbatches enter stage 0 and
activations flow around the ring by ``ppermute``, so at steady state every
stage computes a different microbatch each tick (the classic
(M + S - 1)-step schedule with bubble fraction (S-1)/(M+S-1)).

Stages must share activation shapes (uniform-width blocks), the usual
constraint for homogeneous pipeline demos.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from . import collectives as _coll
from .compat import shard_map as _shard_map


def pipeline_apply(mesh, stage_fn, stacked_params, microbatches,
                   *, axis: str = "pp", aux=None,
                   remat_stage: bool = False):
    """Run microbatches through S = mesh.shape[axis] pipeline stages.

    stage_fn(params_i, h) -> h'  applied by stage i; ``stacked_params`` has
    leading dim S (stage-major, sharded over ``axis``); ``microbatches``
    is [M, mb, ...] (replicated). Returns [M, mb, ...] outputs of the last
    stage.

    ``aux`` (optional, [M, ...] replicated) rides along with each
    microbatch: at tick t stage s is processing microbatch t-s, so the
    stage receives ``aux[t-s]`` and ``stage_fn(params_i, h, aux_mb)`` —
    attention key masks being the motivating case.

    DIFFERENTIABLE: the schedule is a ``lax.scan`` over ticks, so
    ``jax.grad`` runs a backward pipeline through the same ring
    (reversed ``ppermute``s) — pp is a trainable strategy like sp, the
    GPipe fwd+bwd schedule without 1F1B interleaving. ``remat_stage``
    recomputes each stage call in the backward instead of storing its
    activations (GPipe's memory trade; per-tick ``jax.checkpoint``).
    """
    S = int(mesh.shape[axis])
    M = microbatches.shape[0]
    T = M + S - 1
    run_stage = jax.checkpoint(stage_fn) if remat_stage else stage_fn

    def body(params_local, xs, aux_xs):
        params_local = jax.tree.map(lambda p: p[0], params_local)
        stage = _coll.axis_index(axis)
        h = jnp.zeros_like(xs[0])
        outs = jnp.zeros_like(xs)

        def tick(carry, t):
            h_in, outs = carry
            # stage 0 ingests microbatch t (while available)
            mb = jnp.clip(t, 0, M - 1)
            inject = jnp.where(stage == 0,
                               jnp.where(t < M, 1.0, 0.0), 0.0)
            h_cur = inject * xs[mb] + (1.0 - inject) * h_in
            if aux_xs is None:
                h_out = run_stage(params_local, h_cur)
            else:
                # the microbatch this stage is processing right now
                own = jnp.clip(t - stage, 0, M - 1)
                h_out = run_stage(params_local, h_cur, aux_xs[own])
            # last stage emits microbatch t-(S-1)
            emit_idx = jnp.clip(t - (S - 1), 0, M - 1)
            emit = (stage == S - 1) & (t >= S - 1)
            outs = jax.lax.cond(
                emit,
                lambda o: jax.lax.dynamic_update_slice(
                    o, h_out[None], (emit_idx,) + (0,) * h_out.ndim),
                lambda o: o, outs)
            # rotate activations forward around the ring
            perm = [(i, (i + 1) % S) for i in range(S)]
            h_next = _coll.ppermute(h_out, axis, perm)
            return (h_next, outs), None

        (_, outs), _ = jax.lax.scan(tick, (h, outs), jnp.arange(T))
        # every shard returns its buffer; only the last stage's is real —
        # broadcast it to all shards so the output is replicated
        last = _coll.allreduce(
            outs * (stage == S - 1).astype(outs.dtype), axis)
        return last

    if aux is None:
        return _shard_map(
            lambda p, x: body(p, x, None), mesh=mesh,
            in_specs=(P(axis), P()), out_specs=P(),
            check_vma=False)(stacked_params, microbatches)
    return _shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), P(), P()), out_specs=P(),
        check_vma=False)(stacked_params, microbatches, aux)


def pipeline_train_1f1b(mesh, stage_fn, loss_fn, stacked_params,
                        microbatches, targets, *, axis: str = "pp",
                        aux=None, extra_params=None,
                        return_input_grads: bool = False):
    """One 1F1B training step: (mean loss, stacked param grads).

    The GPipe route (``jax.grad`` through ``pipeline_apply``) stores one
    activation per tick across all M + S - 1 ticks — O(M) residuals per
    device. This schedule interleaves: the backward of microbatch m runs
    at stage s on tick ``m + 2(S-1) - s``, i.e. immediately after the
    loss for m is available at the last stage, so a stage holds at most
    2(S-1-s) in-flight activations — O(S), independent of M. Gradients
    ride a REVERSE ppermute ring in the same ``lax.scan`` that carries
    activations forward; each tick every stage runs one forward slot and
    one backward slot (recompute-style ``jax.vjp`` from the saved stage
    INPUT, so memory stays at the ring buffer). The FLOPs are ~4/3 of
    the sequential fwd+bwd (the extra forward inside the vjp), the
    classic 1F1B recompute trade.

    stage_fn(params_i, h[, aux_mb]) -> h'   as in ``pipeline_apply``.
    loss_fn(h_last, target_mb) -> scalar    (summed over microbatches,
    returned as the mean over M); with ``extra_params`` the signature
    becomes ``loss_fn(extra_params, h_last, target_mb)`` — an epilogue
    (e.g. LN + pooling + head) differentiates INSIDE the loss and its
    grads come back too.

    ``microbatches`` [M, mb, ...] replicated; ``targets`` any pytree of
    [M, ...] leaves (replicated) — indexed per microbatch;
    ``stacked_params`` stage-major over ``axis``.

    Returns ``(loss, grads)`` with ``grads`` stacked like
    ``stacked_params``. When ``extra_params`` is given or
    ``return_input_grads`` is set, returns ``(loss, grads, out)`` where
    ``out["extra_grads"]`` matches ``extra_params`` and
    ``out["input_grads"]`` is d(loss)/d(microbatches) — the hook that
    lets a replicated PROLOGUE (e.g. an embedding) train through its
    own ``jax.vjp`` outside the pipeline.
    """
    S = int(mesh.shape[axis])
    M = microbatches.shape[0]
    T = M + 2 * (S - 1)          # last backward: stage 0, tick M-1+2(S-1)
    K = max(2 * S, 2)            # activation ring slots (>= 2(S-1)+1)
    want_out = extra_params is not None or return_input_grads

    def body(params_stacked, xs, ys, aux_xs, extra):
        params_local = jax.tree.map(lambda p: p[0], params_stacked)
        stage = _coll.axis_index(axis)
        h0 = jnp.zeros_like(xs[0])
        ring = jnp.zeros((K,) + xs.shape[1:], xs.dtype)
        gacc = jax.tree.map(jnp.zeros_like, params_local)
        loss0 = jnp.zeros((), jnp.float32)
        eacc0 = jax.tree.map(jnp.zeros_like, extra) \
            if extra is not None else None
        dxs0 = jnp.zeros_like(xs) if return_input_grads else None

        def fwd(params, h, m):
            if aux_xs is None:
                return stage_fn(params, h)
            return stage_fn(params, h, aux_xs[jnp.clip(m, 0, M - 1)])

        def loss_at(e, o, m):
            tgt = jax.tree.map(lambda a: a[m], ys)
            if extra is None:
                return loss_fn(o, tgt)
            return loss_fn(e, o, tgt)

        def tick(carry, t):
            h_in, g_in, ring, gacc, loss, eacc, dxs = carry

            # ---- forward slot: stage s runs microbatch mf = t - s ----
            mf = t - stage
            f_valid = (mf >= 0) & (mf < M)
            inject = (stage == 0) & f_valid
            h_cur = jnp.where(inject, xs[jnp.clip(mf, 0, M - 1)], h_in)
            # save the stage INPUT for the recompute-vjp backward slot
            ring = jax.lax.cond(
                f_valid,
                lambda r: jax.lax.dynamic_update_slice(
                    r, h_cur[None],
                    (jnp.clip(mf, 0, M - 1) % K,) + (0,) * h_cur.ndim),
                lambda r: r, ring)
            h_out = fwd(params_local, h_cur, mf)

            # ---- backward slot: stage s runs microbatch mb ----------
            mb_idx = t - 2 * (S - 1) + stage
            b_valid = (mb_idx >= 0) & (mb_idx < M)
            m_safe = jnp.clip(mb_idx, 0, M - 1)
            h_saved = ring[m_safe % K]
            is_last = stage == S - 1

            # ONE recompute-vjp through the stage from its saved input;
            # the cotangent is either the locally-computed loss gradient
            # (last stage — the backward of m shares m's forward tick)
            # or the cotangent that just arrived on the reverse ring
            out_saved, vjp = jax.vjp(
                lambda p, h: fwd(p, h, m_safe), params_local, h_saved)
            if extra is not None:
                lval, (de, g_loss) = jax.value_and_grad(
                    lambda eo: loss_at(eo[0], eo[1], m_safe))(
                        (extra, out_saved))
            else:
                de = None
                lval, g_loss = jax.value_and_grad(
                    lambda o: loss_at(None, o, m_safe))(out_saved)
            dp, dh = vjp(jnp.where(is_last, g_loss, g_in))
            mask = b_valid
            gacc = jax.tree.map(
                lambda acc, g: acc + jnp.where(mask, g, 0), gacc, dp)
            if eacc is not None:
                emask = mask & is_last
                eacc = jax.tree.map(
                    lambda acc, g: acc + jnp.where(emask, g, 0),
                    eacc, de)
            loss = loss + jnp.where(
                mask & is_last, lval.astype(jnp.float32), 0.0)
            g_out = jnp.where(mask, dh, 0)
            if dxs is not None:
                # stage 0's dh IS d(loss)/d(xs[m]) — capture it for the
                # caller's prologue vjp
                wmask = mask & (stage == 0)
                dxs = jax.lax.dynamic_update_slice(
                    dxs, jnp.where(wmask, dh, dxs[m_safe])[None],
                    (m_safe,) + (0,) * dh.ndim)

            # ---- ring transport ------------------------------------
            h_next = _coll.ppermute(
                h_out, axis, [(i, (i + 1) % S) for i in range(S)])
            g_next = _coll.ppermute(
                g_out, axis, [(i, (i - 1) % S) for i in range(S)])
            return (h_next, g_next, ring, gacc, loss, eacc, dxs), None

        g0 = jnp.zeros_like(xs[0])
        (_, _, _, gacc, loss, eacc, dxs), _ = jax.lax.scan(
            tick, (h0, g0, ring, gacc, loss0, eacc0, dxs0),
            jnp.arange(T))
        # loss lives on the last stage only; grads are per-stage
        loss = _coll.allreduce(loss, axis) / M
        grads = jax.tree.map(lambda g: g[None] / M, gacc)
        outs = []
        if eacc is not None:
            # epilogue grads exist only on the last stage — share them
            outs.append(jax.tree.map(
                lambda g: _coll.allreduce(
                    jnp.where(stage == S - 1, g, 0), axis) / M, eacc))
        if dxs is not None:
            outs.append(_coll.allreduce(
                jnp.where(stage == 0, dxs, 0), axis) / M)
        return (loss, grads, *outs)

    n_outs = 2 + (extra_params is not None) + bool(return_input_grads)
    out_specs = (P(), P(axis)) + (P(),) * (n_outs - 2)
    if aux is None:
        res = _shard_map(
            lambda p, x, y, e: body(p, x, y, None, e), mesh=mesh,
            in_specs=(P(axis), P(), P(), P()), out_specs=out_specs,
            check_vma=False)(stacked_params, microbatches, targets,
                             extra_params)
    else:
        res = _shard_map(
            body, mesh=mesh, in_specs=(P(axis), P(), P(), P(), P()),
            out_specs=out_specs, check_vma=False)(
            stacked_params, microbatches, targets, aux, extra_params)
    if not want_out:
        return res[0], res[1]
    out: dict = {}
    idx = 2
    if extra_params is not None:
        out["extra_grads"] = res[idx]
        idx += 1
    if return_input_grads:
        out["input_grads"] = res[idx]
    return res[0], res[1], out


def _encoder_stages(module, params, N: int, S: int,
                    num_microbatches: int | None):
    """Shared stage-splitting for the encoder pipeline paths
    (``pipeline_encode`` and ``pipeline_train_encoder_1f1b``): checks
    depth % S, picks the microbatch count, stacks block params
    stage-major [S, L, ...], and builds the scanning stage_fn —
    honoring ``module.remat`` (per-block rematerialization) so the
    memory trade the user opted into survives the pipeline split."""
    from ..dl.text_encoder import EncoderBlock

    depth = module.depth
    if depth % S:
        raise ValueError(f"depth {depth} must divide into {S} stages")
    L = depth // S
    if num_microbatches is None:
        # the largest divisor of N that is <= 2*S (the classic
        # bubble-amortizing target) — any batch size is accepted
        M = next(m for m in range(min(2 * S, N), 0, -1) if N % m == 0)
    else:
        M = num_microbatches
        if N % M:
            raise ValueError(
                f"batch {N} must divide into num_microbatches={M}; "
                "pass a divisor of the batch size (or omit it for the "
                "automatic choice)")
    block_trees = [params[f"block{i}"] for i in range(depth)]
    # [S, L, ...] stage-major stack of block parameters
    stacked = jax.tree.map(
        lambda *leaves: jnp.stack(
            [jnp.stack(leaves[s * L:(s + 1) * L]) for s in range(S)]),
        *block_trees)
    block_cls = EncoderBlock
    if getattr(module, "remat", False):
        import flax.linen as nn
        block_cls = nn.remat(EncoderBlock)
    block = block_cls(module.heads, module.mlp_dim, module.width,
                      attention_fn=module.attention_fn,
                      dtype=module.dtype)

    def stage_fn(stage_params, h, mask_mb):
        def one(h, p):
            return block.apply({"params": p}, h, mask_mb), None
        return jax.lax.scan(one, h, stage_params)[0]

    return L, M, stacked, stage_fn


def pipeline_train_encoder_1f1b(mesh, module, variables, ids, targets,
                                loss_on_pooled, *,
                                num_microbatches: int | None = None,
                                axis: str = "pp"):
    """One 1F1B training step over a REAL ``TextEncoder``: returns
    ``(mean loss, grads)`` with ``grads`` matching
    ``variables["params"]`` exactly — embedding prologue, every block,
    and the LN epilogue all train, equal to the dense ``jax.grad``
    (asserted by test).

    Composition: the replicated embedding runs OUTSIDE the pipeline
    under its own ``jax.vjp`` (fed by the schedule's input cotangents),
    the depth blocks run as 1F1B stages, and the finalize epilogue +
    ``loss_on_pooled(pooled, target_mb) -> scalar`` differentiate
    inside the pipeline's loss slot via ``extra_params``.
    """
    S = int(mesh.shape[axis])
    N, Tn = ids.shape
    depth = module.depth
    params = variables["params"]
    L, M, stacked, stage_fn = _encoder_stages(module, params, N, S,
                                              num_microbatches)
    mb = N // M

    # replicated prologue under its own vjp — the pipeline returns
    # d(loss)/d(block inputs), which this closes over the embedding
    h, embed_vjp = jax.vjp(
        lambda p: module.apply({"params": p}, ids, method="embed_ids"),
        params)
    key_mask = ids != 0

    def loss_fn(extra, h_tokens, tgt):
        ids_mb, y_mb = tgt
        out = module.apply({"params": {"ln": extra["ln"]}}, h_tokens,
                           ids_mb, method="finalize")
        return loss_on_pooled(out["pooled"], y_mb)

    h_mb = h.reshape(M, mb, Tn, module.width)
    mask_mb = key_mask.reshape(M, mb, Tn)
    ids_mb = ids.reshape(M, mb, Tn)
    y_mb = jax.tree.map(
        lambda a: a.reshape((M, mb) + a.shape[1:]), targets)

    loss, stacked_grads, out = pipeline_train_1f1b(
        mesh, stage_fn, loss_fn, stacked, h_mb, (ids_mb, y_mb),
        axis=axis, aux=mask_mb, extra_params={"ln": params["ln"]},
        return_input_grads=True)

    # assemble the full-tree gradient: embedding (through the input
    # cotangents — already mean-normalized by the schedule), blocks
    # (unstacked), epilogue LN
    dx = out["input_grads"].reshape(N, Tn, module.width)
    grads = dict(embed_vjp(dx)[0])    # embed grads; zeros elsewhere
    grads["ln"] = jax.tree.map(
        lambda a, b: a + b, grads["ln"], out["extra_grads"]["ln"])
    for i in range(depth):
        grads[f"block{i}"] = jax.tree.map(
            lambda g, gi=i: g[gi // L, gi % L], stacked_grads)
    return loss, grads


def make_pipeline_mlp(width: int):
    """A uniform-width residual MLP block for pipeline demos/tests:
    params = (W [width, width], b [width])."""
    def stage_fn(params, h):
        W, b = params
        return h + jnp.tanh(h @ W + b)
    return stage_fn


def pipeline_encode(mesh, module, variables, ids, *,
                    num_microbatches: int | None = None,
                    axis: str = "pp", remat_stage: bool = False):
    """A REAL model through the pipeline: ``TextEncoder``'s depth
    EncoderBlocks split across the ``axis`` stages (depth % S == 0, each
    stage scanning depth/S blocks), embedding prologue and LN+pool
    epilogue replicated. Numerically equivalent to
    ``module.apply(variables, ids)`` (same blocks, same order; verified
    by test).

    ids [N, T] int32 with pad id 0; N must divide into the microbatch
    count (default M = 2·S, the classic bubble-amortizing choice).
    Returns the ``{"tokens", "pooled"}`` dict of the plain forward.
    """
    S = int(mesh.shape[axis])
    N, T = ids.shape
    L, M, stacked, stage_fn = _encoder_stages(
        module, variables["params"], N, S, num_microbatches)

    # string method dispatch so TextEncoder subclasses keep their
    # overridden prologue/epilogue
    h = module.apply(variables, ids, method="embed_ids")
    key_mask = ids != 0

    mb = N // M
    h_mb = h.reshape(M, mb, T, module.width)
    mask_mb = key_mask.reshape(M, mb, T)
    out = pipeline_apply(mesh, stage_fn, stacked, h_mb, axis=axis,
                         aux=mask_mb, remat_stage=remat_stage)
    x = out.reshape(N, T, module.width)
    return module.apply(variables, x, ids, method="finalize")
