"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pp``
mesh axis.

No reference counterpart (SURVEY §2.14: PP absent there) — this is part of
the TPU-native extension that makes large in-framework models trainable.
Each device holds ONE stage's parameters; microbatches enter stage 0 and
activations flow around the ring by ``ppermute``, so at steady state every
stage computes a different microbatch each tick (the classic
(M + S - 1)-step schedule with bubble fraction (S-1)/(M+S-1)).

Stages must share activation shapes (uniform-width blocks), the usual
constraint for homogeneous pipeline demos.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def pipeline_apply(mesh, stage_fn, stacked_params, microbatches,
                   *, axis: str = "pp"):
    """Run microbatches through S = mesh.shape[axis] pipeline stages.

    stage_fn(params_i, h) -> h'  applied by stage i; ``stacked_params`` has
    leading dim S (stage-major, sharded over ``axis``); ``microbatches``
    is [M, mb, ...] (replicated). Returns [M, mb, ...] outputs of the last
    stage.
    """
    S = int(mesh.shape[axis])
    M = microbatches.shape[0]
    T = M + S - 1

    def body(params_local, xs):
        params_local = jax.tree.map(lambda p: p[0], params_local)
        stage = jax.lax.axis_index(axis)
        h = jnp.zeros_like(xs[0])
        outs = jnp.zeros_like(xs)

        def tick(t, carry):
            h_in, outs = carry
            # stage 0 ingests microbatch t (while available)
            mb = jnp.clip(t, 0, M - 1)
            inject = jnp.where(stage == 0,
                               jnp.where(t < M, 1.0, 0.0), 0.0)
            h_cur = inject * xs[mb] + (1.0 - inject) * h_in
            h_out = stage_fn(params_local, h_cur)
            # last stage emits microbatch t-(S-1)
            emit_idx = jnp.clip(t - (S - 1), 0, M - 1)
            emit = (stage == S - 1) & (t >= S - 1)
            outs = jax.lax.cond(
                emit,
                lambda o: jax.lax.dynamic_update_slice(
                    o, h_out[None], (emit_idx,) + (0,) * h_out.ndim),
                lambda o: o, outs)
            # rotate activations forward around the ring
            perm = [(i, (i + 1) % S) for i in range(S)]
            h_next = jax.lax.ppermute(h_out, axis, perm)
            return h_next, outs

        _, outs = jax.lax.fori_loop(0, T, tick, (h, outs))
        # every shard returns its buffer; only the last stage's is real —
        # broadcast it to all shards so the output is replicated
        last = jax.lax.psum(
            outs * (stage == S - 1).astype(outs.dtype), axis)
        return last

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), P()), out_specs=P(),
        check_vma=False)(stacked_params, microbatches)


def make_pipeline_mlp(width: int):
    """A uniform-width residual MLP block for pipeline demos/tests:
    params = (W [width, width], b [width])."""
    def stage_fn(params, h):
        W, b = params
        return h + jnp.tanh(h @ W + b)
    return stage_fn
