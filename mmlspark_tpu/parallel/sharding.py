"""Sharding + padding helpers.

The reference's answer to ragged work distribution is the ``ignore``
protocol: empty Spark partitions opt out of the collective ring
(``lightgbm/TrainUtils.scala:652-669``, ``LightGBMConstants.scala:36``).
SPMD programs need fixed shapes instead, so the framework's convention is
**pad rows to a multiple of the shard count and carry a row-validity mask**;
every reduction in the compute path honours the mask, so padded rows are the
moral equivalent of ignored partitions.
"""

from __future__ import annotations

import numpy as np

from jax.sharding import NamedSharding, PartitionSpec as P


def replicated(mesh):
    return NamedSharding(mesh, P())


def batch_sharding(mesh, axis: str = "dp", ndim: int = 2):
    """Rows sharded over `axis`, remaining dims replicated."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def pad_rows(arrays, multiple: int, pad_value=0.0):
    """Pad each array's leading dim up to a multiple; returns
    (padded_arrays, mask) where mask is f32 [n_padded] with 1 = real row.

    Accepts a single array or a sequence; None entries pass through.
    Each array keeps its OWN dtype: the pad constant is cast into it
    per-array, so padding an int label column (or a bool flag column)
    alongside float features never silently promotes it to float —
    downstream jit signatures and gather indices depend on the dtype
    surviving the pad. The validity mask alone is always f32.
    """
    single = not isinstance(arrays, (list, tuple))
    arrs = [arrays] if single else list(arrays)
    n = next(a.shape[0] for a in arrs if a is not None)
    n_pad = (-n) % multiple
    out = []
    for a in arrs:
        if a is None:
            out.append(None)
            continue
        a = np.asarray(a)
        if a.shape[0] != n:
            raise ValueError("inconsistent leading dims")
        pad_width = [(0, n_pad)] + [(0, 0)] * (a.ndim - 1)
        # the pad constant casts into each array's OWN dtype — the
        # explicit cast pins the dtype-preservation contract the
        # regression test asserts, independent of np.pad's casting rules
        fill = np.asarray(pad_value).astype(a.dtype, casting="unsafe")
        out.append(np.pad(a, pad_width, constant_values=fill))
    mask = np.ones(n + n_pad, np.float32)
    mask[n:] = 0.0
    return (out[0] if single else out), mask


def unpad_rows(array, n_real: int):
    return array[:n_real]


def shard_batch(mesh, arrays, axis: str = "dp", pad_value=0.0):
    """Pad + device_put a batch sharded over a mesh axis.

    Returns (sharded_arrays, mask_sharded, n_real).
    """
    import jax

    single = not isinstance(arrays, (list, tuple))
    arrs = [arrays] if single else list(arrays)
    n_real = next(a.shape[0] for a in arrs if a is not None)
    size = int(np.prod([mesh.shape[a] for a in ([axis] if isinstance(
        axis, str) else axis)]))
    padded, mask = pad_rows(arrs, size, pad_value)
    out = []
    for a in padded:
        if a is None:
            out.append(None)
            continue
        sh = NamedSharding(mesh, P(axis, *([None] * (a.ndim - 1))))
        out.append(jax.device_put(a, sh))
    mask_dev = jax.device_put(mask, NamedSharding(mesh, P(axis)))
    return (out[0] if single else out), mask_dev, n_real
