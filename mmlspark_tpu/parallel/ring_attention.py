"""Ring attention: exact attention over sequence-sharded inputs.

The reference has no long-context machinery (SURVEY §5: "absent in the
reference") — this is the first-class TPU-native extension the framework
owes its DL path. Sequence axis ``sp`` shards Q/K/V blocks across devices;
K/V blocks rotate around the ring via ``ppermute`` while each device keeps a
numerically-stable running softmax (flash-attention style: running max ``m``,
denominator ``l``, accumulator ``acc``), so attention over a sequence of
length S costs O(S/d) memory per device and the K/V transfer overlaps with
compute on the MXU.

Pattern follows the public blockwise/ring-attention formulation (Liu et al.,
"Ring Attention with Blockwise Transformers"; see PAPERS.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from . import collectives as _coll
from .compat import axis_size as _axis_size, \
    shard_map as _shard_map


def _block_update(q, k, v, m, l, acc, bias, scale):
    """One blockwise softmax-attention accumulation step.

    q [B,H,Tq,D]; k,v [B,H,Tk,D]; m,l [B,H,Tq]; acc [B,H,Tq,D].
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m_new = jnp.maximum(m, s.max(axis=-1))
    # a fully-masked block leaves m_new = -inf; exp(s - m_new) would be
    # exp(-inf - -inf) = nan, so shift by 0 there (every term is then
    # exp(-inf) = 0, the correct weight)
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    corr = jnp.exp(m - m_safe)
    l_new = l * corr + p.sum(axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, acc_new


def blockwise_attention(q, k, v, *, block_size: int = 512,
                        causal: bool = False, scale: float | None = None,
                        key_mask=None, return_lse: bool = False,
                        q_offset=0, k_offset=0):
    """Single-device blockwise (flash-style) attention.

    q/k/v: [B, H, T, D]. Computes exact softmax attention in blocks over the
    key axis so the [T, T] score matrix never materializes. ``key_mask``
    [B, T] bool marks valid keys (False = e.g. padding, excluded from
    the softmax). ``return_lse`` additionally returns the per-row
    logsumexp [B, H, T]; fully-masked rows report the same finite
    sentinel (~-1e30) as ``flash_attention_lse`` so the two backends of
    the lse API agree (consumers may subtract or exp() across them).

    ``q_offset``/``k_offset`` (possibly traced) shift the GLOBAL
    positions the causal mask compares — the O(T)-memory recompute
    backward for offset-carrying fused-kernel calls (the causal ring).
    """
    B, H, T, D = q.shape
    scale = scale if scale is not None else D ** -0.5
    nb = -(-T // block_size)
    pad = nb * block_size - T
    if pad:
        kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    else:
        kp, vp = k, v
    kb = kp.reshape(B, H, nb, block_size, D)
    vb = vp.reshape(B, H, nb, block_size, D)

    q_pos = q_offset + jnp.arange(T)

    if key_mask is not None and pad:
        key_mask = jnp.pad(key_mask, ((0, 0), (0, pad)))

    def body(i, carry):
        m, l, acc = carry
        kv_i = jnp.take(kb, i, axis=2)
        vv_i = jnp.take(vb, i, axis=2)
        k_idx = i * block_size + jnp.arange(block_size)  # LOCAL: pads
        bias = jnp.where(k_idx[None, :] >= T, -jnp.inf, 0.0)
        if causal:
            bias = bias + jnp.where(
                (k_offset + k_idx)[None, :] > q_pos[:, None],
                -jnp.inf, 0.0)
        bias = bias[None, None]
        if key_mask is not None:
            mb = jax.lax.dynamic_slice_in_dim(
                key_mask, i * block_size, block_size, axis=1)
            bias = bias + jnp.where(mb, 0.0, -jnp.inf)[:, None, None, :]
        m, l, acc = _block_update(q, kv_i, vv_i, m, l, acc, bias, scale)
        return m, l, acc

    m0 = jnp.full((B, H, T), -jnp.inf, q.dtype)
    l0 = jnp.zeros((B, H, T), q.dtype)
    a0 = jnp.zeros_like(q)
    m, l, acc = jax.lax.fori_loop(0, nb, body, (m0, l0, a0))
    # valid rows always have l >= 1 (the row max contributes exp(0));
    # fully-masked rows have l == 0 EXACTLY, acc == 0. Dividing by a
    # tiny clamp instead would NaN the BACKWARD: the quotient rule
    # squares the denominator and (1e-35)^2 underflows float32 to 0,
    # so the l-cotangent becomes 0 * inf.
    l_safe = jnp.where(l > 0, l, 1.0)
    out = acc / l_safe[..., None]
    if return_lse:
        # clamp the fully-masked-row -inf to the flash kernel's finite
        # sentinel so both lse backends agree (ADVICE r3)
        return out, jnp.maximum(m + jnp.log(jnp.maximum(l, 1e-35)),
                                -1e30)
    return out


def ring_attention(q, k, v, *, axis: str = "sp", causal: bool = False,
                   scale: float | None = None, key_mask=None,
                   local_impl: str = "blockwise"):
    """Exact attention with Q/K/V sharded over mesh axis ``axis`` along T.

    Call inside ``shard_map``: each shard holds [B, H, T/n, D]. K/V rotate
    n-1 times around the ring; causal masking uses global block positions
    (shards are assumed laid out in sequence order along the axis).

    ``local_impl``: "blockwise" computes each shard-local attention with
    the XLA running-softmax update; "flash" uses the fused Pallas kernel
    per ring step (``dl/pallas_attention.flash_attention_lse``) and
    merges the per-step normalized partials via the standard lse merge —
    the TPU choice. Causal works in both: the kernel takes the held
    K/V block's (traced) global position offsets, so each ring step
    masks against true sequence coordinates.
    """
    n = _axis_size(axis)
    my = _coll.axis_index(axis)
    B, H, Tl, D = q.shape
    scale = scale if scale is not None else D ** -0.5

    q_pos = my * Tl + jnp.arange(Tl)

    if key_mask is None:
        key_mask = jnp.ones((B, Tl), bool)

    if local_impl == "flash":
        if scale != D ** -0.5:
            raise NotImplementedError(
                "local_impl='flash' uses the kernel's fixed D**-0.5 "
                "scale")
        from ..dl.pallas_attention import flash_attention_lse

        def body_flash(i, carry):
            o, lse, kc, vc, mc = carry
            # the held K/V block's GLOBAL offset: whose shard is it
            # after i rotations — traced, passed into the kernel's
            # causal position mask (ignored when non-causal)
            src_shard = (my - i) % n
            o_i, lse_i = flash_attention_lse(
                q, kc, vc, key_mask=mc, causal=causal,
                q_offset=my * Tl, k_offset=src_shard * Tl)
            # merge two normalized partial attentions: softmax weights
            # are exp(lse - M) per side; empty sides carry lse ≈ -1e30.
            # The o carry accumulates in f32 (the merge weights are f32;
            # a bf16 carry would promote and break the fori_loop carry
            # aval), cast back after the loop.
            m_new = jnp.maximum(lse, lse_i)
            la = jnp.exp(lse - m_new)
            lb = jnp.exp(lse_i - m_new)
            denom = jnp.maximum(la + lb, 1e-35)
            o = (o * la[..., None]
                 + o_i.astype(jnp.float32) * lb[..., None]) \
                / denom[..., None]
            lse = m_new + jnp.log(denom)
            perm = [(j, (j + 1) % n) for j in range(n)]
            kc = _coll.ppermute(kc, axis, perm)
            vc = _coll.ppermute(vc, axis, perm)
            mc = _coll.ppermute(mc, axis, perm)
            return o, lse, kc, vc, mc

        o0 = jnp.zeros(q.shape, jnp.float32)
        lse0 = jnp.full((B, H, Tl), -1e30, jnp.float32)
        o, _, _, _, _ = jax.lax.fori_loop(
            0, n, body_flash, (o0, lse0, k, v, key_mask))
        return o.astype(q.dtype)
    if local_impl != "blockwise":
        raise ValueError(f"unknown local_impl {local_impl!r}; expected "
                         "blockwise|flash")

    def body(i, carry):
        m, l, acc, kc, vc, mc = carry
        src_shard = (my - i) % n          # whose K/V we currently hold
        k_pos = src_shard * Tl + jnp.arange(Tl)
        if causal:
            bias = jnp.where(k_pos[None, :] > q_pos[:, None], -jnp.inf, 0.0)
            bias = bias[None, None]
        else:
            bias = jnp.zeros((1, 1, 1, Tl), q.dtype)
        # the key mask travels around the ring with its K/V block
        bias = bias + jnp.where(mc, 0.0, -jnp.inf)[:, None, None, :]
        m, l, acc = _block_update(q, kc, vc, m, l, acc, bias, scale)
        # rotate K/V to the next device; XLA overlaps this with compute
        perm = [(j, (j + 1) % n) for j in range(n)]
        kc = _coll.ppermute(kc, axis, perm)
        vc = _coll.ppermute(vc, axis, perm)
        mc = _coll.ppermute(mc, axis, perm)
        return m, l, acc, kc, vc, mc

    m0 = jnp.full((B, H, Tl), -jnp.inf, q.dtype)
    l0 = jnp.zeros((B, H, Tl), q.dtype)
    a0 = jnp.zeros_like(q)
    m, l, acc, _, _, _ = jax.lax.fori_loop(
        0, n, body, (m0, l0, a0, k, v, key_mask))
    # l == 0 exactly for fully-masked rows (valid rows have l >= 1);
    # see blockwise_attention for why a tiny clamp would NaN backward
    return acc / jnp.where(l > 0, l, 1.0)[..., None]


def make_ring_attention(mesh, *, causal: bool = False, axis: str = "sp",
                        batch_axis: str | None = None,
                        local_impl: str = "blockwise"):
    """shard_map-wrapped ring attention: [B, H, T, D] sharded on T over
    ``axis`` (and optionally on B over ``batch_axis`` — 2D data x
    sequence parallelism; the ring runs independently per batch shard).
    The returned fn is ``fn(q, k, v, key_mask=None)`` with ``key_mask``
    [B, T] bool (True = valid key)."""
    from jax.sharding import PartitionSpec as P
    spec = P(batch_axis, None, axis, None)

    @functools.partial(
        _shard_map, mesh=mesh,
        in_specs=(spec, spec, spec, P(batch_axis, axis)), out_specs=spec,
        check_vma=False)
    def mapped(q, k, v, kmask):
        return ring_attention(q, k, v, axis=axis, causal=causal,
                              key_mask=kmask, local_impl=local_impl)

    def fn(q, k, v, key_mask=None):
        if key_mask is None:
            key_mask = jnp.ones((q.shape[0], q.shape[2]), bool)
        return mapped(q, k, v, key_mask)

    return fn
