"""Mesh construction and multi-host bootstrap.

Replaces the reference's cluster-topology discovery and rendezvous:
``ClusterUtil.getNumTasksPerExecutor`` (``core/utils/ClusterUtil.scala:13-291``)
becomes device enumeration; the driver ServerSocket rendezvous that collects
``host:port`` from every worker (``lightgbm/LightGBMUtils.scala:119-188``)
becomes the JAX coordination service (:func:`distributed_init`).

Axis conventions (used throughout the framework):
  ``dp`` — data parallel (rows / batch)
  ``tp`` — tensor parallel (model weights)
  ``pp`` — pipeline parallel (layer stages)
  ``sp`` — sequence/context parallel (ring attention)
  ``ep`` — expert parallel (MoE)
"""

from __future__ import annotations

import dataclasses
import math
import os

import numpy as np

AXIS_ORDER = ("pp", "dp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh: axis name -> size; -1 for one auto-filled axis."""
    dp: int = -1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        fixed = math.prod(s for s in sizes.values() if s > 0)
        autos = [a for a, s in sizes.items() if s <= 0]
        if len(autos) > 1:
            raise ValueError(f"only one axis may be -1, got {autos}")
        if autos:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"{fixed}")
            sizes[autos[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes


def build_mesh(spec: MeshSpec | None = None, devices=None):
    """Build a Mesh over all (or given) devices.

    Axes of size 1 are kept in the mesh so PartitionSpecs can always name
    them — XLA elides trivial collectives, so this costs nothing.
    """
    import jax
    from jax.sharding import Mesh

    devices = np.asarray(jax.devices() if devices is None else devices)
    spec = spec or MeshSpec()
    sizes = spec.resolve(devices.size)
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    return Mesh(devices.reshape(shape), AXIS_ORDER)


def local_mesh(axis: str = "dp", devices=None):
    """1-D mesh over every visible device — the default data-parallel world
    (the reference's "one LightGBM machine per Spark task")."""
    import jax
    from jax.sharding import Mesh
    devices = np.asarray(jax.devices() if devices is None else devices)
    return Mesh(devices, (axis,))


def mesh_shape_for(n_devices: int, **axes: int) -> MeshSpec:
    """Convenience: MeshSpec from keyword sizes, validated for n_devices."""
    spec = MeshSpec(**axes)
    spec.resolve(n_devices)
    return spec


def distributed_init(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> bool:
    """Multi-host bootstrap: JAX coordination service.

    Stands in for the reference's driver rendezvous
    (``LightGBMUtils.createDriverNodesThread``,
    ``lightgbm/LightGBMUtils.scala:119-188``): instead of every worker
    reporting ``host:port`` over a raw socket and receiving the peer list,
    every process dials the coordinator and PJRT wires the ICI/DCN mesh.

    Arguments default from ``MMLSPARK_TPU_COORDINATOR`` /
    ``MMLSPARK_TPU_NUM_PROCESSES`` / ``MMLSPARK_TPU_PROCESS_ID`` (what
    ``parallel.multihost`` exports into its workers); explicit arguments
    win, and ``process_id=0`` is a real value, not a fall-through to the
    env (the coordinator itself is process 0).

    No-ops (returns False) on single-process (local/test) runs so
    library code can call it unconditionally; returns True once the
    coordination service is up.
    """
    import jax

    addr = coordinator_address or os.environ.get("MMLSPARK_TPU_COORDINATOR")
    if addr is None:
        return False
    # CPU (DCN-style) pods need the gloo collectives backend BEFORE
    # initialize — without it init succeeds and the first cross-process
    # execution fails (see compat.enable_cpu_multiprocess_collectives)
    from .compat import enable_cpu_multiprocess_collectives
    if (os.environ.get("JAX_PLATFORMS") or "").startswith("cpu"):
        enable_cpu_multiprocess_collectives()
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=num_processes if num_processes is not None
        else int(os.environ.get("MMLSPARK_TPU_NUM_PROCESSES", "1")),
        process_id=process_id if process_id is not None
        else int(os.environ.get("MMLSPARK_TPU_PROCESS_ID", "0")))
    return True
