"""Ulysses-style all-to-all sequence parallelism.

The second first-class long-context strategy beside ring attention
(``ring_attention.py``): instead of rotating K/V blocks around a ring,
each device holds a sequence shard and an ``all_to_all`` re-shards the
activations from sequence-sharded to HEAD-sharded before attention, so
every device computes FULL-sequence attention for its subset of heads;
a second ``all_to_all`` restores sequence sharding afterwards.

Trade-off vs ring (the public DeepSpeed-Ulysses formulation, PAPERS.md):
two all-to-alls move O(T·D/d) per device regardless of sequence length
and attention itself needs no per-block softmax bookkeeping, but the
device count is capped by the head count (d ≤ H) — ring has no such cap.
Both ride ICI; pick per model shape.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import collectives as _coll
from .ring_attention import blockwise_attention
from .compat import shard_map as _shard_map


def make_ulysses_attention(mesh: Mesh, axis: str = "sp", *,
                           causal: bool = False,
                           scale: float | None = None,
                           block_size: int | None = None,
                           batch_axis: str | None = None,
                           local_impl: str = "blockwise"):
    """Build an all-to-all sequence-parallel attention fn over ``mesh``.

    Inputs/outputs are [B, H, T, D] arrays sequence-sharded over ``axis``
    (each device holds T/d of the sequence), optionally batch-sharded
    over ``batch_axis`` (2D data x sequence parallelism). H must be
    divisible by the axis size.

    ``local_impl``: "blockwise" (XLA running softmax) or "flash" (the
    fused Pallas kernel, ``dl/pallas_attention.py``) for each device's
    full-sequence head-group attention. Flash supports ``causal``
    directly — after the all-to-all each device sees the FULL sequence
    in global order, so the kernel's global-position triangular mask
    applies as-is (unlike ring, where each shard's kernel call would
    need traced position offsets) — but only the kernel's fixed
    D**-0.5 scale.
    """
    d = int(mesh.shape[axis])
    if local_impl not in ("blockwise", "flash"):
        raise ValueError(f"unknown local_impl {local_impl!r}; expected "
                         "blockwise|flash")
    if local_impl == "flash" and scale is not None:
        raise NotImplementedError(
            "local_impl='flash' supports the kernel's fixed D**-0.5 "
            "scale only")

    def local(q, k, v, kmask):
        # [B, H, t, D] local sequence shard (t = T/d)
        B, H, t, D = q.shape
        if H % d != 0:
            raise ValueError(
                f"ulysses needs head count {H} divisible by the '{axis}' "
                f"axis size {d} (use ring attention otherwise)")
        h = H // d

        def seq_to_heads(x):
            # [B, H, t, D] → [B, H/d, T, D]: head-group j of every
            # device's sequence chunk lands on device j; received chunks
            # stack in source-device order = sequence order
            x = x.reshape(B, d, h, t, D)
            x = _coll.all_to_all(x, axis, split_axis=1,
                                 concat_axis=2, tiled=False)     # [B, h, d, t, D]
            return x.reshape(B, h, d * t, D)

        def heads_to_seq(x):
            # inverse: [B, h, T, D] → [B, H, t, D]; sequence chunk i of
            # every head-group goes home to device i, head-groups stack
            # in source-device order = head order
            x = x.reshape(B, h, d, t, D)
            x = _coll.all_to_all(x, axis, split_axis=2,
                                 concat_axis=1, tiled=False)     # [B, d, h, t, D]
            return x.reshape(B, d * h, t, D)

        qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
        # every device attends over the full sequence for its head
        # group, so it needs the full key mask
        full_mask = _coll.allgather(kmask, axis, gather_axis=1)
        if local_impl == "flash":
            from ..dl.pallas_attention import flash_attention
            out = flash_attention(qh, kh, vh, key_mask=full_mask,
                                  block_k=block_size, causal=causal)
        else:
            out = blockwise_attention(qh, kh, vh, causal=causal,
                                      scale=scale,
                                      block_size=block_size or 512,
                                      key_mask=full_mask)
        return heads_to_seq(out)

    spec = P(batch_axis, None, axis, None)
    mapped = jax.jit(_shard_map(
        local, mesh=mesh,
        in_specs=(spec, spec, spec, P(batch_axis, axis)),
        out_specs=spec, check_vma=False))

    @jax.jit
    def fn(q, k, v, key_mask=None):
        import jax.numpy as jnp
        if key_mask is None:
            key_mask = jnp.ones((q.shape[0], q.shape[2]), bool)
        return mapped(q, k, v, key_mask)

    return fn
