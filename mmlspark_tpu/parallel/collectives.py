"""Named collectives over mesh axes.

These are the framework's replacement for the reference's three comm
backends (SURVEY §2.13): LightGBM's raw-TCP ring/Bruck allreduce
(``lightgbm/TrainUtils.scala:609-625``), VW's spanning-tree AllReduce
(``vw/VowpalWabbitBase.scala:434-461``), and Spark broadcast/barrier
(``LightGBMBase.scala:256-261``). Inside ``shard_map``/``pjit`` these lower
to XLA collectives that ride ICI within a slice and DCN across slices.

Observability: every collective records into the process-wide obs
registry — ``collective_calls_total{op,axis}`` and
``collective_bytes_total{op,axis}`` (per-shard payload bytes). Because
these helpers run at TRACE time, the counters measure distinct traced
call sites × retraces, not per-step executions (XLA replays the
compiled program without re-entering Python) — the right number for
"what collectives does this program issue, and how big are they".
Per-execution device time comes from the paired ``named_scope``: capture
with ``utils.profiling.profile_trace`` and the op shows up labeled in
XProf, the TPU equivalent of wrapping a socket allreduce in a stopwatch.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from ..obs import registry as _obs
from .compat import axis_size as _axis_size

_m_calls = _obs.counter(
    "collective_calls_total",
    "collective trace-time issue count, by op/axis")
_m_bytes = _obs.counter(
    "collective_bytes_total",
    "per-shard payload bytes at collective issue, by op/axis")
# the parallel_* twin of collective_bytes_total: the partition-engine
# series family (parallel_rule_match_total / parallel_unmatched_leaves
# _total / parallel_collective_bytes_total) lives on one prefix so a
# dashboard for "what is the sharding engine doing" is one glob; the
# legacy collective_* names keep recording for existing consumers
_m_par_bytes = _obs.counter(
    "parallel_collective_bytes_total",
    "per-shard payload bytes at collective issue, by op/axis "
    "(partition-engine series; same numbers as collective_bytes_total)")


@contextlib.contextmanager
def _observed(op: str, x, axis):
    """XProf naming scope; records one collective issue on clean exit —
    a typo'd axis (or any trace error) raises out of the wrapped lax
    call and must not leave a phantom series in the registry."""
    try:
        nbytes = int(x.size) * x.dtype.itemsize
    except Exception:
        nbytes = 0
    label = axis if isinstance(axis, str) else ",".join(axis)
    try:
        scope = jax.named_scope(f"collective.{op}[{label}]")
    except Exception:  # pragma: no cover - named_scope is cosmetic
        scope = contextlib.nullcontext()
    with scope:
        yield
    # pod workers tag the series per-process (obs.profile.process_label
    # is None single-process, so existing sample names stay unchanged)
    from ..obs.profile import process_label
    pl = process_label()
    plab = {"process": pl} if pl is not None else {}
    _m_calls.inc(1, op=op, axis=label, **plab)
    _m_bytes.inc(nbytes, op=op, axis=label, **plab)
    _m_par_bytes.inc(nbytes, op=op, axis=label, **plab)


def allreduce(x, axis: str | tuple[str, ...], op: str = "sum"):
    """psum/pmax/pmin/pmean over a named mesh axis (LightGBM's histogram
    allreduce; VW's weight averaging with op="mean")."""
    fns = {"sum": jax.lax.psum, "mean": jax.lax.pmean,
           "max": jax.lax.pmax, "min": jax.lax.pmin}
    # validated BEFORE recording: a typo'd op must raise, not leave a
    # phantom collective series in the registry for the process lifetime
    if op not in fns:
        raise ValueError(f"unknown op {op!r}")
    with _observed(f"allreduce_{op}", x, axis):
        return fns[op](x, axis)


def allgather(x, axis: str, *, tiled: bool = True, gather_axis: int = 0):
    """Gather shards along a named axis (voting-parallel top-K exchange)."""
    with _observed("allgather", x, axis):
        return jax.lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def psum_scatter(x, axis: str, *, scatter_axis: int = 0):
    """reduce_scatter: each shard gets one slice of the summed tensor."""
    with _observed("psum_scatter", x, axis):
        return jax.lax.psum_scatter(x, axis,
                                    scatter_dimension=scatter_axis,
                                    tiled=True)


def ppermute(x, axis: str, perm):
    """Point-to-point shard permutation with an explicit ``(src, dst)``
    list (ring attention's rotation, the pipeline ring's activation
    hand-off). Same accounting as every other collective here — raw
    ``jax.lax.ppermute`` call sites bypass the obs byte series and are
    flagged by graftcheck's collective-audit pass."""
    with _observed("ppermute", x, axis):
        return jax.lax.ppermute(x, axis, perm)


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int,
               tiled: bool = False):
    """Shard-count transpose (Ulysses' sequence↔heads exchange)."""
    with _observed("all_to_all", x, axis):
        return jax.lax.all_to_all(x, axis, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=tiled)


def axis_index(axis: str):
    """This shard's coordinate along a named axis. Moves no real
    payload; recorded (like :func:`barrier`, as a scalar token) so the
    calls-total series still shows which programs ask for topology."""
    z = jnp.zeros((), jnp.int32)
    with _observed("axis_index", z, axis):
        return jax.lax.axis_index(axis)


def ring_permute(x, axis: str, shift: int = 1):
    """Rotate shards around the ring of a named axis (the building block of
    ring attention / sequence parallelism)."""
    with _observed("ring_permute", x, axis):
        n = _axis_size(axis)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return jax.lax.ppermute(x, axis, perm)


def barrier(axis: str):
    """SPMD barrier: a trivial psum forces all shards to rendezvous.

    The reference uses Spark barrier execution to keep partial stages from
    deadlocking the collective ring (``LightGBMBase.scala:106-137``); in SPMD
    every program step is already a barrier, but this is handy to delimit
    phases explicitly.
    """
    z = jnp.zeros((), jnp.int32)
    with _observed("barrier", z, axis):
        return jax.lax.psum(z, axis)
