"""Named collectives over mesh axes.

These are the framework's replacement for the reference's three comm
backends (SURVEY §2.13): LightGBM's raw-TCP ring/Bruck allreduce
(``lightgbm/TrainUtils.scala:609-625``), VW's spanning-tree AllReduce
(``vw/VowpalWabbitBase.scala:434-461``), and Spark broadcast/barrier
(``LightGBMBase.scala:256-261``). Inside ``shard_map``/``pjit`` these lower
to XLA collectives that ride ICI within a slice and DCN across slices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def allreduce(x, axis: str | tuple[str, ...], op: str = "sum"):
    """psum/pmax/pmin/pmean over a named mesh axis (LightGBM's histogram
    allreduce; VW's weight averaging with op="mean")."""
    if op == "sum":
        return jax.lax.psum(x, axis)
    if op == "mean":
        return jax.lax.pmean(x, axis)
    if op == "max":
        return jax.lax.pmax(x, axis)
    if op == "min":
        return jax.lax.pmin(x, axis)
    raise ValueError(f"unknown op {op!r}")


def allgather(x, axis: str, *, tiled: bool = True, gather_axis: int = 0):
    """Gather shards along a named axis (voting-parallel top-K exchange)."""
    return jax.lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def psum_scatter(x, axis: str, *, scatter_axis: int = 0):
    """reduce_scatter: each shard gets one slice of the summed tensor."""
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                                tiled=True)


def ring_permute(x, axis: str, shift: int = 1):
    """Rotate shards around the ring of a named axis (the building block of
    ring attention / sequence parallelism)."""
    n = jax.lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


def barrier(axis: str):
    """SPMD barrier: a trivial psum forces all shards to rendezvous.

    The reference uses Spark barrier execution to keep partial stages from
    deadlocking the collective ring (``LightGBMBase.scala:106-137``); in SPMD
    every program step is already a barrier, but this is handy to delimit
    phases explicitly.
    """
    return jax.lax.psum(jnp.zeros((), jnp.int32), axis)
