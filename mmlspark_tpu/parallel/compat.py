"""JAX API compatibility for the sharding layer.

The framework targets current JAX, where ``shard_map`` is a top-level
``jax.shard_map`` with a ``check_vma`` knob; on the previous API
generation the same transform lives at
``jax.experimental.shard_map.shard_map`` and the knob is ``check_rep``.
Every in-repo call site goes through :func:`shard_map` so the version
split is handled in exactly one place (the bake-what-you-have stance:
no pip installs inside the image, so the code must run on the JAX it
finds).

JAX-free at module scope, like the rest of the package's light
surface.
"""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` on new JAX, ``jax.experimental.shard_map`` on
    old, with ``check_vma``/``check_rep`` translated. ``check_vma=None``
    means "library default" on either version."""
    import jax
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def jit(fn=None, *, name: str | None = None, **jit_kwargs):
    """``jax.jit`` through the obs :class:`CompileTracker`: identical
    call semantics (decorator or call-form; ``donate_argnums`` /
    ``in_shardings`` / ... pass through), but every retrace is counted
    and every compile's wall time is recorded per function in the
    process-wide registry (``profile_compiles_total{fn=...}`` etc.) —
    the runtime counterpart of graftcheck's static recompile-hazard
    pass. Route jit call sites through here so a production server can
    answer "did anything recompile under load?" from a scrape.

    The returned callable forwards ``lower`` — the ahead-of-time path:
    ``fn.lower(*args).compile()`` plus :func:`serialize_compiled` /
    :func:`deserialize_compiled` is how the AOT executable store
    (``core/aot.py``) turns request-latency compiles into build-step
    artifacts.

    JAX-free until called (the tracker imports jax lazily), like the
    rest of this module's surface."""
    from ..obs.profile import compile_tracker
    return compile_tracker.jit(fn, name=name, **jit_kwargs)


def aot_serialization_available() -> bool:
    """Whether this JAX build can serialize compiled executables
    (``jax.experimental.serialize_executable``). When False the AOT
    store (``core/aot.py``) degrades to retrace-tier entries — still a
    build-time cost, just paid per process at warm load."""
    try:
        from jax.experimental import serialize_executable  # noqa: F401
        return hasattr(serialize_executable, "serialize")
    except ImportError:
        return False


def serialize_compiled(compiled) -> bytes:
    """``jax.stages.Compiled`` → one self-contained blob (payload +
    pytree defs pickled together). Raises RuntimeError on JAX builds
    without ``serialize_executable`` — the AOT store catches it and
    writes a retrace-tier entry instead."""
    import pickle
    try:
        from jax.experimental.serialize_executable import serialize
    except ImportError as e:
        raise RuntimeError(
            "this JAX build has no serialize_executable") from e
    payload, in_tree, out_tree = serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree),
                        protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_compiled(blob: bytes, backend=None):
    """Inverse of :func:`serialize_compiled`: blob → a loaded
    ``jax.stages.Compiled`` bound to ``backend`` (default: the
    process's default backend)."""
    import pickle
    try:
        from jax.experimental.serialize_executable import (
            deserialize_and_load)
    except ImportError as e:
        raise RuntimeError(
            "this JAX build has no serialize_executable") from e
    payload, in_tree, out_tree = pickle.loads(blob)
    return deserialize_and_load(payload, in_tree, out_tree,
                                backend=backend)


def tpu_compiler_params(**kwargs):
    """Pallas TPU compiler params across the rename: ``CompilerParams``
    on new JAX was ``TPUCompilerParams`` one generation back — same
    fields, renamed class."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return cls(**kwargs)


def axis_size(axis) -> int:
    """STATIC size of a named mesh axis from inside shard_map/pjit.

    ``jax.lax.axis_size`` on new JAX; on old JAX the classic
    ``psum(1, axis)`` trick — a psum of a concrete Python scalar is
    evaluated at trace time, so the result is a real int either way
    (ring permutation tables and loop bounds need it concrete)."""
    import jax
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    return jax.lax.psum(1, axis)
