"""JAX API compatibility for the sharding layer.

The framework targets current JAX, where ``shard_map`` is a top-level
``jax.shard_map`` with a ``check_vma`` knob; on the previous API
generation the same transform lives at
``jax.experimental.shard_map.shard_map`` and the knob is ``check_rep``.
Every in-repo call site goes through :func:`shard_map` so the version
split is handled in exactly one place (the bake-what-you-have stance:
no pip installs inside the image, so the code must run on the JAX it
finds).

JAX-free at module scope, like the rest of the package's light
surface.
"""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` on new JAX, ``jax.experimental.shard_map`` on
    old, with ``check_vma``/``check_rep`` translated. ``check_vma=None``
    means "library default" on either version."""
    import jax
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def jit(fn=None, *, name: str | None = None, **jit_kwargs):
    """``jax.jit`` through the obs :class:`CompileTracker`: identical
    call semantics (decorator or call-form; ``donate_argnums`` /
    ``in_shardings`` / ... pass through), but every retrace is counted
    and every compile's wall time is recorded per function in the
    process-wide registry (``profile_compiles_total{fn=...}`` etc.) —
    the runtime counterpart of graftcheck's static recompile-hazard
    pass. Route jit call sites through here so a production server can
    answer "did anything recompile under load?" from a scrape.

    JAX-free until called (the tracker imports jax lazily), like the
    rest of this module's surface."""
    from ..obs.profile import compile_tracker
    return compile_tracker.jit(fn, name=name, **jit_kwargs)


def tpu_compiler_params(**kwargs):
    """Pallas TPU compiler params across the rename: ``CompilerParams``
    on new JAX was ``TPUCompilerParams`` one generation back — same
    fields, renamed class."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return cls(**kwargs)


def axis_size(axis) -> int:
    """STATIC size of a named mesh axis from inside shard_map/pjit.

    ``jax.lax.axis_size`` on new JAX; on old JAX the classic
    ``psum(1, axis)`` trick — a psum of a concrete Python scalar is
    evaluated at trace time, so the result is a real int either way
    (ring permutation tables and loop bounds need it concrete)."""
    import jax
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    return jax.lax.psum(1, axis)
