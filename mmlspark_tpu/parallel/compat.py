"""JAX API compatibility for the sharding layer.

The framework targets current JAX, where ``shard_map`` is a top-level
``jax.shard_map`` with a ``check_vma`` knob; on the previous API
generation the same transform lives at
``jax.experimental.shard_map.shard_map`` and the knob is ``check_rep``.
Every in-repo call site goes through :func:`shard_map` so the version
split is handled in exactly one place (the bake-what-you-have stance:
no pip installs inside the image, so the code must run on the JAX it
finds).

JAX-free at module scope, like the rest of the package's light
surface.
"""

from __future__ import annotations

from ..obs import registry as _obs

# same series to_shardings demotes into: a constraint the mesh cannot
# honor replicates that dim LOUDLY, wherever the demotion happens
_m_demoted = _obs.counter(
    "parallel_spec_demoted_total",
    "matched specs demoted to fewer axes because a dim does not divide "
    "the mesh axis, by axis")

# cost_analysis() returns None, a list, or partial dicts depending on
# backend/version — every consumer goes through cost_analysis() below,
# and a backend that yields nothing usable is counted here, never
# silently treated as free
_m_cost_missing = _obs.counter(
    "profile_cost_analysis_missing_total",
    "compiled-program cost_analysis() reads that yielded nothing "
    "usable, by reason (error | empty | zero)")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` on new JAX, ``jax.experimental.shard_map`` on
    old, with ``check_vma``/``check_rep`` translated. ``check_vma=None``
    means "library default" on either version."""
    import jax
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def jit(fn=None, *, name: str | None = None, **jit_kwargs):
    """``jax.jit`` through the obs :class:`CompileTracker`: identical
    call semantics (decorator or call-form; ``donate_argnums`` /
    ``in_shardings`` / ... pass through), but every retrace is counted
    and every compile's wall time is recorded per function in the
    process-wide registry (``profile_compiles_total{fn=...}`` etc.) —
    the runtime counterpart of graftcheck's static recompile-hazard
    pass. Route jit call sites through here so a production server can
    answer "did anything recompile under load?" from a scrape.

    The returned callable forwards ``lower`` — the ahead-of-time path:
    ``fn.lower(*args).compile()`` plus :func:`serialize_compiled` /
    :func:`deserialize_compiled` is how the AOT executable store
    (``core/aot.py``) turns request-latency compiles into build-step
    artifacts.

    JAX-free until called (the tracker imports jax lazily), like the
    rest of this module's surface."""
    from ..obs.profile import compile_tracker
    return compile_tracker.jit(fn, name=name, **jit_kwargs)


def cost_analysis(compiled) -> dict | None:
    """Normalized XLA analytic cost for a ``jax.stages.Compiled``:
    ``{"flops": float, "bytes": float}`` or None.

    ``Compiled.cost_analysis()`` is backend- and version-dependent: it
    can raise, return None, wrap the dict in a single-element list, or
    omit keys ("bytes accessed" is the HBM-traffic key when present).
    This is THE in-repo call site shape — consumers (the AOT store,
    LLM warm paths, bench harnesses) never touch the raw API, and a
    read that yields nothing usable is counted in
    ``profile_cost_analysis_missing_total`` instead of being silently
    treated as a free program."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        _m_cost_missing.inc(1, reason="error")
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict) or not cost:
        _m_cost_missing.inc(1, reason="empty")
        return None
    try:
        flops = float(cost.get("flops", 0.0) or 0.0)
        bytes_ = float(cost.get("bytes accessed", 0.0) or 0.0)
    except (TypeError, ValueError):
        _m_cost_missing.inc(1, reason="empty")
        return None
    if flops <= 0.0 and bytes_ <= 0.0:
        _m_cost_missing.inc(1, reason="zero")
        return None
    return {"flops": flops, "bytes": bytes_}


def aot_serialization_available() -> bool:
    """Whether this JAX build can serialize compiled executables
    (``jax.experimental.serialize_executable``). When False the AOT
    store (``core/aot.py``) degrades to retrace-tier entries — still a
    build-time cost, just paid per process at warm load."""
    try:
        from jax.experimental import serialize_executable  # noqa: F401
        return hasattr(serialize_executable, "serialize")
    except ImportError:
        return False


def serialize_compiled(compiled) -> bytes:
    """``jax.stages.Compiled`` → one self-contained blob (payload +
    pytree defs pickled together). Raises RuntimeError on JAX builds
    without ``serialize_executable`` — the AOT store catches it and
    writes a retrace-tier entry instead."""
    import pickle
    try:
        from jax.experimental.serialize_executable import serialize
    except ImportError as e:
        raise RuntimeError(
            "this JAX build has no serialize_executable") from e
    payload, in_tree, out_tree = serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree),
                        protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_compiled(blob: bytes, backend=None):
    """Inverse of :func:`serialize_compiled`: blob → a loaded
    ``jax.stages.Compiled`` bound to ``backend`` (default: the
    process's default backend)."""
    import pickle
    try:
        from jax.experimental.serialize_executable import (
            deserialize_and_load)
    except ImportError as e:
        raise RuntimeError(
            "this JAX build has no serialize_executable") from e
    payload, in_tree, out_tree = pickle.loads(blob)
    return deserialize_and_load(payload, in_tree, out_tree,
                                backend=backend)


def tpu_compiler_params(**kwargs):
    """Pallas TPU compiler params across the rename: ``CompilerParams``
    on new JAX was ``TPUCompilerParams`` one generation back — same
    fields, renamed class."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return cls(**kwargs)


def _context_mesh():
    """The physical mesh an enclosing ``with mesh:`` bound to this
    thread, or None. The pjit resource env moved modules across JAX
    generations; every read is guarded so API drift degrades to "no
    context mesh" (a no-op constraint), never to an ImportError."""
    try:
        from jax.interpreters import pxla
        m = pxla.thread_resources.env.physical_mesh
        if m is not None and not getattr(m, "empty", True):
            return m
    except Exception:
        pass
    return None


def with_sharding_constraint(x, spec, mesh=None):
    """One wrapper for the sharding-constraint API split (current JAX:
    ``jax.lax.with_sharding_constraint``; the previous generation:
    ``jax.experimental.pjit.with_sharding_constraint``) — the same
    single-call-site contract :func:`shard_map` gives the other split.
    graftcheck's collective-audit flags raw constraint call sites
    outside ``parallel/``, so this is THE way model and train-step code
    annotates activations.

    ``spec``: a ``NamedSharding`` (applied as-is), or a
    ``PartitionSpec`` / tuple of axis entries resolved against
    ``mesh``, falling back to the thread's context mesh (an enclosing
    ``with mesh:`` — the partitioned train steps enter it around their
    body so model-internal block-boundary constraints resolve). With no
    mesh anywhere the constraint is meaningless and ``x`` returns
    unchanged — model code runs un-annotated on a single device without
    carrying mesh plumbing.

    Entries the mesh cannot honor (axis absent, or the dim not
    divisible by the axis size) demote to ``None`` per-dim, counted in
    ``parallel_spec_demoted_total{axis=...}`` — the ``to_shardings``
    contract applied to activations, so a batch of 2 under a dp=8 mesh
    replicates loudly instead of failing the compile."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    fn = getattr(jax.lax, "with_sharding_constraint", None)
    if fn is None:  # previous API generation
        from jax.experimental.pjit import with_sharding_constraint as fn
    if isinstance(spec, NamedSharding):
        return fn(x, spec)
    if mesh is None:
        mesh = _context_mesh()
        if mesh is None:
            return x
    entries = list(tuple(spec))
    shape = getattr(x, "shape", ())
    if len(entries) > len(shape):
        raise ValueError(
            f"constraint spec {tuple(spec)} has more entries than the "
            f"value has dims (shape {tuple(shape)})")
    for i, entry in enumerate(entries):
        if entry is None:
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        size = 1
        for a in axes:
            size *= mesh.shape.get(a, 0)
        if size == 0 or shape[i] % size:
            _m_demoted.inc(1, axis=",".join(axes))
            entries[i] = None
    return fn(x, NamedSharding(mesh, P(*entries)))


def make_array_from_process_local_data(sharding, local_data):
    """Per-host feeding across the API generations: each process hands
    its LOCAL rows and gets back one global array sharded per
    ``sharding`` (current JAX: ``jax.make_array_from_process_local_
    data``; older: ``multihost_utils.host_local_array_to_global_
    array``). On a single-process mesh this degrades to a plain
    ``device_put`` of the (already-global) data."""
    import jax
    fn = getattr(jax, "make_array_from_process_local_data", None)
    if fn is not None:
        return fn(sharding, local_data)
    if jax.process_count() == 1:  # pragma: no cover - old-API fallback
        return jax.device_put(local_data, sharding)
    from jax.experimental import multihost_utils  # pragma: no cover
    return multihost_utils.host_local_array_to_global_array(
        local_data, sharding.mesh, sharding.spec)


def process_allgather(x, *, tiled: bool = False):
    """Global array → full host numpy value on EVERY process — the
    read-side twin of :func:`make_array_from_process_local_data`, and
    the loud-error escape hatch ``gather_params`` points at when a leaf
    spans processes. Single-process arrays take the plain
    ``device_get`` path (no collective, no coordination service)."""
    import jax
    import numpy as np
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(jax.device_get(x))
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=tiled))


def enable_cpu_multiprocess_collectives() -> bool:
    """Switch the CPU backend's collectives to the gloo implementation
    — REQUIRED before ``jax.distributed.initialize`` on a multi-process
    CPU (DCN-style) run: without it initialization succeeds but the
    first cross-process execution fails with "Multiprocess computations
    aren't implemented on the CPU backend". Returns whether the config
    took (False on JAX builds without the knob, e.g. TPU-only)."""
    import jax
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        return True
    except Exception:
        return False


def axis_size(axis) -> int:
    """STATIC size of a named mesh axis from inside shard_map/pjit.

    ``jax.lax.axis_size`` on new JAX; on old JAX the classic
    ``psum(1, axis)`` trick — a psum of a concrete Python scalar is
    evaluated at trace time, so the result is a real int either way
    (ring permutation tables and loop bounds need it concrete)."""
    import jax
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    return jax.lax.psum(1, axis)
