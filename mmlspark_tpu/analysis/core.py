"""graftcheck core: findings, the parsed-project model, and the pass
framework.

The analyzer is **pure stdlib** (``ast`` + ``hashlib``): it parses the
package source, never imports it, so it runs with no JAX, no device and
no side effects — the same posture TVM takes with compile-time program
analysis (PAPERS.md arXiv:1802.04799): decide what a program *can* do
before anything executes. The CI smoke check asserts
``import mmlspark_tpu.analysis`` pulls in neither JAX nor the package
under analysis.

Vocabulary:

- A :class:`Finding` is one diagnostic: ``(pass, rule, severity, path,
  line, symbol, message)`` plus a *stable fingerprint* that survives
  line-number drift — the baseline file keys on it.
- A :class:`Project` is the parsed package: every module's AST + source,
  keyed by dotted name.
- An :class:`AnalysisPass` turns a Project into findings. Passes
  register themselves in :data:`PASS_REGISTRY` at import.

Severities: ``error`` (a correctness contract is violated), ``warning``
(hazard that needs a human look), ``info`` (report-only, never gates).
The CI gate fails on any unbaselined error or warning.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os

SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass
class Finding:
    """One diagnostic emitted by a pass."""

    pass_name: str
    rule: str
    severity: str
    path: str          # repo-relative, forward slashes
    line: int
    symbol: str        # qualified name of the enclosing def/class ("" = module)
    message: str
    detail: str = ""   # stable token folded into the fingerprint (e.g. the
                       # flagged call name) — never line numbers

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"bad severity {self.severity!r}")

    @property
    def fingerprint(self) -> str:
        """Stable id for baselining: pass|rule|path|symbol|detail hashed.
        Line numbers are deliberately excluded so reformatting a file
        does not invalidate its baseline; one fingerprint therefore
        suppresses EVERY identical finding in the same symbol (adding a
        second identical hazard to a baselined function will not fail
        the gate — the triage workflow in docs/analysis.md calls this
        out)."""
        raw = "|".join((self.pass_name, self.rule, self.path,
                        self.symbol, self.detail))
        return hashlib.sha1(raw.encode()).hexdigest()[:12]

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_json(self) -> dict:
        return {"pass": self.pass_name, "rule": self.rule,
                "severity": self.severity, "path": self.path,
                "line": self.line, "symbol": self.symbol,
                "message": self.message, "fingerprint": self.fingerprint}


@dataclasses.dataclass
class ModuleInfo:
    """One parsed module."""

    name: str          # dotted ("mmlspark_tpu.sched.policy")
    path: str          # absolute
    rel_path: str      # repo-relative, forward slashes
    tree: ast.Module
    source: str


class Project:
    """The parsed package: module table + conveniences shared by passes."""

    def __init__(self, root: str, package: str):
        self.root = os.path.abspath(root)
        self.package = package
        self.modules: dict[str, ModuleInfo] = {}
        self.skipped: list[tuple[str, str]] = []  # (rel_path, why)

    @classmethod
    def load(cls, root: str, package: str = "mmlspark_tpu") -> "Project":
        """Parse every ``.py`` under ``root/package``. Unparseable files
        are recorded in ``skipped`` (and surfaced as findings by
        :func:`run_passes`) rather than aborting the whole run."""
        proj = cls(root, package)
        pkg_dir = os.path.join(proj.root, *package.split("."))
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, proj.root).replace(os.sep, "/")
                parts = rel[:-3].split("/")
                if parts[-1] == "__init__":
                    parts = parts[:-1]
                name = ".".join(parts)
                try:
                    with open(path, encoding="utf-8") as f:
                        src = f.read()
                    tree = ast.parse(src, filename=rel)
                except (SyntaxError, UnicodeDecodeError, OSError) as e:
                    proj.skipped.append((rel, f"{type(e).__name__}: {e}"))
                    continue
                proj.modules[name] = ModuleInfo(
                    name=name, path=path, rel_path=rel, tree=tree,
                    source=src)
        return proj

    def module_for_path(self, rel_path: str) -> ModuleInfo | None:
        for m in self.modules.values():
            if m.rel_path == rel_path:
                return m
        return None


class AnalysisPass:
    """Base pass: subclass, set ``name``/``description``, implement
    :meth:`run`."""

    name = "base"
    description = ""

    def run(self, project: Project) -> list[Finding]:
        raise NotImplementedError

    def finding(self, rule: str, severity: str, module: ModuleInfo,
                node: ast.AST | None, symbol: str, message: str,
                detail: str = "") -> Finding:
        return Finding(pass_name=self.name, rule=rule, severity=severity,
                       path=module.rel_path,
                       line=getattr(node, "lineno", 0) or 0,
                       symbol=symbol, message=message,
                       detail=detail or rule)


# pass registry: passes append themselves at import (order = report order)
PASS_REGISTRY: list[type[AnalysisPass]] = []


def register_pass(cls: type[AnalysisPass]) -> type[AnalysisPass]:
    if cls.name in {p.name for p in PASS_REGISTRY}:
        raise ValueError(f"duplicate pass name {cls.name!r}")
    PASS_REGISTRY.append(cls)
    return cls


def all_passes() -> list[AnalysisPass]:
    # imported here (not at module top) so core stays import-cycle-free
    from . import (trace_safety, recompile, locks, donation,  # noqa: F401
                   collectives_audit)  # noqa: F401
    return [cls() for cls in PASS_REGISTRY]


def run_passes(project: Project,
               passes: list[AnalysisPass] | None = None) -> list[Finding]:
    """Run every (or the given) pass over the project; unparseable files
    become error findings so a syntax error cannot silently shrink the
    analyzed surface."""
    out: list[Finding] = []
    for rel, why in project.skipped:
        out.append(Finding(
            pass_name="project", rule="unparseable", severity="error",
            path=rel, line=0, symbol="",
            message=f"file could not be parsed ({why}) — "
                    f"it is invisible to every pass", detail="unparseable"))
    for p in (passes if passes is not None else all_passes()):
        out.extend(p.run(project))
    order = {s: i for i, s in enumerate(SEVERITIES)}
    out.sort(key=lambda f: (order[f.severity], f.path, f.line, f.rule))
    return out
