"""Pass 5 — collective/mesh audit: raw ``lax.p*`` calls that bypass
``parallel.collectives``, and collective axis names no enclosing mesh
binds.

PR 4 routed the LightGBM histogram/vote reductions through
``parallel.collectives`` so every collective lands in the obs registry
(``parallel_collective_bytes_total{op,axis}``). A raw
``jax.lax.psum``/``ppermute``/``all_gather`` call site silently escapes
that accounting — the scrape under-reports cross-chip traffic exactly
where it matters. Rule ``raw-collective`` (warning) flags them
everywhere except ``parallel/collectives.py`` and ``parallel/compat.py``
(the blessed wrappers' own bodies).

Rule ``raw-sharding-constraint`` (warning) is the same discipline for
activation sharding: ``jax.lax.with_sharding_constraint`` (or the
``jax.experimental.pjit`` spelling) called outside ``parallel/`` skips
``parallel.compat.with_sharding_constraint`` — the one site that
handles the API-generation split, resolves bare PartitionSpecs against
the context mesh, and demotes (with a counter) axes the mesh cannot
honor. A raw call site works on today's jax and silently breaks on the
other generation.

Rule ``unbound-axis`` (error) checks literal axis names: a string axis
passed to a collective must appear among the module's declared axes
(string literals inside ``shard_map``/``Mesh``/``make_mesh``/
``PartitionSpec``/``axis_names=`` forms). A typo'd axis fails at run
time with an unbound-name error — but only on the multi-device path CI
rarely exercises, which is why it is worth proving statically. Axes
passed as variables are not checkable and are skipped; modules that
declare no axes at all are skipped too (nothing to check against).
"""

from __future__ import annotations

import ast

from .callgraph import dotted, graphs_for, resolve
from .core import AnalysisPass, Finding, ModuleInfo, Project, register_pass

COLLECTIVE_NAMES = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "axis_index", "pbroadcast"})
# modules allowed to touch lax.p* directly (the instrumented wrappers)
BLESSED = ("parallel/collectives.py", "parallel/compat.py")


def _is_collective(resolved: str | None) -> str | None:
    if not resolved:
        return None
    head, _, last = resolved.rpartition(".")
    if last in COLLECTIVE_NAMES and (
            "lax" in head.split(".") or head in ("jax.lax", "lax")):
        return last
    return None


def _is_raw_constraint(resolved: str | None) -> bool:
    """A jax-spelled ``with_sharding_constraint`` — either generation's
    home (``jax.lax`` / ``jax.experimental.pjit``) or the bare ``jax.``
    re-export; the compat wrapper's own qualname never matches."""
    if not resolved or not resolved.endswith("with_sharding_constraint"):
        return False
    head = resolved.rpartition(".")[0]
    parts = head.split(".")
    return "jax" in parts or "lax" in parts or "pjit" in parts


def _strings_in(node: ast.AST) -> set[str]:
    return {s.value for s in ast.walk(node)
            if isinstance(s, ast.Constant) and isinstance(s.value, str)
            and s.value.isidentifier()}


def _declared_axes(g, mod: ModuleInfo) -> set[str]:
    """Axis names the module provably binds. Deliberately narrow — only
    axis-bearing positions are harvested, because every over-collected
    string ('flash', a mode default…) is a typo the unbound-axis rule
    can no longer catch:

    - positional string args of ``PartitionSpec``/``P``/``NamedSharding``
      (their positionals ARE axis names);
    - the axis-names argument of ``Mesh``/``make_mesh``/``mesh`` (2nd
      positional or ``axis_names=``);
    - ``axis_names=``/``axis_name=``/``axis_resources=`` kwargs of any
      call (shard_map/pjit forms);
    - defaults of parameters whose NAME mentions axis
      (``def ring(..., axis: str = "sp")`` — callers inherit it).
    """
    axes: set[str] = set()
    spec_binders = {"PartitionSpec", "P", "NamedSharding"}
    mesh_binders = {"Mesh", "make_mesh", "mesh", "make_simple_mesh"}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            resolved = resolve(dotted(node.func), g.imports) or ""
            last = resolved.rsplit(".", 1)[-1]
            if last in spec_binders:
                for a in node.args:
                    axes |= _strings_in(a)
            elif last in mesh_binders and len(node.args) >= 2:
                axes |= _strings_in(node.args[1])
            for kw in node.keywords:
                if kw.arg in ("axis_names", "axis_name",
                              "axis_resources"):
                    axes |= _strings_in(kw.value)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            pos = args.posonlyargs + args.args
            for a, d in zip(pos[len(pos) - len(args.defaults):],
                            args.defaults):
                if "axis" in a.arg and isinstance(d, ast.Constant) \
                        and isinstance(d.value, str):
                    axes.add(d.value)
            for a, d in zip(args.kwonlyargs, args.kw_defaults):
                if d is not None and "axis" in a.arg and \
                        isinstance(d, ast.Constant) and \
                        isinstance(d.value, str):
                    axes.add(d.value)
    return axes


def _axis_literals(call: ast.Call) -> list[str]:
    """String-literal axis names handed to a collective call: the
    second positional arg (lax convention) or axis/axis_name kwargs,
    including tuples of names."""
    cands: list[ast.AST] = []
    if len(call.args) >= 2:
        cands.append(call.args[1])
    elif call.args and _last_name(call) == "axis_index":
        cands.append(call.args[0])
    for kw in call.keywords:
        if kw.arg in ("axis", "axis_name"):
            cands.append(kw.value)
    out = []
    for c in cands:
        for sub in ast.walk(c):
            if isinstance(sub, ast.Constant) and \
                    isinstance(sub.value, str):
                out.append(sub.value)
    return out


def _last_name(call: ast.Call) -> str:
    name = dotted(call.func) or ""
    return name.rsplit(".", 1)[-1]


@register_pass
class CollectiveAuditPass(AnalysisPass):
    name = "collective-audit"
    description = ("raw lax.p* collectives bypassing parallel."
                   "collectives' obs accounting; literal axis names no "
                   "mesh in the module declares")

    def run(self, project: Project) -> list[Finding]:
        graphs = graphs_for(project)
        out: list[Finding] = []
        for mod in project.modules.values():
            g = graphs.of(mod)
            blessed = any(mod.rel_path.endswith(b) for b in BLESSED)
            axes = None  # computed lazily per module
            for fi in g.functions.values():
                for call in g._own_calls(fi.node):
                    resolved = resolve(dotted(call.func), g.imports)
                    if not blessed and _is_raw_constraint(resolved):
                        out.append(self.finding(
                            "raw-sharding-constraint", "warning", mod,
                            call, fi.qualname,
                            f"raw with_sharding_constraint in "
                            f"{fi.qualname!r} bypasses parallel.compat "
                            f"— no API-generation split, no context-"
                            f"mesh spec resolution, no demotion "
                            f"accounting", detail=resolved))
                        continue
                    op = _is_collective(resolved)
                    if op is None:
                        continue
                    if not blessed:
                        out.append(self.finding(
                            "raw-collective", "warning", mod, call,
                            fi.qualname,
                            f"raw jax.lax.{op} in {fi.qualname!r} "
                            f"bypasses parallel.collectives — this "
                            f"transfer never lands in parallel_"
                            f"collective_bytes_total (obs accounting)",
                            detail=op))
                    if axes is None:
                        axes = _declared_axes(g, mod)
                    if axes:
                        for lit in _axis_literals(call):
                            if lit not in axes:
                                out.append(self.finding(
                                    "unbound-axis", "error", mod, call,
                                    fi.qualname,
                                    f"axis {lit!r} in jax.lax.{op} is "
                                    f"not declared by any mesh/"
                                    f"shard_map/PartitionSpec in this "
                                    f"module (known: "
                                    f"{', '.join(sorted(axes))})",
                                    detail=f"{op}:{lit}"))
        return out
