"""Pass 4 — donation/aliasing: donated buffers read after the call, and
train steps that forget to donate at all.

``donate_argnums`` lets XLA reuse an input buffer for an output — the
difference between 1× and 2× peak memory for optimizer state. The two
failure modes:

- ``use-after-donate`` (error): the caller passes a name into a
  donated position and then reads that name again. JAX marks the buffer
  deleted; the read raises (or silently sees garbage under some
  transfer guards). Only provable when the wrap and the call share a
  scope and the argument is a plain name — exactly the
  ``state = step(state, batch)`` shape train loops use.
- ``missing-donation`` (warning): a ``jit``/``pjit`` wrap of a function
  whose name says it is a train/update step (``*train_step*``,
  ``*update*``, ``*step_fn*``) with no ``donate_argnums``: the step
  carries its state twice. The fix is one kwarg; the baseline is for
  steps that genuinely must keep their input (e.g. trajectory pinning
  comparisons in tests).
"""

from __future__ import annotations

import ast

from .callgraph import dotted, graphs_for, resolve
from .core import AnalysisPass, Finding, ModuleInfo, Project, register_pass

STEP_NAME_HINTS = ("train_step", "update_step", "step_fn", "opt_step")


def _donated_nums(call: ast.Call) -> list[int]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return [n.value for n in ast.walk(kw.value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, int)]
    return []


@register_pass
class DonationPass(AnalysisPass):
    name = "donation"
    description = ("donated buffers used after the donating call; "
                   "train-step wraps with no donate_argnums")

    def run(self, project: Project) -> list[Finding]:
        graphs = graphs_for(project)
        out: list[Finding] = []
        for mod in project.modules.values():
            g = graphs.of(mod)
            for fi in g.functions.values():
                out.extend(self._use_after_donate(g, mod, fi))
            out.extend(self._missing_donation(g, mod))
        return out

    # -- use-after-donate ---------------------------------------------------
    def _use_after_donate(self, g, mod: ModuleInfo, fi) -> list[Finding]:
        """Within one function body: ``step = jit(f, donate_argnums=…)``
        …… ``out = step(x, …)`` …… later load of ``x``."""
        wrapped: dict[str, list[int]] = {}   # local name -> donated nums
        out: list[Finding] = []
        #: donated arg name -> (line of donating call, callee name)
        donated_at: dict[str, tuple[int, str]] = {}

        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                resolved = resolve(dotted(node.value.func), g.imports)
                if resolved and resolved.rsplit(".", 1)[-1] in \
                        ("jit", "pjit") and len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    nums = _donated_nums(node.value)
                    if nums:
                        wrapped[node.targets[0].id] = nums

        if not wrapped:
            return out
        # single linear sweep in line order: calls bind donations, later
        # Name loads of a donated arg fire. Loops re-binding the name
        # (state = step(state, …)) clear the donation on the STORE.
        events: list[tuple[int, str, object]] = []
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in wrapped:
                events.append((node.lineno, "call", node))
            elif isinstance(node, ast.Name):
                kind = ("load" if isinstance(node.ctx, ast.Load)
                        else "store")
                events.append((node.lineno, kind, node))
        # within one line, execution order is loads → the call → the
        # store: `state = step(state, b)` rebinds AFTER donating, so
        # the store must clear the fresh donation, not precede it
        prio = {"load": 0, "call": 1, "store": 2}
        events.sort(key=lambda e: (e[0], prio[e[1]]))
        for line, kind, node in events:
            if kind == "call":
                # register at the call's END line so a multi-line call's
                # own argument loads never read as use-after-donate
                end = getattr(node, "end_lineno", line) or line
                for i in wrapped[node.func.id]:
                    if i < len(node.args) and \
                            isinstance(node.args[i], ast.Name):
                        donated_at[node.args[i].id] = (end,
                                                       node.func.id)
            elif kind == "store" and node.id in donated_at:
                del donated_at[node.id]     # rebound: fresh buffer
            elif kind == "load" and node.id in donated_at:
                dline, callee = donated_at[node.id]
                if line > dline:
                    out.append(self.finding(
                        "use-after-donate", "error", mod, node,
                        fi.qualname,
                        f"{node.id!r} was donated to {callee!r} (line "
                        f"{dline}) and read again here: the buffer is "
                        f"deleted after the call",
                        detail=f"{callee}:{node.id}"))
                    del donated_at[node.id]  # one finding per donation
        return out

    # -- missing-donation ---------------------------------------------------
    def _missing_donation(self, g, mod: ModuleInfo) -> list[Finding]:
        out = []
        for q, wraps in sorted(g.traced_entries.items()):
            base = q.rsplit(".", 1)[-1].lower()
            if not any(h in base for h in STEP_NAME_HINTS):
                continue
            for wrap in wraps:
                if wrap is None:
                    continue
                resolved = resolve(dotted(wrap.func), g.imports) or ""
                if resolved.rsplit(".", 1)[-1] not in ("jit", "pjit"):
                    continue
                if not any(kw.arg == "donate_argnums"
                           for kw in wrap.keywords):
                    out.append(self.finding(
                        "missing-donation", "warning", mod, wrap, q,
                        f"train step {q!r} is wrapped without "
                        f"donate_argnums: optimizer/param state is held "
                        f"twice per step (2x peak memory)", detail=q))
        return out
