"""Report rendering: human text and machine JSON."""

from __future__ import annotations

import json

from .core import Finding

_SEV_ORDER = {"error": 0, "warning": 1, "info": 2}


def summarize(findings: list[Finding]) -> dict:
    by_pass: dict[str, int] = {}
    by_sev: dict[str, int] = {}
    for f in findings:
        by_pass[f.pass_name] = by_pass.get(f.pass_name, 0) + 1
        by_sev[f.severity] = by_sev.get(f.severity, 0) + 1
    return {"total": len(findings), "by_pass": by_pass,
            "by_severity": by_sev}


def render_text(unbaselined: list[Finding], suppressed: list[Finding],
                stale: list[dict], modules: int) -> str:
    lines: list[str] = []
    lines.append(f"graftcheck: {modules} modules analyzed, "
                 f"{len(unbaselined)} unbaselined finding(s), "
                 f"{len(suppressed)} baselined, "
                 f"{len(stale)} stale baseline entr"
                 f"{'y' if len(stale) == 1 else 'ies'}")
    current_pass = None
    for f in sorted(unbaselined,
                    key=lambda f: (f.pass_name, _SEV_ORDER[f.severity],
                                   f.path, f.line)):
        if f.pass_name != current_pass:
            current_pass = f.pass_name
            lines.append("")
            lines.append(f"[{f.pass_name}]")
        lines.append(f"  {f.severity.upper():7s} {f.location()} "
                     f"[{f.rule}] ({f.fingerprint})")
        lines.append(f"          {f.message}")
    if stale:
        lines.append("")
        lines.append("stale baseline entries (fix landed? delete them):")
        for e in stale:
            lines.append(f"  {e['fingerprint']} [{e.get('rule', '?')}] "
                         f"{e.get('path', '?')} :: "
                         f"{e.get('symbol', '')}")
    if not unbaselined:
        lines.append("gate: CLEAN")
    else:
        lines.append("")
        lines.append(
            "gate: FAIL — fix the findings above, or baseline them WITH "
            "a justification (--write-baseline, then edit the TODOs; "
            "see docs/analysis.md)")
    return "\n".join(lines) + "\n"


def render_json(unbaselined: list[Finding], suppressed: list[Finding],
                stale: list[dict], modules: int) -> str:
    payload = {
        "version": 1,
        "modules_analyzed": modules,
        "summary": summarize(unbaselined),
        "findings": [f.to_json() for f in unbaselined],
        "suppressed": [f.to_json() for f in suppressed],
        "stale_baseline": stale,
        "gate": "clean" if not unbaselined else "fail",
    }
    return json.dumps(payload, indent=2) + "\n"
