"""Pass 3 — lock discipline: mutations of shared state that dodge the
class's own lock.

The control plane (obs registry, scheduler queues, breaker maps, the
serving mesh's lease table) is mutated from handler threads, executor
threads, and monitor threads at once. The convention the codebase
follows — and this pass turns into a contract — is: *a class that owns
a lock routes every mutation of its shared attributes through it*.

Two rules:

- ``lock-inconsistent`` (error): an attribute is mutated under
  ``with self._lock`` in one method and WITHOUT it in another. The
  guarded sites prove the author considers the attribute shared; the
  unguarded one is the bug (or needs a written justification).
- ``lock-unguarded`` (warning): a mutable container attribute
  (dict/list/set/deque assigned in ``__init__``) of a lock-owning class
  is mutated from two or more methods, never under any lock. Multiple
  mutating methods on a lock-owning class almost always means multiple
  threads (the single-writer case is one method).

What does NOT fire: reads (they are a different, rarer contract);
``__init__``/``__post_init__`` (the object is not shared yet); methods
whose every intra-class call site is inside a ``with``-lock block or in
another such method (transitively) — the ``_locked``-suffix helper
pattern (``_append_locked``, ``_check_reset_locked``) is recognized
both by that call-site analysis and by the name suffix itself.
"""

from __future__ import annotations

import ast

from .callgraph import dotted, graphs_for, resolve
from .core import AnalysisPass, Finding, ModuleInfo, Project, register_pass

LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition"})
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "remove",
    "discard", "pop", "popitem", "popleft", "clear", "update",
    "setdefault", "sort", "reverse"})
CONTAINER_FACTORIES = frozenset({
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
    "Counter"})
INIT_METHODS = frozenset({"__init__", "__post_init__", "__new__"})


def _lock_factory_name(value: ast.AST) -> bool:
    if isinstance(value, ast.Call):
        name = dotted(value.func)
        if name and name.rsplit(".", 1)[-1] in LOCK_FACTORIES:
            return True
        # dataclass field(default_factory=threading.Lock)
        if name and name.rsplit(".", 1)[-1] == "field":
            for kw in value.keywords:
                if kw.arg == "default_factory":
                    fac = dotted(kw.value)
                    if fac and fac.rsplit(".", 1)[-1] in LOCK_FACTORIES:
                        return True
    return False


def _self_attr(node: ast.AST) -> str | None:
    """``self.X`` (or a subscript/attribute path rooted there) → X."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            return node.attr
        node = node.value
    return None


class _ClassModel:
    """Locks, per-method mutations (with held-lock context), and the
    intra-class held-call graph for one class."""

    def __init__(self, mod: ModuleInfo, cls: ast.ClassDef):
        self.mod = mod
        self.cls = cls
        self.locks: set[str] = set()          # self.<name> lock attrs
        self.container_attrs: set[str] = set()
        #: method -> list of (attr, node, frozenset(held_locks), how)
        self.mutations: dict[str, list] = {}
        #: method -> {callee_method: set of frozensets of held locks}
        self.held_calls: dict[str, dict[str, set[frozenset]]] = {}
        self.methods: dict[str, ast.AST] = {}
        self._collect()

    def scan(self) -> None:
        """Scan method bodies. Called AFTER the pass has merged
        inherited locks in (a subclass of a lock-owning base guards
        with ``self._lock`` it never declared itself)."""
        for name, fn in self.methods.items():
            self._scan_method(name, fn)

    def _collect(self) -> None:
        for node in self.cls.body:
            # class-body lock declarations (dataclass fields)
            if isinstance(node, ast.AnnAssign) and node.value is not None \
                    and isinstance(node.target, ast.Name):
                if _lock_factory_name(node.value):
                    self.locks.add(node.target.id)
            elif isinstance(node, ast.Assign) and node.value is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name) and \
                            _lock_factory_name(node.value):
                        self.locks.add(t.id)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[node.name] = node
        # __init__-time lock + container discovery
        for m in INIT_METHODS | {"_init_shared_state"}:
            fn = self.methods.get(m)
            if fn is None:
                continue
            for stmt in ast.walk(fn):
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    targets = stmt.targets if isinstance(
                        stmt, ast.Assign) else [stmt.target]
                    value = stmt.value
                    if value is None:
                        continue
                    for t in targets:
                        attr = _self_attr(t) if isinstance(
                            t, ast.Attribute) else None
                        if attr is None:
                            continue
                        if _lock_factory_name(value):
                            self.locks.add(attr)
                        elif self._container_value(value):
                            self.container_attrs.add(attr)

    @staticmethod
    def _container_value(value: ast.AST) -> bool:
        if isinstance(value, (ast.Dict, ast.List, ast.Set)):
            return True
        if isinstance(value, ast.Call):
            name = dotted(value.func)
            return bool(name) and \
                name.rsplit(".", 1)[-1] in CONTAINER_FACTORIES
        return False

    def _scan_method(self, name: str, fn: ast.AST) -> None:
        muts: list = []
        calls: dict[str, set[frozenset]] = {}

        def walk(node, held: frozenset):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # nested defs execute later, context unknown
                now_held = held
                if isinstance(child, ast.With):
                    acquired = set()
                    for item in child.items:
                        attr = _self_attr(item.context_expr)
                        if attr in self.locks:
                            acquired.add(attr)
                    now_held = held | frozenset(acquired)
                self._record(child, now_held, muts, calls)
                walk(child, now_held)

        walk(fn, frozenset())
        self.mutations[name] = muts
        self.held_calls[name] = calls

    def _record(self, node, held, muts, calls) -> None:
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            flat = []
            for t in targets:
                if isinstance(t, (ast.Tuple, ast.List)):
                    flat.extend(t.elts)
                else:
                    flat.append(t)
            for t in flat:
                attr = _self_attr(t)
                if attr is not None and attr not in self.locks:
                    how = ("augassign" if isinstance(node, ast.AugAssign)
                           else "assign")
                    # self.x[k] = v is a container mutation of x
                    if isinstance(t, ast.Subscript):
                        how = "setitem"
                    muts.append((attr, node, held, how))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    muts.append((attr, node, held, "del"))
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in MUTATOR_METHODS:
                attr = _self_attr(f.value)
                if attr is not None:
                    muts.append((attr, node, held, f".{f.attr}"))
            elif isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in ("self", "cls") and \
                    f.attr in self.methods:
                calls.setdefault(f.attr, set()).add(held)

    def always_held(self) -> dict[str, frozenset]:
        """method → set of locks provably held at EVERY intra-class call
        site (transitively). Methods never called intra-class hold
        nothing (they are external entry points) — unless their name
        carries the ``_locked`` convention suffix, which documents the
        contract explicitly."""
        held: dict[str, frozenset] = {}
        for name in self.methods:
            if name.endswith("_locked"):
                held[name] = frozenset(self.locks)
        for _ in range(len(self.methods) + 1):
            changed = False
            for name in self.methods:
                if name in held and held[name] == frozenset(self.locks):
                    continue
                sites: list[frozenset] = []
                for caller, callees in self.held_calls.items():
                    for callee, heldsets in callees.items():
                        if callee != name:
                            continue
                        for h in heldsets:
                            sites.append(h | held.get(caller,
                                                      frozenset()))
                if not sites:
                    continue
                common = frozenset.intersection(*map(frozenset, sites))
                prev = held.get(name)
                new = common | (prev or frozenset())
                if new != prev:
                    held[name] = new
                    changed = True
            if not changed:
                break
        return held


@register_pass
class LockDisciplinePass(AnalysisPass):
    name = "lock-discipline"
    description = ("mutations of lock-owning classes' shared attributes "
                   "outside the lock (inconsistent or never-guarded)")

    def run(self, project: Project) -> list[Finding]:
        graphs = graphs_for(project)
        # project-wide top-level class models, for inherited-lock
        # resolution (a DistributedServingServer guards with the
        # self._lock its ServingServer base created)
        models: dict[tuple[str, str], _ClassModel] = {}
        by_name: dict[str, list[tuple[str, str]]] = {}
        for mod in project.modules.values():
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    key = (mod.name, node.name)
                    models[key] = _ClassModel(mod, node)
                    by_name.setdefault(node.name, []).append(key)

        def base_keys(key: tuple[str, str]) -> list[tuple[str, str]]:
            mod_name, _ = key
            model = models[key]
            g = graphs.of(model.mod)
            out = []
            for base in model.cls.bases:
                name = resolve(dotted(base), g.imports)
                if not name:
                    continue
                bmod, _, bcls = name.rpartition(".")
                if (bmod, bcls) in models:
                    out.append((bmod, bcls))
                elif len(by_name.get(name.rsplit(".", 1)[-1], [])) == 1:
                    out.append(by_name[name.rsplit(".", 1)[-1]][0])
            return out

        def inherited_locks(key, seen=None) -> set[str]:
            seen = seen or set()
            if key in seen:
                return set()
            seen.add(key)
            locks = set(models[key].locks)
            for bk in base_keys(key):
                locks |= inherited_locks(bk, seen)
            return locks

        out: list[Finding] = []
        for key in sorted(models):
            model = models[key]
            model.locks = inherited_locks(key)
            model.scan()
            out.extend(self._check_class(model))
        return out

    def _check_class(self, model: "_ClassModel") -> list[Finding]:
        mod, cls = model.mod, model.cls
        if not model.locks:
            return []
        held_map = model.always_held()
        # guarded = attrs mutated under a lock in ≥1 non-init method
        guarded: dict[str, str] = {}
        for m, muts in model.mutations.items():
            if m in INIT_METHODS:
                continue
            eff = held_map.get(m, frozenset())
            for attr, node, held, how in muts:
                locks = held | eff
                if locks:
                    guarded.setdefault(attr, sorted(locks)[0])
        out: list[Finding] = []
        unguarded_sites: dict[str, list] = {}
        for m, muts in model.mutations.items():
            if m in INIT_METHODS or m == "_init_shared_state":
                continue
            eff = held_map.get(m, frozenset())
            for attr, node, held, how in muts:
                if held or eff:
                    continue
                if attr in guarded:
                    out.append(self.finding(
                        "lock-inconsistent", "error", mod, node,
                        f"{cls.name}.{m}",
                        f"{cls.name}.{attr} is guarded by self."
                        f"{guarded[attr]} elsewhere but mutated here "
                        f"({how}) without it",
                        detail=f"{attr}:{how}"))
                else:
                    unguarded_sites.setdefault(attr, []).append(
                        (m, node, how))
        for attr, sites in sorted(unguarded_sites.items()):
            methods = {m for m, _, _ in sites}
            if attr in model.container_attrs and len(methods) >= 2:
                m, node, how = sites[0]
                out.append(self.finding(
                    "lock-unguarded", "warning", mod, node,
                    f"{cls.name}.{m}",
                    f"{cls.name}.{attr} (shared container) is mutated "
                    f"from {len(methods)} methods "
                    f"({', '.join(sorted(methods))}) and never under "
                    f"any of this class's locks "
                    f"({', '.join(sorted(model.locks))})",
                    detail=attr))
        return out
