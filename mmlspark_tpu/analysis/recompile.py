"""Pass 2 — recompile hazards: code shapes that make XLA re-trace or
re-compile per call instead of once.

The serving stack exists because compiles at request latency are
catastrophic (``serving.bucket_pad``'s docstring measures p99 96 ms →
5 ms once shapes stop being novel). The hazards this pass can prove
statically:

- ``jit-in-loop`` — a ``jit``/``pjit``/``shard_map`` wrap call inside a
  ``for``/``while`` body builds a NEW wrapped callable (and cache entry)
  every iteration; hoist the wrap out of the loop.
- ``traced-branch`` — Python ``if``/``while`` comparing a traced
  parameter's *value* inside a wrapped function: every distinct outcome
  re-traces (or throws ``ConcretizationTypeError`` outright). Static
  facts — ``x is None``, ``x.shape``/``ndim``/``dtype``, ``len(x)`` —
  are exempt (they are trace-time constants).
- ``traced-concretize`` — ``bool()/int()/float()`` applied to a traced
  parameter expression inside a wrapped function: concretization, the
  same failure spelled differently.
- ``unhashable-static`` — ``static_argnums`` pointing at a parameter
  whose default is a list/dict/set: every call raises (static args are
  cache keys and must hash).

Parameters that are *obviously* static are skipped: named in
``static_argnums``/``static_argnames`` at the wrap site, annotated with
a Python scalar type (``bool``/``int``/``str``), or defaulted to a
Python constant — branching on those is exactly what static args are
for.
"""

from __future__ import annotations

import ast

from .callgraph import (FuncInfo, ModuleGraph, dotted, graphs_for,
                        resolve)
from .core import AnalysisPass, Finding, ModuleInfo, Project, register_pass

_STATIC_ANNOTATIONS = {"bool", "int", "str", "float"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}


def _static_params(fi: FuncInfo,
                   wraps: list[ast.Call | None]) -> set[str]:
    """Parameter names the wrap sites mark static, plus annotation/
    default-based obviously-static ones."""
    static: set[str] = set()
    pos = fi.positional_params
    for wrap in wraps:
        if wrap is None:
            continue
        for kw in wrap.keywords:
            if kw.arg == "static_argnums":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and \
                            isinstance(n.value, int) and \
                            0 <= n.value < len(pos):
                        static.add(pos[n.value])
            elif kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and \
                            isinstance(n.value, str):
                        static.add(n.value)
    args = fi.node.args
    for a in args.posonlyargs + args.args + args.kwonlyargs:
        ann = dotted(a.annotation) if a.annotation is not None else None
        if ann and ann.rsplit(".", 1)[-1] in _STATIC_ANNOTATIONS:
            static.add(a.arg)
    defaults = args.defaults
    params_with_defaults = (args.posonlyargs + args.args)[
        len(args.posonlyargs) + len(args.args) - len(defaults):]
    for a, d in zip(params_with_defaults, defaults):
        if isinstance(d, ast.Constant) and not isinstance(d.value,
                                                          (bytes,)):
            if isinstance(d.value, (bool, int, str, float, type(None))):
                static.add(a.arg)
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if isinstance(d, ast.Constant) and \
                isinstance(d.value, (bool, int, str, float, type(None))):
            static.add(a.arg)
    return static


def _param_rooted(expr: ast.AST, params: set[str]) -> str | None:
    """The parameter name an expression reads through value-land (not
    through static attributes like ``.shape``). Returns None when the
    expression cannot reach a traced parameter's values."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return None  # rooted in a static fact, not values
        if isinstance(node, ast.Call) and dotted(node.func) == "len":
            return None  # len(tracer) is its static leading dim
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in params:
            return node.id
    return None


class _FnScanner(ast.NodeVisitor):
    def __init__(self, pass_, mod, fi: FuncInfo, params: set[str]):
        self.pass_ = pass_
        self.mod = mod
        self.fi = fi
        self.params = params
        self.findings: list[Finding] = []

    def _flag_test(self, test: ast.AST, kind: str) -> None:
        # exempt static shapes of test: `x is None`, pure static attrs
        if isinstance(test, ast.Compare) and \
                any(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops):
            return
        if not isinstance(test, (ast.Compare, ast.BoolOp, ast.UnaryOp)):
            return  # bare-name truthiness: usually a static flag — skip
        p = _param_rooted(test, self.params)
        if p is not None:
            self.findings.append(self.pass_.finding(
                "traced-branch", "error", self.mod, test,
                self.fi.qualname,
                f"Python {kind} on traced parameter {p!r} inside "
                f"{self.fi.qualname!r}: re-traces per outcome (or raises "
                f"ConcretizationTypeError) — use lax.cond/lax.while_loop "
                f"or mark the arg static", detail=f"{kind}:{p}"))

    def visit_If(self, node: ast.If) -> None:
        self._flag_test(node.test, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._flag_test(node.test, "while")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._flag_test(node.test, "if")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fname = dotted(node.func)
        if fname in ("bool", "int", "float") and len(node.args) == 1:
            p = _param_rooted(node.args[0], self.params)
            if p is not None:
                self.findings.append(self.pass_.finding(
                    "traced-concretize", "error", self.mod, node,
                    self.fi.qualname,
                    f"{fname}() concretizes traced parameter {p!r} "
                    f"inside {self.fi.qualname!r}",
                    detail=f"{fname}:{p}"))
        self.generic_visit(node)


@register_pass
class RecompilePass(AnalysisPass):
    name = "recompile-hazard"
    description = ("jit-in-loop rewraps, Python branches on traced "
                   "values, concretizing casts, unhashable static args")

    def run(self, project: Project) -> list[Finding]:
        graphs = graphs_for(project)
        out: list[Finding] = []
        for mod in project.modules.values():
            g = graphs.of(mod)
            out.extend(self._jit_in_loop(g, mod))
            for q, wraps in sorted(g.traced_entries.items()):
                fi = g.functions.get(q)
                if fi is None:
                    continue
                static = _static_params(fi, wraps)
                params = {p for p in fi.params
                          if p not in static and p not in ("self", "cls")}
                sc = _FnScanner(self, mod, fi, params)
                for stmt in fi.node.body:
                    sc.visit(stmt)
                out.extend(sc.findings)
                out.extend(self._unhashable_static(g, mod, fi, wraps))
        return out

    def _jit_in_loop(self, g: ModuleGraph, mod: ModuleInfo
                     ) -> list[Finding]:
        out = []

        def walk(node, in_loop: bool, symbol: str):
            for child in ast.iter_child_nodes(node):
                sym = symbol
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    # a def resets loop context (the body runs at call
                    # time, not per enclosing-loop iteration)
                    walk(child, False, child.name)
                    continue
                loop = in_loop or isinstance(child, (ast.For, ast.While))
                if isinstance(child, ast.Call) and in_loop:
                    resolved = resolve(dotted(child.func), g.imports)
                    if resolved and resolved.rsplit(".", 1)[-1] in \
                            ("jit", "pjit", "shard_map", "pallas_call"):
                        out.append(self.finding(
                            "jit-in-loop", "warning", mod, child, sym,
                            f"{resolved} called inside a loop: builds a "
                            f"new wrapped callable (and trace-cache "
                            f"entry) per iteration — hoist the wrap",
                            detail=resolved))
                walk(child, loop, sym)

        walk(mod.tree, False, "")
        return out

    def _unhashable_static(self, g: ModuleGraph, mod: ModuleInfo,
                           fi: FuncInfo, wraps: list[ast.Call | None]
                           ) -> list[Finding]:
        out = []
        args = fi.node.args
        defaults = dict(zip(
            [a.arg for a in (args.posonlyargs + args.args)[
                len(args.posonlyargs) + len(args.args)
                - len(args.defaults):]], args.defaults))
        defaults.update({a.arg: d for a, d in zip(args.kwonlyargs,
                                                  args.kw_defaults)
                         if d is not None})
        pos = fi.positional_params
        for wrap in wraps:
            if wrap is None:
                continue
            named: list[str] = []
            for kw in wrap.keywords:
                if kw.arg == "static_argnums":
                    named += [pos[n.value] for n in ast.walk(kw.value)
                              if isinstance(n, ast.Constant)
                              and isinstance(n.value, int)
                              and 0 <= n.value < len(pos)]
                elif kw.arg == "static_argnames":
                    named += [n.value for n in ast.walk(kw.value)
                              if isinstance(n, ast.Constant)
                              and isinstance(n.value, str)]
            for p in named:
                d = defaults.get(p)
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    out.append(self.finding(
                        "unhashable-static", "error", mod, wrap,
                        fi.qualname,
                        f"static arg {p!r} of {fi.qualname!r} defaults "
                        f"to an unhashable "
                        f"{type(d).__name__.lower()} — static args are "
                        f"cache keys and must hash", detail=p))
        return out
