"""Name resolution, wrap-site discovery, and the module-level call graph.

Everything here is best-effort *static* resolution over ``ast``: a name
is resolved through the module's import table (``import jax.numpy as
jnp`` makes ``jnp.dot`` resolve to ``jax.numpy.dot``), calls on ``self``
resolve to methods of the enclosing class, and bare names resolve to
module-level functions or single-hop ``from .mod import fn`` imports
inside the analyzed package. Anything dynamic (getattr, dict dispatch,
re-bound callables) stays unresolved — passes must treat "unresolved"
as "unknown", never as "safe" or "unsafe".

The central artifact is the set of **traced regions**: functions wrapped
by (or decorated with) ``jit`` / ``pjit`` / ``shard_map`` /
``pallas_call`` — the compile boundaries the ROADMAP's whole-pipeline
compilation item cares about — plus everything reachable from them
through the call graph (bounded depth). Lambdas handed straight to a
wrapper are traced regions of their enclosing function.
"""

from __future__ import annotations

import ast
import dataclasses

from .core import ModuleInfo, Project

# call targets (last dotted component) that wrap a Python callable into
# a traced/staged computation. `vmap`/`grad` trace too, but they are
# almost always re-wrapped in jit at the real boundary — listing them
# would double-count the same region.
WRAP_NAMES = frozenset({"jit", "pjit", "shard_map", "pallas_call"})

# how deep reachability walks from a traced entry. Two hops catches the
# helper-inside-a-step pattern without dragging in half the package
# through utility fan-out (each hop multiplies false-positive surface:
# a deep callee may be host-side when called from elsewhere).
REACH_DEPTH = 4


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` attribute chains / bare names → dotted string."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_table(tree: ast.Module, module_name: str) -> dict[str, str]:
    """local alias → fully dotted origin. Relative imports are resolved
    against ``module_name`` so ``from ..obs import registry`` inside
    ``mmlspark_tpu.sched.policy`` maps ``registry`` →
    ``mmlspark_tpu.obs.registry``."""
    table: dict[str, str] = {}
    pkg_parts = module_name.split(".")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    table[a.asname] = a.name       # jnp -> jax.numpy
                else:
                    head = a.name.split(".")[0]
                    table[head] = head             # import a.b binds `a`
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[:-node.level] if node.level <= len(
                    pkg_parts) else []
                prefix = ".".join(base + ([node.module]
                                          if node.module else []))
            else:
                prefix = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                origin = f"{prefix}.{a.name}" if prefix else a.name
                table[a.asname or a.name] = origin
    return table


def resolve(name: str | None, imports: dict[str, str]) -> str | None:
    """Expand the leading component of a dotted name through the import
    table (``jnp.dot`` → ``jax.numpy.dot``)."""
    if name is None:
        return None
    head, _, rest = name.partition(".")
    origin = imports.get(head)
    if origin is None:
        return name
    return f"{origin}.{rest}" if rest else origin


@dataclasses.dataclass
class FuncInfo:
    """One function/method definition."""

    qualname: str                  # "Class.method" / "fn" / "fn.<locals>.g"
    node: ast.AST                  # FunctionDef | AsyncFunctionDef | Lambda
    class_name: str | None = None  # enclosing class, if a method

    @property
    def params(self) -> list[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        return names

    @property
    def positional_params(self) -> list[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args]
        if names and names[0] in ("self", "cls"):
            names = names[1:]
        return names


class ModuleGraph:
    """Per-module function index + call graph + traced entries."""

    def __init__(self, module: ModuleInfo):
        self.module = module
        self.imports = import_table(module.tree, module.name)
        self.functions: dict[str, FuncInfo] = {}
        #: caller qualname -> set of locally-resolved callee qualnames
        self.calls: dict[str, set[str]] = {}
        #: callee qualname -> list of (caller qualname, Call node)
        self.call_sites: dict[str, list[tuple[str, ast.Call]]] = {}
        #: qualnames wrapped by jit/pjit/shard_map/pallas_call, with the
        #: wrap Call node (None for decorators carrying no call)
        self.traced_entries: dict[str, list[ast.Call | None]] = {}
        self._index()
        self._find_wraps()

    # -- indexing -----------------------------------------------------------
    def _index(self) -> None:
        module = self.module

        def visit_body(body, prefix: str, class_name: str | None):
            for node in body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    q = f"{prefix}{node.name}"
                    self.functions[q] = FuncInfo(q, node, class_name)
                    visit_body(node.body, f"{q}.<locals>.", class_name)
                elif isinstance(node, ast.ClassDef):
                    visit_body(node.body, f"{node.name}.", node.name)
                elif isinstance(node, (ast.If, ast.Try, ast.With,
                                       ast.For, ast.While)):
                    for field in ("body", "orelse", "finalbody",
                                  "handlers"):
                        sub = getattr(node, field, [])
                        for item in sub:
                            if isinstance(item, ast.ExceptHandler):
                                visit_body(item.body, prefix, class_name)
                        if sub and not isinstance(sub[0],
                                                  ast.ExceptHandler):
                            visit_body(sub, prefix, class_name)

        visit_body(module.tree.body, "", None)
        # call edges: walk each function's own statements (not nested
        # defs' — those have their own entry)
        for q, fi in self.functions.items():
            callees: set[str] = set()
            for call in self._own_calls(fi.node):
                target = self._resolve_local_callee(call, fi)
                if target is not None:
                    callees.add(target)
                    self.call_sites.setdefault(target, []).append((q, call))
            self.calls[q] = callees

    def _own_calls(self, root: ast.AST) -> list[ast.Call]:
        """Every Call lexically inside ``root`` but NOT inside a nested
        def (nested defs are separate function entries)."""
        out: list[ast.Call] = []

        def walk(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                if isinstance(child, ast.Call):
                    out.append(child)
                walk(child)

        walk(root)
        return out

    def _resolve_local_callee(self, call: ast.Call,
                              caller: FuncInfo) -> str | None:
        """Resolve a call target to a qualname in THIS module (methods
        via self/cls, bare module-level names, nested defs)."""
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in ("self", "cls") and caller.class_name:
            q = f"{caller.class_name}.{f.attr}"
            return q if q in self.functions else None
        if isinstance(f, ast.Name):
            q = f"{caller.qualname}.<locals>.{f.id}"
            if q in self.functions:
                return q
            if f.id in self.functions:
                return f.id
        return None

    # -- wrap-site discovery ------------------------------------------------
    def resolve_call_name(self, call: ast.Call) -> str | None:
        return resolve(dotted(call.func), self.imports)

    def _is_wrap(self, resolved: str | None) -> bool:
        if resolved is None:
            return False
        last = resolved.rsplit(".", 1)[-1]
        return last in WRAP_NAMES

    def _wrapped_target(self, call: ast.Call) -> ast.AST | None:
        """The callable a wrap call stages: first positional arg, or the
        ``partial(jit, ...)`` / keyword ``fun=`` forms."""
        if call.args:
            return call.args[0]
        for kw in call.keywords:
            if kw.arg in ("fun", "f", "func", "kernel"):
                return kw.value
        return None

    def _mark_traced(self, target: ast.AST, wrap_call: ast.Call | None,
                     scope: FuncInfo | None) -> None:
        if isinstance(target, ast.Lambda):
            # a lambda handed to jit: treat the ENCLOSING function as
            # hosting a traced region (the lambda body is its code)
            if scope is not None:
                self.traced_entries.setdefault(
                    scope.qualname, []).append(wrap_call)
            return
        name = dotted(target)
        if name is None:
            return
        candidates = []
        if scope is not None:
            candidates.append(f"{scope.qualname}.<locals>.{name}")
            if scope.class_name and name.startswith("self."):
                candidates.append(
                    f"{scope.class_name}.{name.split('.', 1)[1]}")
        candidates.append(name)
        for q in candidates:
            if q in self.functions:
                self.traced_entries.setdefault(q, []).append(wrap_call)
                return

    def _find_wraps(self) -> None:
        # decorators: @jit / @partial(jit, ...) / @jax.jit
        for q, fi in self.functions.items():
            for dec in getattr(fi.node, "decorator_list", []):
                resolved = resolve(dotted(dec), self.imports)
                if self._is_wrap(resolved):
                    self.traced_entries.setdefault(q, []).append(None)
                elif isinstance(dec, ast.Call):
                    dec_name = resolve(dotted(dec.func), self.imports)
                    if self._is_wrap(dec_name):
                        self.traced_entries.setdefault(q, []).append(dec)
                    elif dec_name and dec_name.rsplit(".", 1)[-1] \
                            == "partial" and dec.args:
                        inner = resolve(dotted(dec.args[0]), self.imports)
                        if self._is_wrap(inner):
                            self.traced_entries.setdefault(
                                q, []).append(dec)
        # call-form wraps: jit(fn, ...) anywhere in the module (the
        # module-level scope covers class bodies and top-level code)
        scopes: list[tuple[FuncInfo | None, ast.AST]] = [
            (None, self.module.tree)]
        scopes += [(fi, fi.node) for fi in self.functions.values()]
        for scope, root in scopes:
            for call in self._own_calls(root):
                resolved = self.resolve_call_name(call)
                if not self._is_wrap(resolved):
                    # partial(jit, ...) in call position
                    if resolved and resolved.rsplit(".", 1)[-1] \
                            == "partial" and call.args:
                        inner = resolve(dotted(call.args[0]), self.imports)
                        if self._is_wrap(inner) and len(call.args) > 1:
                            self._mark_traced(call.args[1], call, scope)
                    continue
                target = self._wrapped_target(call)
                if target is not None:
                    self._mark_traced(target, call, scope)

    # -- reachability -------------------------------------------------------
    def traced_functions(self, depth: int = REACH_DEPTH
                         ) -> dict[str, int]:
        """qualname → hop distance from the nearest traced entry (0 =
        entry itself), over the intra-module call graph."""
        dist = {q: 0 for q in self.traced_entries}
        frontier = list(dist)
        for d in range(1, depth + 1):
            nxt: list[str] = []
            for q in frontier:
                for callee in self.calls.get(q, ()):
                    if callee not in dist:
                        dist[callee] = d
                        nxt.append(callee)
            frontier = nxt
        return dist


class ProjectGraph:
    """Lazily built per-module graphs, shared across passes (built once
    per run through :meth:`of`)."""

    def __init__(self, project: Project):
        self.project = project
        self._graphs: dict[str, ModuleGraph] = {}

    def of(self, module: ModuleInfo) -> ModuleGraph:
        g = self._graphs.get(module.name)
        if g is None:
            g = self._graphs[module.name] = ModuleGraph(module)
        return g


def graphs_for(project: Project) -> ProjectGraph:
    """One ProjectGraph per Project instance (passes share the index
    work instead of each rebuilding it). Cached ON the project — an
    id()-keyed module global would go stale when a GC'd project's id is
    reused by a new one (exactly the churn a test suite produces)."""
    pg = getattr(project, "_graphs", None)
    if pg is None:
        pg = project._graphs = ProjectGraph(project)
    return pg
