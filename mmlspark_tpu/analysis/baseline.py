"""The justification-carrying baseline: known findings the gate accepts.

The contract (enforced here, relied on by the CI gate):

- every entry carries a **non-empty human justification** — a baseline
  is a reviewed decision, not a mute button; loading a baseline with a
  missing/empty justification raises;
- entries key on the finding **fingerprint** (pass|rule|path|symbol|
  detail — line-number free, see ``core.Finding.fingerprint``), so
  reformatting does not churn the file but *moving the code to another
  file or symbol does* — the justification must be re-reviewed where
  the code now lives;
- **stale entries** (fingerprints no current finding produces) are
  reported so the file shrinks as fixes land; ``--strict`` makes them
  fail the gate.

Workflow: run ``python -m mmlspark_tpu.analysis --write-baseline`` to
append new findings with ``justification: "TODO"`` placeholders, then
replace every TODO with the actual reason before committing — the gate
rejects TODOs like any other empty justification.
"""

from __future__ import annotations

import json
import os

from .core import Finding

PLACEHOLDER = "TODO"


class BaselineError(ValueError):
    """A baseline file violates the contract (bad shape, missing or
    placeholder justification)."""


def load(path: str, lenient: bool = False) -> dict[str, dict]:
    """fingerprint → entry. Missing file = empty baseline. ``lenient``
    skips the justification check (ONLY for ``--write-baseline``, which
    must be able to re-open its own placeholder output)."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "findings" not in data:
        raise BaselineError(f"{path}: expected {{'findings': [...]}}")
    out: dict[str, dict] = {}
    for i, entry in enumerate(data["findings"]):
        fp = entry.get("fingerprint")
        just = (entry.get("justification") or "").strip()
        if not fp:
            raise BaselineError(f"{path}: entry {i} has no fingerprint")
        if not lenient and (not just
                            or just.upper().startswith(PLACEHOLDER)):
            raise BaselineError(
                f"{path}: entry {fp} ({entry.get('rule', '?')} in "
                f"{entry.get('path', '?')}) has no justification — every "
                f"baselined finding must say WHY it is acceptable")
        if fp in out:
            raise BaselineError(f"{path}: duplicate fingerprint {fp}")
        out[fp] = entry
    return out


def apply(findings: list[Finding], baseline: dict[str, dict]
          ) -> tuple[list[Finding], list[Finding], list[dict]]:
    """→ (unbaselined, suppressed, stale_entries). ``info`` findings are
    report-only and never need baselining."""
    unbaselined: list[Finding] = []
    suppressed: list[Finding] = []
    seen: set[str] = set()
    for f in findings:
        fp = f.fingerprint
        if f.severity == "info":
            continue
        if fp in baseline:
            suppressed.append(f)
            seen.add(fp)
        else:
            unbaselined.append(f)
    stale = [e for fp, e in baseline.items() if fp not in seen]
    return unbaselined, suppressed, stale


def write(path: str, findings: list[Finding],
          existing: dict[str, dict] | None = None) -> int:
    """Merge current unbaselined findings into the baseline file with
    placeholder justifications (which the loader will reject until a
    human replaces them). Returns the number of NEW entries."""
    entries: dict[str, dict] = dict(existing or {})
    added = 0
    for f in findings:
        if f.severity == "info" or f.fingerprint in entries:
            continue
        entries[f.fingerprint] = {
            "fingerprint": f.fingerprint, "pass": f.pass_name,
            "rule": f.rule, "path": f.path, "symbol": f.symbol,
            "message": f.message,
            "justification": PLACEHOLDER + ": replace with the reason "
                             "this finding is acceptable",
        }
        added += 1
    payload = {
        "version": 1,
        "comment": "graftcheck baseline — every entry needs a human "
                   "justification; the gate rejects TODO placeholders. "
                   "See docs/analysis.md for the triage workflow.",
        "findings": [entries[fp] for fp in sorted(entries)],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")
    return added
