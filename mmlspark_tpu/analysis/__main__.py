"""graftcheck CLI: ``python -m mmlspark_tpu.analysis``.

Exit codes: 0 = clean (no unbaselined error/warning findings);
1 = unbaselined findings (or, with ``--strict``, stale baseline
entries); 2 = usage/baseline-contract errors.

The CI gate is exactly::

    python -m mmlspark_tpu.analysis --strict \
        --json analysis_report.json \
        --traceability mmlspark_tpu/analysis/traceability.json

which must finish < 60 s (pure ``ast`` over the package; no JAX, no
imports of the analyzed code).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from . import baseline as baseline_mod
from .core import Project, run_passes
from .report import render_json, render_text
from .trace_safety import build_traceability

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mmlspark_tpu.analysis",
        description="graftcheck: JAX-aware static analysis "
                    "(trace-safety, recompile hazards, lock discipline, "
                    "donation, collective audit)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the directory containing "
                         "the analyzed package)")
    ap.add_argument("--package", default="mmlspark_tpu",
                    help="dotted package to analyze (default: "
                         "mmlspark_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: the package's "
                         "analysis/baseline.json)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the machine-readable report here")
    ap.add_argument("--traceability", default=None,
                    help="write the stage/featurizer TRACEABLE/"
                         "HOST-BOUND report here")
    ap.add_argument("--write-baseline", action="store_true",
                    help="append current unbaselined findings to the "
                         "baseline with TODO justifications (then edit "
                         "them — the gate rejects TODOs)")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on stale baseline entries")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the text report (exit code only)")
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    root = args.root
    if root is None:
        # the package's own location: .../repo/mmlspark_tpu/analysis ->
        # repo
        here = os.path.dirname(os.path.abspath(__file__))
        root = os.path.dirname(os.path.dirname(here))
    project = Project.load(root, args.package)
    if not project.modules:
        print(f"no modules found under {root}/{args.package}",
              file=sys.stderr)
        return 2
    findings = run_passes(project)

    if args.write_baseline:
        existing = baseline_mod.load(args.baseline, lenient=True)
        unb, _, _ = baseline_mod.apply(findings, existing)
        added = baseline_mod.write(args.baseline, unb, existing)
        print(f"baseline: {added} new entr"
              f"{'y' if added == 1 else 'ies'} written to "
              f"{args.baseline} — edit every TODO justification before "
              f"committing")
        return 0

    try:
        base = baseline_mod.load(args.baseline)
    except baseline_mod.BaselineError as e:
        print(f"baseline contract violation: {e}", file=sys.stderr)
        return 2
    unbaselined, suppressed, stale = baseline_mod.apply(findings, base)

    if args.traceability:
        tr = build_traceability(project)
        with open(args.traceability, "w", encoding="utf-8") as f:
            json.dump(tr, f, indent=2)
            f.write("\n")
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            f.write(render_json(unbaselined, suppressed, stale,
                                len(project.modules)))
    if not args.quiet:
        print(render_text(unbaselined, suppressed, stale,
                          len(project.modules)), end="")
        print(f"({time.monotonic() - t0:.1f}s)")
    if unbaselined:
        return 1
    if args.strict and stale:
        if not args.quiet:
            print("strict: stale baseline entries present — delete them")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
