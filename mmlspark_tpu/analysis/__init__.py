"""graftcheck — JAX-aware static analysis for the mmlspark_tpu codebase.

Five passes over the package source (pure ``ast``; imports neither JAX
nor the analyzed code):

- ``trace-safety``   host ops reachable from jit/pjit/shard_map/
                     pallas_call wrap sites; wall-clock reads in
                     control-plane deadline paths; feeds the
                     stage/featurizer traceability report
- ``recompile-hazard``  jit-in-loop rewraps, Python branches on traced
                     values, concretizing casts, unhashable static args
- ``lock-discipline``   mutations of lock-owning classes' shared state
                     outside the lock
- ``donation``       donated buffers read after the donating call;
                     train steps wrapped without donate_argnums
- ``collective-audit``  raw lax.p* bypassing parallel.collectives'
                     obs accounting; undeclared literal axis names

CLI: ``python -m mmlspark_tpu.analysis`` (see ``__main__.py``); the CI
gate runs it with ``--strict`` and fails on any unbaselined finding.
Baseline entries (``analysis/baseline.json``) each carry a written
justification — see docs/analysis.md for the triage workflow.
"""

from .core import (AnalysisPass, Finding, Project, all_passes,
                   register_pass, run_passes)
from .trace_safety import build_traceability

__all__ = ["AnalysisPass", "Finding", "Project", "all_passes",
           "register_pass", "run_passes", "build_traceability"]
