"""Pass 1 — trace-safety: host ops inside traced regions, wall-clock in
deadline paths, and the stage/featurizer traceability report.

A function staged by ``jit``/``pjit``/``shard_map``/``pallas_call``
executes its Python body **once per trace**, not once per step. A host
op inside it is therefore one of two bugs waiting to happen:

- a *silent constant*: ``time.time()``, ``random.random()``, an
  ``np.*`` read of a traced value — evaluated at trace time, frozen
  into the compiled program, and never updated again;
- a *tracer leak*: ``.item()`` / ``print`` / file I/O force
  materialization, which either throws ``ConcretizationTypeError`` or
  inserts a blocking device→host sync into the hot path.

Lock acquisition in a traced region is its own hazard class: the lock
is taken at trace time (usually harmless but always meaningless) and
NOT taken per step — a reader assuming per-step mutual exclusion is
wrong on both counts.

The same host-op scanner classifies every stage/featurizer as
``TRACEABLE`` or ``HOST-BOUND`` (``analysis/traceability.json``) — the
work-list for the ROADMAP's whole-pipeline XLA compilation item: a
Pipeline can lower featurize → model → postproc into one pjit'd
computation exactly when every stage on the path is TRACEABLE, and the
report's per-stage ``reasons`` name what blocks the rest.

Separately (not gated on traced regions), the ``wallclock-deadline``
rule flags ``time.time()`` anywhere in the control plane (``sched/``,
``resilience/``, ``serving/``, ``obs/``): deadline, lease, and backoff
arithmetic must ride ``time.monotonic()`` — an NTP step backwards would
otherwise un-expire leases or fire every deadline shed at once
(tests/test_analysis.py carries the clock-step regression test).
"""

from __future__ import annotations

import ast
import dataclasses

from .callgraph import ModuleGraph, dotted, graphs_for, resolve
from .core import AnalysisPass, Finding, ModuleInfo, Project, register_pass

# resolved-call-prefix → (rule, severity, short reason). First match by
# dotted-prefix wins; "prefix" means exact name or name + ".".
HOST_CALL_TABLE: tuple[tuple[str, str, str, str], ...] = (
    ("time.time", "host-time", "error",
     "host clock read is frozen at trace time"),
    ("time.monotonic", "host-time", "error",
     "host clock read is frozen at trace time"),
    ("time.perf_counter", "host-time", "error",
     "host clock read is frozen at trace time"),
    ("time.sleep", "host-time", "error",
     "sleeps at trace time only; no-op per step"),
    ("print", "host-print", "warning",
     "prints the tracer at trace time (use jax.debug.print)"),
    ("builtins.print", "host-print", "warning",
     "prints the tracer at trace time (use jax.debug.print)"),
    ("open", "host-io", "error", "file I/O inside a traced region"),
    ("input", "host-io", "error", "blocking host input"),
    ("socket.", "host-io", "error", "socket I/O inside a traced region"),
    ("http.", "host-io", "error", "HTTP I/O inside a traced region"),
    ("urllib.", "host-io", "error", "HTTP I/O inside a traced region"),
    ("requests.", "host-io", "error", "HTTP I/O inside a traced region"),
    ("subprocess.", "host-io", "error", "subprocess inside a traced region"),
    ("random.", "host-rng", "warning",
     "stdlib RNG draws once at trace time (use jax.random)"),
    ("numpy.asarray", "host-materialize", "warning",
     "materializes the traced value on host"),
    ("numpy.array", "host-materialize", "warning",
     "materializes the traced value on host"),
    ("np.asarray", "host-materialize", "warning",
     "materializes the traced value on host"),
    ("np.array", "host-materialize", "warning",
     "materializes the traced value on host"),
    ("jax.device_get", "host-materialize", "warning",
     "forces a device→host sync inside the traced region"),
)

# method names that force materialization when called on a traced value
MATERIALIZE_METHODS = frozenset({"item", "tolist", "to_py"})
# logger-ish receivers for `.warning(...)`-style calls
LOG_METHODS = frozenset({"debug", "info", "warning", "error", "exception",
                         "critical", "log"})
LOG_RECEIVER_HINTS = ("log", "logger")

# control-plane packages whose deadline/lease arithmetic must never use
# the wall clock (satellite: the time.time-vs-monotonic bug class)
WALLCLOCK_PACKAGES = ("sched", "resilience", "serving", "obs")


@dataclasses.dataclass
class HostOp:
    node: ast.AST
    rule: str
    severity: str
    token: str      # stable detail ("time.time", ".item", "with-lock")
    reason: str


def _lockish_name(name: str | None) -> bool:
    if not name:
        return False
    last = name.rsplit(".", 1)[-1].lower()
    return "lock" in last or last in ("_cv", "cv", "cond", "condition")


def scan_host_ops(graph: ModuleGraph, fn_node: ast.AST,
                  include_nested: bool = True) -> list[HostOp]:
    """Host ops lexically inside ``fn_node``. With ``include_nested``,
    nested defs are scanned too (inside a traced region they are traced
    helpers — scan bodies, cond branches)."""
    out: list[HostOp] = []

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and not include_nested:
                continue
            if isinstance(child, ast.With):
                for item in child.items:
                    name = dotted(item.context_expr)
                    if name is None and isinstance(item.context_expr,
                                                   ast.Call):
                        name = dotted(item.context_expr.func)
                    if _lockish_name(name):
                        out.append(HostOp(
                            child, "lock-in-trace", "error",
                            f"with:{name}",
                            "lock held at trace time, not per step"))
            if isinstance(child, ast.Call):
                _visit_call(child)
            visit(child)

    def _visit_call(call: ast.Call) -> None:
        resolved = resolve(dotted(call.func), graph.imports)
        if resolved:
            for prefix, rule, sev, reason in HOST_CALL_TABLE:
                if resolved == prefix or (prefix.endswith(".") and
                                          resolved.startswith(prefix)) \
                        or resolved.startswith(prefix + "."):
                    out.append(HostOp(call, rule, sev, prefix.rstrip("."),
                                      reason))
                    return
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr in MATERIALIZE_METHODS and not call.args:
                out.append(HostOp(
                    call, "host-materialize", "warning", f".{f.attr}",
                    "materializes the traced value on host"))
            elif f.attr == "acquire":
                out.append(HostOp(
                    call, "lock-in-trace", "error", ".acquire",
                    "lock held at trace time, not per step"))
            elif f.attr in LOG_METHODS:
                recv = dotted(f.value) or ""
                if any(h in recv.lower() for h in LOG_RECEIVER_HINTS):
                    out.append(HostOp(
                        call, "host-log", "warning", f"log.{f.attr}",
                        "logging executes at trace time only"))

    visit(fn_node)
    return out


def _expand_traced(graph: ModuleGraph) -> dict[str, int]:
    """Traced entries + call-graph reachability + lexically nested defs
    of traced functions (a nested def inside a traced body runs at
    trace time even when handed to scan/cond rather than called)."""
    dist = graph.traced_functions()
    changed = True
    while changed:
        changed = False
        for q in list(dist):
            prefix = q + ".<locals>."
            for other in graph.functions:
                if other.startswith(prefix) and other not in dist:
                    dist[other] = dist[q]
                    changed = True
    return dist


@register_pass
class TraceSafetyPass(AnalysisPass):
    name = "trace-safety"
    description = ("host ops (clock, I/O, prints, locks, RNG, numpy "
                   "materialization) reachable from jit/pjit/shard_map/"
                   "pallas_call wrap sites; wall-clock reads in "
                   "control-plane deadline paths")

    def run(self, project: Project) -> list[Finding]:
        graphs = graphs_for(project)
        out: list[Finding] = []
        pkg = project.package
        for mod in project.modules.values():
            g = graphs.of(mod)
            traced = _expand_traced(g)
            seen: set[int] = set()
            for q, d in sorted(traced.items()):
                fi = g.functions.get(q)
                if fi is None:
                    continue
                # entry functions scan nested defs; reached helpers
                # scan only their own statements (their nested defs are
                # separate entries if also reached)
                for op in scan_host_ops(g, fi.node,
                                        include_nested=(d == 0)):
                    if id(op.node) in seen:
                        continue
                    seen.add(id(op.node))
                    via = "" if d == 0 else f" ({d} calls below the wrap)"
                    out.append(self.finding(
                        op.rule, op.severity, mod, op.node, q,
                        f"{op.token} inside traced region {q!r}{via}: "
                        f"{op.reason}",
                        detail=op.token))
            # wall-clock rule: whole control-plane modules, traced or not
            rel = mod.name[len(pkg) + 1:] if mod.name.startswith(pkg + ".") \
                else mod.name
            if rel.split(".", 1)[0] in WALLCLOCK_PACKAGES:
                out.extend(self._wallclock(g, mod))
        return out

    def _wallclock(self, g: ModuleGraph, mod: ModuleInfo) -> list[Finding]:
        out = []
        for q, fi in sorted(g.functions.items()):
            for call in g._own_calls(fi.node):
                if resolve(dotted(call.func), g.imports) == "time.time":
                    out.append(self.finding(
                        "wallclock-deadline", "error", mod, call, q,
                        "time.time() in a control-plane module: deadline/"
                        "lease/backoff arithmetic must use time.monotonic()"
                        " — an NTP step would un-expire leases or fire "
                        "every shed at once", detail="time.time"))
        return out


# --------------------------------------------------------- traceability
# stage base classes (mmlspark_tpu.core.pipeline) that mark a class as a
# registered Stage for the report
STAGE_BASES = frozenset({"Transformer", "Estimator", "Model",
                         "PipelineStage"})
STAGE_METHODS = ("transform", "_transform", "fit", "_fit")
# classification marker set: anything here makes a stage HOST-BOUND for
# whole-pipeline compilation purposes. Broader than the traced-region
# rules: plain numpy compute is fine on host today but blocks lowering
# the stage into one XLA computation.
_NUMPY_PREFIXES = ("numpy.", "np.")


def _class_index(project: Project) -> dict[str, tuple[ModuleInfo,
                                                      ast.ClassDef]]:
    idx: dict[str, tuple[ModuleInfo, ast.ClassDef]] = {}
    for mod in project.modules.values():
        if ".stages" not in mod.name and ".featurize" not in mod.name:
            continue
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                idx[node.name] = (mod, node)
    return idx


def _is_stage(cls: ast.ClassDef, idx, seen=None) -> bool:
    seen = seen or set()
    if cls.name in seen:
        return False
    seen.add(cls.name)
    for base in cls.bases:
        name = dotted(base)
        if name is None:
            continue
        last = name.rsplit(".", 1)[-1]
        if last in STAGE_BASES:
            return True
        if last in idx and _is_stage(idx[last][1], idx, seen):
            return True
    return False


def _stage_markers(project: Project, mod: ModuleInfo,
                   cls: ast.ClassDef, idx) -> tuple[list[str], set[str]]:
    """→ (host markers blocking traceability, child stage classes this
    stage instantiates). Scans the stage's transform/fit methods plus
    same-class and same-module helpers (depth-limited through the call
    graph), plus inherited methods from in-scope bases. Children matter
    because a composite stage (TextFeaturizer building Tokenizer →
    HashingTF → IDF) is only as traceable as the stages it assembles —
    :func:`build_traceability` propagates their markers in."""
    graphs = graphs_for(project)
    markers: set[str] = set()
    children: set[str] = set()
    visited: set[tuple[str, str]] = set()

    def scan_method(mmod: ModuleInfo, qual: str, depth: int) -> None:
        if depth > 3 or (mmod.name, qual) in visited:
            return
        visited.add((mmod.name, qual))
        g = graphs.of(mmod)
        fi = g.functions.get(qual)
        if fi is None:
            return
        for op in scan_host_ops(g, fi.node):
            markers.add(f"{op.rule}:{op.token}")
        for call in g._own_calls(fi.node):
            resolved = resolve(dotted(call.func), g.imports)
            if resolved and any(resolved.startswith(p)
                                for p in _NUMPY_PREFIXES):
                markers.add(f"host-numpy:{resolved}")
            last = (resolved or "").rsplit(".", 1)[-1]
            if last in idx and last != cls.name:
                children.add(last)
        for callee in g.calls.get(qual, ()):
            scan_method(mmod, callee, depth + 1)

    def scan_class(cmod: ModuleInfo, cnode: ast.ClassDef,
                   depth: int) -> None:
        for m in STAGE_METHODS:
            scan_method(cmod, f"{cnode.name}.{m}", depth)
        for base in cnode.bases:
            name = dotted(base)
            last = name.rsplit(".", 1)[-1] if name else ""
            if last in idx and depth < 3:
                bmod, bnode = idx[last]
                scan_class(bmod, bnode, depth + 1)

    scan_class(mod, cls, 0)
    return sorted(markers), children


def build_traceability(project: Project) -> dict:
    """Classify every registered stage/featurizer class in ``stages/``
    and ``featurize/`` as TRACEABLE or HOST-BOUND, with reasons — the
    feeder report for whole-pipeline XLA compilation (ROADMAP)."""
    idx = _class_index(project)
    own: dict[str, list[str]] = {}
    kids: dict[str, set[str]] = {}
    for name in sorted(idx):
        mod, cls = idx[name]
        if not _is_stage(cls, idx):
            continue
        own[name], kids[name] = _stage_markers(project, mod, cls, idx)
    # composite propagation to a fixpoint: a stage that builds other
    # stages is only as traceable as what it assembles
    merged = {n: set(m) for n, m in own.items()}
    changed = True
    while changed:
        changed = False
        for n, children in kids.items():
            for c in children:
                if c not in merged:
                    continue
                add = {f"via:{c}"} if merged[c] else set()
                if not add <= merged[n]:
                    merged[n] |= add
                    changed = True
    stages = []
    for name in sorted(own):
        mod, _cls = idx[name]
        markers = sorted(merged[name])
        stages.append({
            "stage": name,
            "module": mod.name,
            "kind": "featurizer" if ".featurize" in mod.name else "stage",
            "classification": "HOST-BOUND" if markers else "TRACEABLE",
            "reasons": markers,
        })
    n_traceable = sum(1 for s in stages
                      if s["classification"] == "TRACEABLE")
    return {
        "version": 1,
        "package": project.package,
        "summary": {"stages": len(stages), "traceable": n_traceable,
                    "host_bound": len(stages) - n_traceable},
        "stages": stages,
    }
