"""On-demand device profiler capture behind the serving debug surface.

``POST /debug/xprof?duration_ms=500`` on either serving front captures
a bounded-duration device+host trace (``jax.profiler.start_trace`` /
``stop_trace``) into a rank-suffixed directory under the capture root;
``GET /debug/xprof`` lists finished captures and
``GET /debug/xprof?fetch=<name>`` returns one as a zip archive. The
distributed server adds pod fanout on top (one POST captures every
rank over the ``__fleet__`` mesh route — ``serving/distributed.py``).

Contracts the serving plane depends on:

- **one capture at a time** — a second POST while a trace is open
  answers 409 (the profiler is a process-global singleton; overlapping
  sessions corrupt each other),
- **bounded duration** — ``duration_ms`` is clamped to
  [1, ``MMLSPARK_TPU_XPROF_MAX_MS``] (default 30 s) so a fat-fingered
  request cannot leave tracing on,
- **no-JAX-safe degradation** — a host-only process answers
  503-with-reason without EVER importing jax (same never-initialize
  guard as ``profile.device_platform``); merely asking for a capture
  must not drag backend bring-up into a serving process.

Import is stdlib-only; jax is touched only inside a capture, and only
when it is already live in the process.
"""

from __future__ import annotations

import io
import json
import os
import sys
import threading
import time
import urllib.parse
import zipfile

from .metrics import registry as _registry

#: duration ceiling (ms) — env-overridable for long captures
ENV_MAX_MS = "MMLSPARK_TPU_XPROF_MAX_MS"
#: capture root override (default: a per-process dir under /tmp)
ENV_DIR = "MMLSPARK_TPU_XPROF_DIR"

_DEFAULT_MAX_MS = 30_000.0


def _jax_ready() -> tuple[bool, str]:
    """Whether a capture can run NOW, without importing jax or
    initializing a backend. The reason string is the 503 body's
    payload when not."""
    mod = sys.modules.get("jax")
    if mod is None:
        return False, "jax not imported in this process"
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None or not getattr(xb, "_backends", None):
        return False, "jax backend not initialized"
    return True, ""


class XprofCaptures:
    """The per-process capture manager both fronts route through."""

    def __init__(self, root: str | None = None, registry=None):
        reg = registry if registry is not None else _registry
        self._root = root or os.environ.get(ENV_DIR) \
            or os.path.join("/tmp", f"mmlspark_tpu_xprof_{os.getpid()}")
        self._lock = threading.Lock()
        self._active: str | None = None
        self._seq = 0
        self._c_captures = reg.counter(
            "profile_xprof_captures_total",
            "on-demand device-trace capture attempts, by outcome "
            "(ok | busy | unavailable | error)")

    @property
    def root(self) -> str:
        return self._root

    def _max_ms(self) -> float:
        try:
            return float(os.environ.get(ENV_MAX_MS, _DEFAULT_MAX_MS))
        except (TypeError, ValueError):
            return _DEFAULT_MAX_MS

    def _rank(self) -> str:
        from .profile import process_label
        return process_label() or "0"

    # -- capture -----------------------------------------------------------
    def capture(self, duration_ms: float, tag: str = "") -> dict:
        """Run one bounded capture, blocking for its duration. Raises
        :class:`CaptureUnavailable` (-> 503) when jax is absent and
        :class:`CaptureBusy` (-> 409) when a capture is already open."""
        ok, reason = _jax_ready()
        if not ok:
            self._c_captures.inc(1, outcome="unavailable")
            raise CaptureUnavailable(reason)
        duration_ms = min(max(float(duration_ms), 1.0), self._max_ms())
        with self._lock:
            if self._active is not None:
                self._c_captures.inc(1, outcome="busy")
                raise CaptureBusy(self._active)
            self._seq += 1
            name = f"capture-{self._seq:04d}"
            if tag:
                name += f"-{_clean(tag)}"
            name += f"-r{self._rank()}"
            self._active = name
        log_dir = os.path.join(self._root, name)
        import jax
        try:
            os.makedirs(log_dir, exist_ok=True)
            jax.profiler.start_trace(log_dir,
                                     create_perfetto_link=False)
            try:
                time.sleep(duration_ms / 1e3)
            finally:
                jax.profiler.stop_trace()
        except Exception:
            self._c_captures.inc(1, outcome="error")
            raise
        finally:
            with self._lock:
                self._active = None
        self._c_captures.inc(1, outcome="ok")
        return {"capture": name, "dir": log_dir,
                "duration_ms": duration_ms,
                "files": _count_files(log_dir)}

    # -- read surface ------------------------------------------------------
    def list_captures(self) -> dict:
        captures = []
        if os.path.isdir(self._root):
            for name in sorted(os.listdir(self._root)):
                d = os.path.join(self._root, name)
                if os.path.isdir(d):
                    captures.append({"capture": name,
                                     "files": _count_files(d)})
        ok, reason = _jax_ready()
        with self._lock:
            active = self._active
        return {"root": self._root, "active": active,
                "available": ok, "reason": reason,
                "captures": captures}

    def fetch(self, name: str) -> bytes | None:
        """One finished capture as zip bytes (None when unknown). The
        name is sanitized against traversal — only direct children of
        the root are fetchable."""
        name = os.path.basename(name)
        d = os.path.join(self._root, name)
        if not name or not os.path.isdir(d):
            return None
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            for base, _dirs, files in os.walk(d):
                for f in files:
                    full = os.path.join(base, f)
                    z.write(full, os.path.relpath(full, d))
        return buf.getvalue()

    # -- the /debug/xprof route adapter ------------------------------------
    def handle_query(self, query: str, body: bytes) -> tuple[int, bytes]:
        """Both fronts' ``/debug/xprof`` handler: ``duration_ms=`` in
        the query runs a capture, ``fetch=<name>`` returns an archive,
        anything else lists. (Method is not part of the shared route
        signature; the query carries the intent, like
        ``/debug/timeline``.)"""
        q = urllib.parse.parse_qs(query or "")
        if "duration_ms" in q:
            try:
                duration = float(q["duration_ms"][0])
            except (TypeError, ValueError, IndexError):
                return 400, b'{"error": "bad duration_ms"}'
            tag = (q.get("tag") or [""])[0]
            try:
                out = self.capture(duration, tag=tag)
            except CaptureUnavailable as e:
                return 503, json.dumps(
                    {"error": "xprof unavailable",
                     "reason": str(e)}).encode()
            except CaptureBusy as e:
                return 409, json.dumps(
                    {"error": "capture in flight",
                     "active": str(e)}).encode()
            except Exception as e:
                return 500, json.dumps(
                    {"error": "capture failed",
                     "reason": repr(e)}).encode()
            return 200, json.dumps(out, indent=1).encode()
        if "fetch" in q:
            blob = self.fetch((q.get("fetch") or [""])[0])
            if blob is None:
                return 404, b'{"error": "unknown capture"}'
            return 200, blob
        return 200, json.dumps(self.list_captures(),
                               indent=1).encode()


class CaptureUnavailable(RuntimeError):
    """No live jax backend in this process -> HTTP 503."""


class CaptureBusy(RuntimeError):
    """A capture is already open -> HTTP 409."""


def _clean(tag: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in str(tag))[:48]


def _count_files(d: str) -> int:
    return sum(len(files) for _b, _d, files in os.walk(d))


#: THE process-wide capture manager (both fronts route through it).
xprof_captures = XprofCaptures()
