"""Continuous compile/device profiler + the cost-model feature log.

Three instruments, all always-on-capable (bounded, registry-backed, no
trace files to rotate):

- :class:`CompileTracker` — wraps ``jax.jit`` call sites (route through
  :func:`mmlspark_tpu.parallel.compat.jit`) so every retrace is counted
  and every compile's wall time lands in a histogram, per function.
  This is the RUNTIME counterpart of graftcheck's static
  recompile-hazard pass: the static pass says "this branch COULD
  recompile per step"; the tracker says "this function DID compile 14
  times in the last hour". Steady-state serving must show zero misses.

- :class:`StepProfiler` — attributes wall time into host-dispatch vs
  device-execute per pipeline stage using the ``block_until_ready``
  delta (dispatch returns as soon as XLA enqueues; the remainder until
  the sync completes is device/transfer time). This generalizes
  bench.py's MFU accounting into an always-on gauge: pass ``flops`` and
  ``profile_mfu{stage=...}`` updates per step. The ~64 ms contended
  dispatch RTT in BENCH_TPU_BANKED.json is exactly what this surface
  makes visible per stage, continuously.

- :class:`FeatureLog` — a bounded structured log appending one record
  per served request (route, batch/bucket, dtype/shapes when known,
  queue ms, execute ms, device ms): the training data for the learned
  scheduler cost model (arXiv:2008.01040) and the measurement substrate
  a TVM-style autotuner (arXiv:1802.04799) searches over.

``utils.profiling``'s device-trace helpers (:func:`profile_trace`,
:func:`profiled`) moved here — that module keeps deprecation shims.

Import is stdlib-only; JAX is imported lazily inside the jit wrapper
and the XProf helpers only.
"""

from __future__ import annotations

import collections
import contextlib
import functools
import os
import sys
import threading
import time

from .attribution import PEAK_SPECS, peak_spec
from .metrics import registry as _registry
from .tracing import tracer as _tracer, wall_now

# kept importable for callers that pinned against the old constant —
# but it is now the v5e row of the shared PeakSpec table
# (obs.attribution), not a free-floating literal. The MFU gauge itself
# resolves the LIVE platform's peak per call unless explicitly
# overridden.
DEFAULT_PEAK_FLOPS = PEAK_SPECS["tpu-v5e"].peak_flops


class CompileTracker:
    """Counts retraces and compile time per jitted function.

    ``tracker.jit(fn, name=..., **jit_kwargs)`` returns a callable with
    ``jax.jit`` semantics whose Python body is instrumented: the wrapped
    function executes once per TRACE, so each execution is a cache miss
    (a compile). Per-call hit/miss outcomes and compile wall seconds go
    to the obs registry:

    - ``profile_compiles_total{fn=...}`` — retrace count (>= 2 on a
      shape-unstable function; the static recompile-hazard pass's
      runtime ground truth),
    - ``profile_jit_calls_total{fn=...,outcome=hit|miss}``,
    - ``profile_compile_seconds{fn=...}`` — trace+compile wall time.

    Intentionally lock-free: the trace-noting shim runs INSIDE the
    traced region (that is the mechanism), where lock acquisition is a
    trace-safety hazard. Python-level dict bumps are GIL-atomic enough
    for compile events, which JAX serializes under its own tracing
    machinery; the registry counters (internally locked) carry the
    authoritative monotone series.
    """

    def __init__(self, registry=None):
        reg = registry if registry is not None else _registry
        self._traces: dict[str, int] = {}
        self._calls: dict[str, int] = {}
        # steady-state assertion mode (AOT acceptance, ISSUE 11):
        # after mark_steady(), every further compile is a violation —
        # counted separately so "did the warm worker compile?" is one
        # scrape of profile_runtime_compiles_total, which must stay 0.
        self._steady = False
        self._steady_base: dict[str, int] = {}
        self._c_compiles = reg.counter(
            "profile_compiles_total",
            "jit retraces (compiles) per tracked function")
        self._c_runtime = reg.counter(
            "profile_runtime_compiles_total",
            "compiles AFTER steady state was declared (mark_steady) — "
            "an AOT-warmed server must hold this at 0")
        self._c_calls = reg.counter(
            "profile_jit_calls_total",
            "tracked jit calls, by function and cache outcome")
        self._h_compile = reg.histogram(
            "profile_compile_seconds",
            "trace+compile wall seconds per tracked function")

    def _note_trace(self, label: str) -> None:
        # runs at trace time, inside the traced region: must stay free
        # of locks/clock/IO (graftcheck's trace-safety pass gates this
        # file). The dict bump is best-effort; the counter is exact.
        self._traces[label] = self._traces.get(label, 0) + 1
        self._c_compiles.inc(1, fn=label)
        if self._steady:
            self._c_runtime.inc(1, fn=label)

    def jit(self, fn=None, *, name: str | None = None, **jit_kwargs):
        """``jax.jit`` with compile tracking. Usable as a decorator
        (``@tracker.jit`` / ``@tracker.jit(name=...)``) or call-form;
        ``jit_kwargs`` pass through (donate_argnums, in_shardings, ...).
        ``lower``/``eval_shape``/``clear_cache`` forward to the
        underlying jitted callable."""
        if fn is None:
            return functools.partial(self.jit, name=name, **jit_kwargs)
        import jax
        label = name or getattr(fn, "__name__", None) or "<jit>"

        @functools.wraps(fn)
        def traced(*args, **kwargs):
            self._note_trace(label)
            return fn(*args, **kwargs)

        compiled = jax.jit(traced, **jit_kwargs)

        @functools.wraps(fn)
        def call(*args, **kwargs):
            before = self._traces.get(label, 0)
            t0 = time.perf_counter()
            out = compiled(*args, **kwargs)
            if self._traces.get(label, 0) > before:
                # the call that traced pays trace+compile inline: its
                # wall time IS the compile cost (async device dispatch
                # makes a cache-hit call return in microseconds)
                self._h_compile.observe(time.perf_counter() - t0,
                                        fn=label)
                self._c_calls.inc(1, fn=label, outcome="miss")
            else:
                self._c_calls.inc(1, fn=label, outcome="hit")
            self._calls[label] = self._calls.get(label, 0) + 1
            return out

        for attr in ("lower", "eval_shape", "trace", "clear_cache"):
            if hasattr(compiled, attr):
                setattr(call, attr, getattr(compiled, attr))
        call.__tracked_label__ = label
        return call

    # -- read surface ------------------------------------------------------
    def compiles(self, name: str) -> int:
        """Retrace count for a tracked function (0 if never traced)."""
        return self._traces.get(name, 0)

    def calls(self, name: str) -> int:
        return self._calls.get(name, 0)

    def stats(self) -> dict[str, dict[str, int]]:
        return {label: {"compiles": n,
                        "calls": self._calls.get(label, 0)}
                for label, n in sorted(self._traces.items())}

    def unstable(self, min_compiles: int = 2) -> dict[str, int]:
        """Functions that recompiled — the runtime recompile-hazard
        flags. A steady-state serving process must return ``{}`` here
        (after warmup); a shape-unstable fn shows its retrace count."""
        return {label: n for label, n in sorted(self._traces.items())
                if n >= min_compiles}

    # -- steady-state assertion mode (AOT warm-boot acceptance) ----------
    def mark_steady(self) -> None:
        """Declare warmup over: from here, every compile is a
        violation (``profile_runtime_compiles_total`` counts it). Call
        after an AOT warm load, or after a deliberate warmup sweep."""
        self._steady_base = dict(self._traces)
        self._steady = True

    def unmark_steady(self) -> None:
        self._steady = False

    @property
    def steady(self) -> bool:
        return self._steady

    def runtime_compiled(self) -> dict[str, int]:
        """Per-function compiles since :meth:`mark_steady` — the
        functions an operator must add to the AOT build."""
        if not self._steady:
            return {}
        return {label: n - self._steady_base.get(label, 0)
                for label, n in sorted(self._traces.items())
                if n > self._steady_base.get(label, 0)}

    def runtime_compiles(self) -> int:
        """Total compiles since steady state was declared (0 = the
        AOT contract held)."""
        return sum(self.runtime_compiled().values())

    def assert_steady_state(self) -> None:
        """Raise (loudly, with the offending functions) if anything
        compiled after :meth:`mark_steady` — the scale-up acceptance's
        programmatic form."""
        bad = self.runtime_compiled()
        if bad:
            raise AssertionError(
                f"{sum(bad.values())} runtime compile(s) in steady "
                f"state: {bad} — add these (fn × bucket) to the AOT "
                "build (python -m mmlspark_tpu.core.aot build)")


#: THE process-wide tracker (``parallel.compat.jit`` routes through it).
compile_tracker = CompileTracker()


class _StepHandle:
    """Yielded by :meth:`StepProfiler.step`: call ``done(result)`` with
    whatever the stage produced so the profiler can measure the
    device-execute tail (``block_until_ready`` delta). Without it the
    whole step is attributed to host dispatch. After the ``with`` block
    exits, ``seconds`` / ``dispatch_seconds`` / ``device_seconds``
    carry the measured split (callers like ``stages.Timer`` re-surface
    them)."""

    __slots__ = ("result", "seconds", "dispatch_seconds",
                 "device_seconds")

    def __init__(self):
        self.result = None
        self.seconds = 0.0
        self.dispatch_seconds = 0.0
        self.device_seconds = 0.0

    def done(self, result):
        self.result = result
        return result


def _block_on(obj) -> bool:
    """Best-effort sync on anything block_until_ready-able (a jax
    array, a tuple/list/dict of them, or a DataFrame's columns).
    Returns whether anything was actually synced — a pure-host stage
    records device_seconds ~0 with ``synced=False``."""
    if obj is None or isinstance(obj, (str, bytes, int, float, bool)):
        # scalars can't hold device handles, and a str ITERATES TO
        # ITSELF — without this cut a single text cell recurses forever
        return False
    synced = False
    blocker = getattr(obj, "block_until_ready", None)
    if callable(blocker):
        blocker()
        return True
    # numeric numpy arrays cannot hold device handles: skip before the
    # generic __iter__ branch walks a million rows in Python
    dt = getattr(obj, "dtype", None)
    if dt is not None and getattr(dt, "kind", "O") != "O":
        return False
    cols = getattr(obj, "columns", None)
    if cols is not None and hasattr(obj, "__getitem__"):
        for c in cols:  # DataFrame-shaped: sync column by column
            if _block_on(obj[c]):
                synced = True
        return synced
    if isinstance(obj, dict):
        obj = obj.values()
    if isinstance(obj, (list, tuple)) or hasattr(obj, "__iter__"):
        try:
            for leaf in obj:
                if _block_on(leaf):
                    synced = True
        except TypeError:
            pass
    return synced


def process_label() -> str | None:
    """This worker's ``process`` metric label, or None when the label
    should not be attached. Single-process runs (the overwhelmingly
    common case, and every existing dashboard/test) get None so their
    sample names stay exactly as before; only a live multi-process
    (pod) backend yields ``"0"``/``"1"``/… so per-worker series stay
    distinguishable when N workers push to one aggregation point.
    Guarded like :func:`device_platform`: never imports jax, never
    initializes a backend — ``jax.process_count()`` would bring one up.
    """
    mod = sys.modules.get("jax")
    if mod is None:
        return None
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None or not getattr(xb, "_backends", None):
        return None     # don't cache: distributed init may come later
    try:
        if int(mod.process_count()) <= 1:
            return None
        return str(int(mod.process_index()))
    except Exception:
        return None


class StepProfiler:
    """Host-dispatch vs device-execute attribution per pipeline stage.

    ``with profiler.step("featurize", flops=f) as h: h.done(stage(x))``
    records:

    - ``profile_step_seconds{stage=...,phase=dispatch|device}`` — the
      host time until dispatch returned vs the block_until_ready tail,
    - ``profile_steps_total{stage=...}``,
    - ``profile_mfu{stage=...}`` when ``flops`` is given (always-on MFU:
      flops / total seconds / peak),

    and emits ``profile.dispatch`` / ``profile.device`` child spans
    under the ambient trace (or an explicit ``parent=``), so a request's
    flame graph shows where host↔device time went per stage.
    """

    def __init__(self, service: str = "", registry=None, tracer=None,
                 peak_flops: float | None = None):
        reg = registry if registry is not None else _registry
        self.service = service
        # None (default) = resolve the live platform's PeakSpec per
        # call — the platform may only initialize after construction
        self.peak_flops = None if peak_flops is None \
            else float(peak_flops)
        self._tracer = tracer if tracer is not None else _tracer
        self._h_step = reg.histogram(
            "profile_step_seconds",
            "per-stage wall seconds, split host-dispatch vs device")
        self._c_steps = reg.counter(
            "profile_steps_total", "profiled stage executions")
        self._g_mfu = reg.gauge(
            "profile_mfu",
            "achieved FLOP/s over peak per stage (always-on MFU)")

    _AMBIENT = object()

    @contextlib.contextmanager
    def step(self, stage: str, *, parent=_AMBIENT,
             flops: float | None = None, features: dict | None = None):
        handle = _StepHandle()
        if parent is StepProfiler._AMBIENT:
            parent = self._tracer.current_span()
        # lazy: memory imports this module for process_label
        from .memory import memory_profiler
        mem0 = memory_profiler.watermark()
        w0 = wall_now()
        t0 = time.perf_counter()
        try:
            yield handle
        finally:
            t1 = time.perf_counter()
            synced = False
            if handle.result is not None:
                try:
                    synced = _block_on(handle.result)
                except Exception:
                    synced = False
            t2 = time.perf_counter()
            dispatch_s, device_s = t1 - t0, t2 - t1
            handle.dispatch_seconds = dispatch_s
            handle.device_seconds = device_s
            handle.seconds = t2 - t0
            # on a pod worker the step/mfu families carry a `process`
            # label; single-process series keep their exact names
            pl = process_label()
            plab = {"process": pl} if pl is not None else {}
            self._h_step.observe(dispatch_s, stage=stage,
                                 phase="dispatch", **plab)
            self._h_step.observe(device_s, stage=stage, phase="device",
                                 **plab)
            self._c_steps.inc(1, stage=stage, **plab)
            # live-buffer delta this stage left behind (HBM profiler;
            # absent on hosts whose devices report no memory stats)
            memory_profiler.segment_delta(
                stage, mem0, memory_profiler.watermark())
            if flops:
                self.record_mfu(stage, flops, t2 - t0)
            dspan = self._tracer.emit_span(
                "profile.dispatch", parent=parent, seconds=dispatch_s,
                start_wall=w0, stage=stage)
            self._tracer.emit_span(
                "profile.device", parent=dspan, seconds=device_s,
                start_wall=w0 + dispatch_s, stage=stage, synced=synced)
            if features is not None:
                feature_log.record(
                    stage=stage, dispatch_ms=dispatch_s * 1e3,
                    device_ms=device_s * 1e3, **features)

    def record_mfu(self, stage: str, flops: float,
                   seconds: float) -> float:
        """Set the always-on MFU gauge from an externally measured
        (flops, seconds) pair — bench.py's sweep and the step context
        both land here. The peak divided by is the resolved PeakSpec's
        (env-overridable; obs.attribution) unless the profiler was
        built with an explicit ``peak_flops``, and the gauge carries
        the platform it was computed against."""
        if self.peak_flops is not None:
            peak, platform = self.peak_flops, device_platform()
        else:
            spec = peak_spec()
            peak, platform = spec.peak_flops, spec.platform
        mfu = float(flops) / max(float(seconds), 1e-12) / peak
        labels = {"stage": stage, "platform": platform}
        pl = process_label()
        if pl is not None:
            labels["process"] = pl
        self._g_mfu.set(mfu, **labels)
        return mfu


#: THE process-wide step profiler (serving, pipelines, benches share it
#: so the mfu/step series stay one family).
step_profiler = StepProfiler()


#: Feature-row schema version. v2 (ISSUE 12) added the fields the cost
#: model needs that PR 6 did not record — ``padded_batch`` (the
#: post-bucket batch shape the executor actually runs), ``queue_depth``
#: at execute time, ``compiled_segments``, and the device ``platform``
#: — plus this stamp itself. v3 (ISSUE 15) stamps the ``process`` index
#: (``process_label()``; None on single-process hosts) so fleet-merged
#: training data is rank-attributable. v4 (ISSUE 17) adds the
#: generation-row fields ``decode_steps`` and ``prefill_tokens`` (the
#: LLM serving engine records one row per completed sequence) so the
#: cost model can price decode separately from prefill; non-generation
#: rows simply omit them. v5 (ISSUE 18) adds ``context_blocks`` (KV
#: blocks resident at completion) so decode-step time is priced by
#: resident context, not just batch — the paged-attention kernel's
#: cost scales with the chain length it streams. v6 (ISSUE 20) adds
#: the analytic-cost pair ``analytic_flops`` / ``analytic_bytes``
#: (XLA ``cost_analysis`` totals for the service's compiled programs,
#: from ``obs.attribution``) so the model can price requests by the
#: device work they actually dispatch, not just by shape proxies.
#: Consumers (``perf.costmodel``) accept v6 through v2 rows and SKIP
#: anything else, loudly, instead of misparsing old logs; fields
#: absent in old rows train as 0.
FEATURE_SCHEMA_VERSION = 6

_platform_cache: str | None = None


def device_platform() -> str:
    """Best-effort device platform for feature rows WITHOUT importing
    jax OR initializing its backend — a host-only serving process must
    not drag backend bring-up (seconds; on a TPU host it claims the
    device) into its executor thread. ``"none"`` until something else
    imports jax; a merely-imported jax reports the pinned platform
    config (or ``"uninitialized"``) until something else actually
    initializes a backend; cached once a live backend answers."""
    global _platform_cache
    if _platform_cache is not None:
        return _platform_cache
    mod = sys.modules.get("jax")
    if mod is None:
        return "none"       # don't cache: jax may import later
    # only ask default_backend() once backends exist — the call itself
    # INITIALIZES them otherwise (private attr read is guarded: on API
    # drift this degrades to the config string, never to an init)
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is not None and getattr(xb, "_backends", None):
        try:
            _platform_cache = str(mod.default_backend())
            return _platform_cache
        except Exception:
            return "unknown"    # don't cache a failed backend
    try:
        plats = mod.config.jax_platforms
        if plats:
            return str(plats).split(",")[0]
    except Exception:
        pass
    return "uninitialized"


class FeatureLog:
    """Bounded in-memory log of per-request cost-model features.

    One dict per served request, appended by the serving executor
    (route, batch, padding bucket, queue/execute ms) and enriched by
    model transforms through :meth:`record` or
    ``StepProfiler.step(features=...)`` (op shapes, dtype, device ms).
    This is TRAINING DATA for the learned performance model
    (``perf.costmodel``) that prices ``sched/policy.py``'s admission
    and batch-close decisions — bounded (ring buffer) so an always-on
    server never grows it past ``maxlen`` records.

    Every record is stamped with :data:`FEATURE_SCHEMA_VERSION` and the
    device ``platform`` unless the caller supplies them;
    :attr:`total_recorded` counts monotonically past the ring bound
    (the cost model's refresh trigger).
    """

    def __init__(self, maxlen: int = 4096, registry=None):
        reg = registry if registry is not None else _registry
        self._lock = threading.Lock()
        self._records = collections.deque(maxlen=int(maxlen))
        self._total = 0
        self._c_records = reg.counter(
            "profile_feature_records_total",
            "cost-model feature records appended, by service")

    def record(self, **fields) -> None:
        fields.setdefault("schema_version", FEATURE_SCHEMA_VERSION)
        fields.setdefault("platform", device_platform())
        fields.setdefault("process", process_label())
        with self._lock:
            self._records.append(dict(fields))
            self._total += 1
        self._c_records.inc(1, service=str(fields.get("service", "")))

    @property
    def total_recorded(self) -> int:
        """Monotone append count (NOT bounded by the ring)."""
        with self._lock:
            return self._total

    def snapshot(self) -> list[dict]:
        """Copy of the retained records, oldest first."""
        with self._lock:
            return [dict(r) for r in self._records]

    def clear(self) -> None:
        with self._lock:
            self._records.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


#: THE process-wide feature log.
feature_log = FeatureLog()


# ------------------------------------------------- pipeline profiling hook
# PipelineModel.transform consults this: None (the default) keeps the
# async-dispatch pipeline untouched; enabling it syncs per stage (that
# is the point — attribution requires the block_until_ready delta).
_pipeline_profiler: StepProfiler | None = None
_env_checked = False


def enable_pipeline_profiling(profiler: StepProfiler | None = None
                              ) -> StepProfiler:
    """Turn on per-stage host/device attribution for every
    ``PipelineModel.transform`` (also via MMLSPARK_TPU_PROFILE_PIPELINE=1).
    Costs one device sync per stage — measurement, not a free lunch."""
    global _pipeline_profiler
    _pipeline_profiler = profiler if profiler is not None \
        else step_profiler
    return _pipeline_profiler


def disable_pipeline_profiling() -> None:
    global _pipeline_profiler, _env_checked
    _pipeline_profiler = None
    _env_checked = True  # an explicit disable beats the env default


def pipeline_profiler() -> StepProfiler | None:
    """The active pipeline profiler or None (the hot-path check)."""
    global _env_checked
    if _pipeline_profiler is None and not _env_checked:
        _env_checked = True
        if os.environ.get("MMLSPARK_TPU_PROFILE_PIPELINE") == "1":
            enable_pipeline_profiling()
    return _pipeline_profiler


# ----------------------------------------------------- XProf device traces
# (folded in from utils/profiling.py — the duplicate timing path PR 1
# left behind; that module now shims here with a DeprecationWarning)
@contextlib.contextmanager
def profile_trace(log_dir: str, *, host_tracer_level: int = 2):
    """Capture a device+host trace for the enclosed region
    (``jax.profiler.trace`` wrapper; open with XProf/TensorBoard)."""
    import jax
    jax.profiler.start_trace(log_dir, create_perfetto_link=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def profiled(name: str | None = None):
    """Decorator: annotate a function in device traces
    (``jax.profiler.TraceAnnotation``) and record wall time."""
    def wrap(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            import jax
            with jax.profiler.TraceAnnotation(label):
                return fn(*args, **kwargs)
        return inner
    return wrap
