"""Process-wide metrics: Counter / Gauge / Histogram behind one registry.

The reference ships only per-stage JSON telemetry
(``logging/BasicLogging.scala``) and VW's nanosecond stopwatches
(SURVEY §5) — numbers that die inside whichever object measured them.
Here every component records into ONE process-wide
:class:`MetricsRegistry` so a serving request, a boosting round, and a
collective all land on the same surface, snapshot-able as a dict
(:meth:`MetricsRegistry.snapshot`) and scrapeable as Prometheus text
exposition (:meth:`MetricsRegistry.exposition`, served by the serving
fronts at ``GET /metrics``).

Design constraints:
- stdlib only, and importable with no backend initialization — the CI
  smoke check imports this under ``JAX_PLATFORMS=cpu`` with no JAX
  import at all.
- thread-safe: the serving fronts observe from handler threads, the
  query loop from its executor thread, and scrapes can happen
  mid-update. One registry lock per update keeps counts exact (an inc
  is a dict read-modify-write).
- labels ride as kwargs on the observation call (``c.inc(1, route="/")``)
  and become Prometheus labels; each distinct label combination is an
  independent series.
"""

from __future__ import annotations

import threading
import time

# Fixed log-scale latency buckets (seconds): 100 µs → ~105 s, factor 2.
# One fixed geometric ladder for every latency histogram keeps series
# comparable across components (serving request, boosting round, bench
# phase) and bounds the exposition size; counts above the top land in
# +Inf like any Prometheus histogram.
DEFAULT_LATENCY_BUCKETS: tuple[float, ...] = tuple(
    1e-4 * 2 ** k for k in range(21))


def _label_key(labels: dict) -> tuple[tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n") \
                .replace('"', '\\"')


def _render(name: str, key: tuple[tuple[str, str], ...],
            extra: tuple[tuple[str, str], ...] = ()) -> str:
    """Prometheus sample name: ``name{a="b",...}`` (bare name when no
    labels). ``extra`` appends synthetic labels (histogram ``le``)."""
    pairs = key + extra
    if not pairs:
        return name
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs)
    return f"{name}{{{inner}}}"


def _num(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    return f"{v:.10g}"


def bucket_quantile(bounds: tuple, counts, q: float) -> float:
    """Estimate the ``q``-quantile of a bucketed distribution.

    ``bounds`` are the finite upper bucket bounds (sorted ascending);
    ``counts`` are PER-BUCKET (non-cumulative) observation counts, one
    per bound plus a final +Inf bucket. The estimate interpolates
    linearly inside the target bucket — exact at bucket edges, off by
    at most half a bucket width inside one, which on the factor-2
    latency ladder bounds relative error at ~50% of the true value.

    Documented bias at the top: mass in the +Inf bucket has no upper
    edge to interpolate toward, so any quantile landing there is
    CLAMPED to the highest finite bound. A p99 that truly lives above
    the ladder reads as ``bounds[-1]`` — an underestimate, never a
    fabricated larger number. Widen the ladder if the tail matters.
    """
    total = float(sum(counts))
    if total <= 0:
        return 0.0
    q = min(max(float(q), 0.0), 1.0)
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= target:
            if i >= len(bounds):        # +Inf bucket: clamp (see above)
                return float(bounds[-1])
            lo = float(bounds[i - 1]) if i > 0 else 0.0
            hi = float(bounds[i])
            return lo + (hi - lo) * ((target - cum) / c)
        cum += c
    return float(bounds[-1])


class _Metric:
    """Base: one named metric holding per-label-combination series."""

    kind = "untyped"

    def __init__(self, name: str, help: str, lock: threading.Lock):
        self.name = name
        self.help = help
        self._lock = lock
        self._series: dict = {}

    def _copy_series(self) -> dict:
        """Cheap value copy of the series (called under the registry
        lock) — rendering then happens OUTSIDE the lock, so a scrape
        formatting thousands of sample lines never stalls the handler
        threads' ``inc``/``observe`` calls."""
        return dict(self._series)

    def remove_matching(self, **labels) -> None:
        """Drop every series whose label set CONTAINS the given pairs
        (e.g. ``remove_matching(endpoint=wid)`` clears all from/to
        transition combos for one endpoint). For metrics labeled by
        unbounded identities — per-worker breaker endpoints in a mesh
        with churn — the exposition would otherwise grow forever."""
        want = set(_label_key(labels))
        with self._lock:
            for key in [k for k in self._series if want <= set(k)]:
                del self._series[key]

    def _samples(self, series: dict) -> dict[str, float]:
        """Flat ``{sample_name: value}`` from a ``_copy_series`` copy."""
        return {_render(self.name, k): v for k, v in series.items()}


class Counter(_Metric):
    """Monotonically increasing count (requests served, bytes moved)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0.0)


class Gauge(_Metric):
    """A value that goes both ways (queue depth, in-flight requests)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0.0)


class _HistSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class _Timer:
    """``with hist.time(**labels) as t: ...`` → observes elapsed wall
    seconds into the histogram at exit and exposes them as ``t.seconds``
    — the ONE stopwatch shape callers use instead of paired
    ``perf_counter`` reads, so every timed region is registry-visible."""

    __slots__ = ("_hist", "_labels", "_t0", "seconds")

    def __init__(self, hist: "Histogram", labels: dict):
        self._hist = hist
        self._labels = labels
        self.seconds = 0.0

    def __enter__(self) -> "_Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._t0
        self._hist.observe(self.seconds, **self._labels)


class Histogram(_Metric):
    """Distribution over fixed buckets (log-scale latency ladder by
    default). Exposes cumulative ``_bucket{le=...}`` / ``_sum`` /
    ``_count`` samples exactly like a Prometheus histogram."""

    kind = "histogram"

    def __init__(self, name: str, help: str, lock: threading.Lock,
                 buckets: tuple[float, ...] = DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help, lock)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bs  # upper bounds, +Inf implicit

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        value = float(value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistSeries(len(self.buckets) + 1)
            i = 0
            for i, b in enumerate(self.buckets):
                if value <= b:
                    break
            else:
                i = len(self.buckets)  # +Inf bucket
            s.counts[i] += 1
            s.sum += value
            s.count += 1

    def time(self, **labels) -> _Timer:
        return _Timer(self, labels)

    def count(self, **labels) -> int:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return 0 if s is None else s.count

    def quantile(self, q: float, **labels) -> float:
        """Estimated ``q``-quantile of one label combination's series
        (:func:`bucket_quantile`: linear interpolation inside the
        log-ladder bucket, clamped at the +Inf bucket). 0.0 when the
        series has no observations."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            counts = None if s is None else tuple(s.counts)
        if counts is None:
            return 0.0
        return bucket_quantile(self.buckets, counts, q)

    def sum(self, **labels) -> float:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return 0.0 if s is None else s.sum

    def _copy_series(self) -> dict:
        return {k: (tuple(s.counts), s.sum, s.count)
                for k, s in self._series.items()}

    def _samples(self, series: dict) -> dict[str, float]:
        out: dict[str, float] = {}
        for key, (counts, total, n) in series.items():
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                out[_render(f"{self.name}_bucket", key,
                            (("le", _num(b)),))] = cum
            out[_render(f"{self.name}_bucket", key,
                        (("le", "+Inf"),))] = n
            out[_render(f"{self.name}_sum", key)] = total
            out[_render(f"{self.name}_count", key)] = n
        return out


class MetricsRegistry:
    """Thread-safe get-or-create registry of named metrics.

    ``counter``/``gauge``/``histogram`` are idempotent: asking for an
    existing name returns the existing instance (so a re-constructed
    ServingServer keeps accumulating into the same series), and asking
    for it as a different type raises — silent shadowing would split
    series invisibly.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}, requested {cls.kind}")
                want = kw.get("buckets")
                if want is not None and \
                        tuple(sorted(float(b) for b in want)) != m.buckets:
                    # same rationale as the kind check: creation order
                    # silently deciding which bucket ladder wins would
                    # make the losing caller's series meaningless
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {m.buckets}, requested {want}")
                return m
            m = cls(name, help, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple[float, ...] = DEFAULT_LATENCY_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def metrics(self, prefix: str = "") -> list["_Metric"]:
        """Registered metric objects whose name starts with ``prefix``
        — the eviction surface: callers bounding label cardinality
        (idle-tenant sweeps, mesh churn) iterate these and
        :meth:`_Metric.remove_matching` the departing identity's
        series without having to hold references to every metric."""
        with self._lock:
            return [m for name, m in self._metrics.items()
                    if name.startswith(prefix)]

    def _collect(self) -> list[tuple["_Metric", dict]]:
        """Value-copy every metric's series under the lock; callers
        render outside it (a scrape must not stall ``inc``/``observe``
        in the request hot path while it string-formats samples)."""
        with self._lock:
            return [(self._metrics[name], self._metrics[name]._copy_series())
                    for name in sorted(self._metrics)]

    def snapshot(self) -> dict[str, float]:
        """Every sample as a flat ``{sample_name: value}`` dict — the
        same names (and numbers) the text exposition renders, so tests
        and benches can assert on either surface interchangeably."""
        out: dict[str, float] = {}
        for m, series in self._collect():
            out.update(m._samples(series))
        return out

    def exposition(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: list[str] = []
        for m, series in self._collect():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for sample, value in m._samples(series).items():
                lines.append(f"{sample} {_num(float(value))}")
        return "\n".join(lines) + "\n"

    def clear(self) -> None:
        """Drop every metric (test isolation only — production callers
        hold metric references that would silently detach)."""
        with self._lock:
            self._metrics.clear()


# THE process-wide registry. Component code imports this instance
# (``from mmlspark_tpu.obs import registry``); a private registry is
# only for tests that need isolation.
registry = MetricsRegistry()
