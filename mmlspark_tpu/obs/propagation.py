"""Cross-process trace propagation: W3C-style ``traceparent`` carriers.

A request that crosses the driver→worker mesh used to lose its trace at
every process boundary: the HTTP client, the lease pull, and the reply
hop each started fresh roots. This module is the one place the wire
format lives:

- :func:`inject` writes ``traceparent: 00-<trace_id>-<parent_span_id>-01``
  into a headers dict (the HTTP client stack calls it on every send);
- :func:`extract` parses it back into a :class:`TraceContext`, which
  ``tracer.start_span(parent=ctx)`` accepts directly (duck-typed
  ``trace_id``/``span_id``), so one request yields ONE cross-process
  span tree;
- :func:`span_from_dict` rebuilds a finished remote span from the
  ``Span.to_dict`` wire form (mesh replies carry the worker's spans
  home to the ingest server's flight recorder).

Ids are opaque lowercase-hex tokens (``tracing._new_id`` guarantees it
for in-process spans; the native load generator synthesizes compatible
ones), so the four ``-``-delimited traceparent fields parse
unambiguously. Not byte-for-byte W3C (ids are variable-length, not
16/32 hex chars) — the STRUCTURE matches, which is what interop inside
this mesh needs.

Stdlib-only and backend-free, like the rest of ``obs``.
"""

from __future__ import annotations

import dataclasses

from .tracing import Span, tracer as _tracer

TRACEPARENT = "traceparent"
_VERSION = "00"
_FLAGS = "01"
_HEX = set("0123456789abcdef")


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """A remote span's coordinates — everything a child span needs.
    Shape-compatible with ``Span`` where parentage is concerned, so it
    can be passed anywhere a parent span is accepted."""

    trace_id: str
    span_id: str

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}


def context_of(span) -> TraceContext | None:
    """The propagatable context of a span (or None for None — callers
    chain off ``tracer.current_span()`` without a guard)."""
    if span is None:
        return None
    return TraceContext(trace_id=span.trace_id, span_id=span.span_id)


def _hexish(token: str) -> bool:
    return bool(token) and all(c in _HEX for c in token)


def format_traceparent(ctx) -> str:
    """``00-<trace_id>-<span_id>-01`` for a Span/TraceContext."""
    return f"{_VERSION}-{ctx.trace_id}-{ctx.span_id}-{_FLAGS}"


def inject(headers: dict, span=None) -> dict:
    """Write the traceparent header for ``span`` (default: the ambient
    current span) into ``headers`` (mutated AND returned). No ambient
    trace → no header: propagation never invents a root."""
    ctx = span if span is not None else _tracer.current_span()
    if ctx is not None and getattr(ctx, "trace_id", None):
        headers[TRACEPARENT] = format_traceparent(ctx)
    return headers


def extract(headers) -> TraceContext | None:
    """Parse the traceparent header (case-insensitive lookup) back into
    a :class:`TraceContext`; None when absent or malformed — a garbled
    header degrades to a fresh root, never an error."""
    if not headers:
        return None
    value = None
    for k, v in headers.items():
        if str(k).lower() == TRACEPARENT:
            value = str(v)
            break
    if value is None:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    _version, trace_id, span_id, _flags = parts
    if not (_hexish(trace_id.lower()) and _hexish(span_id.lower())):
        return None
    return TraceContext(trace_id=trace_id.lower(), span_id=span_id.lower())


def trace_of(headers) -> str | None:
    """Just the trace id from a headers dict (log/lookup convenience)."""
    ctx = extract(headers)
    return ctx.trace_id if ctx is not None else None


def span_from_dict(d: dict) -> Span:
    """Rebuild a finished span from its ``Span.to_dict`` wire form (the
    mesh reply payload). Unknown/missing fields default safely."""
    span = Span(
        name=str(d.get("name", "")),
        trace_id=str(d.get("traceId", "")),
        span_id=str(d.get("spanId", "")),
        parent_id=d.get("parentId"),
        attrs=dict(d.get("attrs") or {}),
        start_wall=float(d.get("startWall") or 0.0),
        seconds=(None if d.get("seconds") is None
                 else float(d["seconds"])),
        error=d.get("error"),
        proc=str(d.get("proc", "")),
    )
    span._done = True
    return span
