"""Embedded time-series store: ONE bounded history substrate.

Until this module, every consumer of "how has this series moved" kept
a private history: BurnRateMonitor held a tick list, the Autoscaler a
depth deque, the StragglerDetector only its last flag set, and the
cost model an EWMA nobody could query. Each invented its own
retention, none was visible over HTTP, and the perf-regression
sentinel (``obs.regression``) would have needed a fourth copy. This
module is the shared substrate instead:

- :class:`TimeSeriesStore` — per-series ring buffers keyed by the
  REGISTRY SAMPLE NAME (``name{label="v"}``), timestamps derived from
  ``time.monotonic`` (graftcheck's wallclock pass holds for ``obs/``).
  Bounded three ways, each with a loud eviction counter
  (``obs_timeseries_evicted_total{reason}``): per-series point cap
  (``ring``), per-series retention horizon (``retention``), and a
  global point bound across all series (``global``).
- :class:`Recorder` — a tick that snapshots the metrics registry,
  filters to the federated prefixes (``profile_``, ``sched_``,
  ``serving_``, ``mem_``, ``fleet_``, ``aot_``, ``slo_``), and appends
  every matching sample. Run it manually (tests, health ticks) or as a
  background thread (:meth:`Recorder.start`).
- a PromQL-shaped query API: :meth:`~TimeSeriesStore.range`,
  :meth:`~TimeSeriesStore.rate` / :meth:`~TimeSeriesStore.increase`
  (counters), ``avg/min/max_over_time``, ``mad_over_time`` (the robust
  dispersion the straggler flap suppression uses), and
  :meth:`~TimeSeriesStore.quantile_over_time` which rebuilds
  quantiles from Histogram ``_bucket{le=...}`` deltas over the window
  via the same :func:`~mmlspark_tpu.obs.metrics.bucket_quantile`
  estimator ``Histogram.quantile`` uses.
- :func:`timeline_payload` — the JSON body both serving fronts expose
  at ``GET /debug/timeline?series=<patterns>&window=<seconds>``.

Import is stdlib-only and side-effect-free (the CI no-JAX smoke
imports it with no jax in the process). All shared state mutates under
the store's lock; registry handles do their own locking.
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import deque

from .metrics import bucket_quantile, registry as _registry

__all__ = [
    "DEFAULT_RECORD_PREFIXES",
    "Recorder",
    "TimeSeriesStore",
    "recorder",
    "timeline_payload",
    "timeseries_store",
]

#: registry prefixes the Recorder samples by default — the same
#: families the fleet plane federates, plus the SLO burn series.
DEFAULT_RECORD_PREFIXES = (
    "profile_", "sched_", "serving_", "mem_", "fleet_", "aot_", "slo_",
    "kv_", "gen_", "deploy_", "goodput_",
)

#: /debug/timeline response bounds: series per response, points per
#: series — a scrape surface must not become an OOM surface.
_TIMELINE_MAX_SERIES = 64
_TIMELINE_MAX_POINTS = 512


class _Ring:
    """One series' bounded history: (t, value) points plus its limits."""

    __slots__ = ("pts", "maxlen", "retention_s")

    def __init__(self, maxlen: int, retention_s: float):
        self.pts: deque = deque()
        self.maxlen = int(maxlen)
        self.retention_s = float(retention_s)


class TimeSeriesStore:
    """Bounded in-process TSDB over registry sample names.

    ``clock`` must be monotonic-derived (default ``time.monotonic``) —
    timestamps are spans since an arbitrary origin, never wall time, so
    a suspended host or an NTP step cannot tear a window. Tests inject
    a hand-cranked clock for frozen-time assertions.
    """

    def __init__(self, registry=None, *, clock=time.monotonic,
                 default_maxlen: int = 512,
                 default_retention_s: float = 900.0,
                 max_total_points: int = 200_000):
        self._reg = registry if registry is not None else _registry
        self._clock = clock
        self.default_maxlen = int(default_maxlen)
        self.default_retention_s = float(default_retention_s)
        self.max_total_points = int(max_total_points)
        self._lock = threading.Lock()
        self._rings: dict[str, _Ring] = {}
        self._total = 0
        self._c_evicted = self._reg.counter(
            "obs_timeseries_evicted_total",
            "history points dropped, by reason "
            "(ring | retention | global)")
        self._g_series = self._reg.gauge(
            "obs_timeseries_series", "live series in the history store")
        self._g_points = self._reg.gauge(
            "obs_timeseries_points", "total points across all series")

    # -- write path --------------------------------------------------------

    def ensure(self, series: str, *, maxlen: int | None = None,
               retention_s: float | None = None) -> None:
        """Create (or re-limit) one series' ring. Consumers with a
        known horizon (burn windows, depth trends) size their rings
        here instead of inheriting the defaults."""
        with self._lock:
            self._ensure_locked(series, maxlen, retention_s)

    def _ensure_locked(self, series: str, maxlen, retention_s) -> _Ring:
        ring = self._rings.get(series)
        if ring is None:
            ring = self._rings[series] = _Ring(
                maxlen if maxlen is not None else self.default_maxlen,
                retention_s if retention_s is not None
                else self.default_retention_s)
        else:
            if maxlen is not None:
                ring.maxlen = int(maxlen)
            if retention_s is not None:
                ring.retention_s = float(retention_s)
        return ring

    def append(self, series: str, value: float, *, t: float | None = None,
               maxlen: int | None = None,
               retention_s: float | None = None) -> None:
        """Append one point (timestamp = store clock unless given)."""
        self.append_many({series: value}, t=t, maxlen=maxlen,
                         retention_s=retention_s)

    def append_many(self, samples: dict, *, t: float | None = None,
                    maxlen: int | None = None,
                    retention_s: float | None = None) -> int:
        """Append a batch under one lock hold (the Recorder hot path).
        Non-numeric values are skipped. Returns points appended."""
        now = self._clock() if t is None else float(t)
        evicted = {"ring": 0, "retention": 0, "global": 0}
        n = 0
        with self._lock:
            for series, value in samples.items():
                try:
                    v = float(value)
                except (TypeError, ValueError):
                    continue
                ring = self._ensure_locked(series, maxlen, retention_s)
                ring.pts.append((now, v))
                self._total += 1
                n += 1
                while len(ring.pts) > ring.maxlen:
                    ring.pts.popleft()
                    self._total -= 1
                    evicted["ring"] += 1
                horizon = now - ring.retention_s
                while ring.pts and ring.pts[0][0] < horizon:
                    ring.pts.popleft()
                    self._total -= 1
                    evicted["retention"] += 1
            evicted["global"] += self._enforce_global_locked()
            n_series, n_points = len(self._rings), self._total
        for reason, count in evicted.items():
            if count:
                self._c_evicted.inc(count, reason=reason)
        self._g_series.set(n_series)
        self._g_points.set(n_points)
        return n

    def _enforce_global_locked(self) -> int:
        """Oldest-first global eviction: while over the total bound,
        drop the oldest point in the store (whichever series holds it).
        Loud by design — a tripped global bound means some producer's
        cardinality needs a look, not silent data loss."""
        dropped = 0
        while self._total > self.max_total_points:
            oldest_key = None
            oldest_t = math.inf
            for key, ring in self._rings.items():
                if ring.pts and ring.pts[0][0] < oldest_t:
                    oldest_t = ring.pts[0][0]
                    oldest_key = key
            if oldest_key is None:
                break
            ring = self._rings[oldest_key]
            ring.pts.popleft()
            self._total -= 1
            dropped += 1
            if not ring.pts:
                del self._rings[oldest_key]
        return dropped

    def clear(self) -> None:
        """Drop every series (test isolation)."""
        with self._lock:
            self._rings.clear()
            self._total = 0
        self._g_series.set(0)
        self._g_points.set(0)

    # -- read path ---------------------------------------------------------

    def now(self) -> float:
        return self._clock()

    def size(self) -> tuple[int, int]:
        """(series, total points)."""
        with self._lock:
            return len(self._rings), self._total

    def series_names(self, pattern: str = "") -> list[str]:
        """Sorted series names; ``pattern`` is a prefix filter."""
        with self._lock:
            names = list(self._rings)
        return sorted(n for n in names if n.startswith(pattern))

    def points(self, series: str, window: float | None = None,
               now: float | None = None) -> list:
        """One series' ``[(t, value), ...]`` oldest-first, optionally
        clipped to the trailing ``window`` seconds."""
        with self._lock:
            ring = self._rings.get(series)
            pts = list(ring.pts) if ring is not None else []
        if window is None:
            return pts
        t0 = (self._clock() if now is None else now) - float(window)
        return [p for p in pts if p[0] >= t0]

    def last_n(self, series: str, n: int) -> list:
        """The newest ``n`` points, oldest-first."""
        with self._lock:
            ring = self._rings.get(series)
            if ring is None:
                return []
            pts = list(ring.pts)
        return pts[-int(n):] if n > 0 else []

    def latest(self, series: str):
        """Newest ``(t, value)`` or None."""
        pts = self.last_n(series, 1)
        return pts[0] if pts else None

    def range(self, patterns, window: float | None = None) -> dict:
        """``{series: [(t, value), ...]}`` for every series matching
        any pattern (exact name or name prefix — a bare family name
        matches all its label combinations)."""
        if isinstance(patterns, str):
            patterns = [patterns]
        pats = [p for p in patterns if p]
        with self._lock:
            names = list(self._rings)
        out = {}
        now = self._clock()
        for name in sorted(names):
            if any(name == p or name.startswith(p) for p in pats):
                out[name] = self.points(name, window, now=now)
        return out

    # -- window functions --------------------------------------------------

    def increase(self, series: str, window: float) -> float:
        """Counter increase over the window: the sum of positive
        deltas, so a counter reset (process restart mid-window) loses
        the pre-reset increase instead of fabricating a negative one."""
        pts = self.points(series, window)
        inc = 0.0
        for (_, a), (_, b) in zip(pts, pts[1:]):
            if b > a:
                inc += b - a
        return inc

    def rate(self, series: str, window: float) -> float:
        """Per-second counter rate over the window (0.0 under 2 points
        or zero elapsed)."""
        pts = self.points(series, window)
        if len(pts) < 2:
            return 0.0
        elapsed = pts[-1][0] - pts[0][0]
        if elapsed <= 0:
            return 0.0
        return self.increase(series, window) / elapsed

    def _values(self, series: str, window: float) -> list:
        return [v for _, v in self.points(series, window)]

    def avg_over_time(self, series: str, window: float) -> float:
        vals = self._values(series, window)
        return sum(vals) / len(vals) if vals else 0.0

    def min_over_time(self, series: str, window: float) -> float:
        vals = self._values(series, window)
        return min(vals) if vals else 0.0

    def max_over_time(self, series: str, window: float) -> float:
        vals = self._values(series, window)
        return max(vals) if vals else 0.0

    @staticmethod
    def _median(vals: list) -> float:
        vals = sorted(vals)
        n = len(vals)
        mid = n // 2
        return vals[mid] if n % 2 else (vals[mid - 1] + vals[mid]) / 2.0

    def mad_over_time(self, series: str, window: float) -> float:
        """Median absolute deviation of the window's values — the
        robust dispersion behind straggler flap suppression and the
        offline gate's noise tolerance. 0.0 under 2 points."""
        vals = self._values(series, window)
        if len(vals) < 2:
            return 0.0
        med = self._median(vals)
        return self._median([abs(v - med) for v in vals])

    def quantile_over_time(self, family: str, q: float, window: float,
                           **labels) -> float:
        """Reconstruct the ``q``-quantile of a HISTOGRAM family's
        observations made during the window, from the recorded
        cumulative ``<family>_bucket{le=...}`` series (label filter =
        subset match). Bucket increases over the window un-cumulate
        into per-bucket counts; :func:`bucket_quantile` interpolates —
        so the serving p99 the sentinel watches is a WINDOWED p99, not
        the all-time one the raw registry snapshot gives. 0.0 when no
        observation landed in the window."""
        prefix = f"{family}_bucket{{"
        want = [f'{k}="{v}"' for k, v in labels.items()]
        per_le: dict[float, float] = {}
        for name in self.series_names(prefix):
            if any(w not in name for w in want):
                continue
            le = _parse_le(name)
            if le is None:
                continue
            per_le[le] = per_le.get(le, 0.0) + self.increase(name, window)
        if not per_le:
            return 0.0
        bounds = sorted(b for b in per_le if not math.isinf(b))
        if not bounds:
            return 0.0
        counts, prev = [], 0.0
        for b in bounds:
            counts.append(max(0.0, per_le[b] - prev))
            prev = per_le[b]
        inf_cum = per_le.get(math.inf, prev)
        counts.append(max(0.0, inf_cum - prev))
        return bucket_quantile(tuple(bounds), counts, q)

    # -- HTTP export -------------------------------------------------------

    def timeline_payload(self, query: str = "") -> tuple[int, bytes]:
        """The ``GET /debug/timeline?series=&window=`` body (both
        serving fronts route here). ``series`` is a comma-separated
        pattern list (exact sample name or prefix); without it the
        response is an index of series names + sizes, so an operator
        can discover what to ask for. ``window`` defaults to 300 s."""
        params = _parse_qs(query)
        window = 300.0
        try:
            if params.get("window"):
                window = float(params["window"])
        except ValueError:
            return 400, b'{"error": "window must be a number"}'
        pats = [p for p in params.get("series", "").split(",") if p]
        n_series, n_points = self.size()
        body = {
            "window_s": window,
            "now": self.now(),
            "series_total": n_series,
            "points_total": n_points,
        }
        if not pats:
            body["series"] = {
                name: len(self.points(name))
                for name in self.series_names()[:_TIMELINE_MAX_SERIES]}
        else:
            matched = self.range(pats, window)
            truncated = len(matched) > _TIMELINE_MAX_SERIES
            body["truncated"] = truncated
            body["series"] = {
                name: [[round(t, 4), v] for t, v in
                       pts[-_TIMELINE_MAX_POINTS:]]
                for name, pts in
                list(matched.items())[:_TIMELINE_MAX_SERIES]}
        return 200, json.dumps(body).encode()


def _parse_le(sample: str) -> float | None:
    """Extract the ``le`` bound from a rendered bucket sample name."""
    i = sample.find('le="')
    if i < 0:
        return None
    j = sample.find('"', i + 4)
    if j < 0:
        return None
    raw = sample[i + 4:j]
    if raw == "+Inf":
        return math.inf
    try:
        return float(raw)
    except ValueError:
        return None


def _parse_qs(query: str) -> dict:
    """Tiny query-string parser (last value wins; %xx unescaping via
    stdlib). Kept local so the native front's poller thread never
    imports urllib lazily under load."""
    from urllib.parse import unquote_plus
    out: dict[str, str] = {}
    for part in (query or "").split("&"):
        if not part:
            continue
        k, _, v = part.partition("=")
        out[unquote_plus(k)] = unquote_plus(v)
    return out


class Recorder:
    """Samples registry prefixes into the store, one tick at a time.

    ``tick()`` is the unit of work: snapshot the registry, keep samples
    matching the configured prefixes, append them all at one timestamp.
    Drive it from a health loop for lockstep tests, or
    :meth:`start` the background thread (idempotent) for production.
    Its own cost is exported (``obs_recorder_tick_seconds``) so the
    ≤1% serving-p99 overhead contract is itself a watchable series.
    """

    def __init__(self, store: TimeSeriesStore | None = None,
                 registry=None, *,
                 prefixes=DEFAULT_RECORD_PREFIXES,
                 interval_s: float = 1.0):
        self._reg = registry if registry is not None else _registry
        self.store = store if store is not None else timeseries_store
        self.prefixes = tuple(prefixes)
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._c_ticks = self._reg.counter(
            "obs_recorder_ticks_total", "history recorder ticks")
        self._c_points = self._reg.counter(
            "obs_recorder_points_total", "samples recorded into history")
        self._g_cost = self._reg.gauge(
            "obs_recorder_tick_seconds", "wall cost of the last tick")

    def tick(self) -> int:
        """One sampling pass. Returns points appended."""
        t0 = time.perf_counter()
        snap = self._reg.snapshot()
        picked = {k: v for k, v in snap.items()
                  if k.startswith(self.prefixes)}
        n = self.store.append_many(picked)
        self._c_ticks.inc()
        if n:
            self._c_points.inc(n)
        self._g_cost.set(time.perf_counter() - t0)
        return n

    # -- background loop ---------------------------------------------------

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self, interval_s: float | None = None) -> "Recorder":
        """Start the background sampling thread (idempotent)."""
        with self._lock:
            if interval_s is not None:
                self.interval_s = float(interval_s)
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, name="obs-recorder", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
            self._stop.set()
        if thread is not None:
            thread.join(timeout=5)

    def _loop(self) -> None:
        stop = self._stop
        while not stop.is_set():
            try:
                self.tick()
            except Exception:
                # a bad sample must not kill the history plane
                pass
            stop.wait(self.interval_s)


#: THE process-wide history substrate — burn windows, depth trends,
#: straggler score histories, and the regression sentinel all read it.
timeseries_store = TimeSeriesStore()

#: THE process-wide recorder over it (started by ``serving_query``;
#: tests tick it by hand).
recorder = Recorder(timeseries_store)


def timeline_payload(query: str = "",
                     store: TimeSeriesStore | None = None
                     ) -> tuple[int, bytes]:
    """Route-shaped helper: the serving fronts call this with the raw
    query string of ``GET /debug/timeline``."""
    return (store if store is not None
            else timeseries_store).timeline_payload(query)
