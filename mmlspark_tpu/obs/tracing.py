"""Spans and the process-wide tracer.

The reference has no tracer (SURVEY §5) — only the ``Timer`` transformer
and VW's stopwatches. This is the structured replacement: a
:class:`Span` is a named, timed region with a trace id, a span id, and a
parent id propagated through ``contextvars`` — nest ``tracer.span``
calls and the tree falls out. Spans emit as JSON events through the SAME
logger ``BasicLogging`` writes stage telemetry to
(``mmlspark_tpu.telemetry``), so one sink carries both: a traced
LightGBM ``fit`` shows the stage event and its nested boosting-round
spans side by side.

Device time: a span with ``device=True`` additionally wraps the region
in ``jax.profiler.TraceAnnotation`` so it shows up named in XProf
traces captured by ``utils.profiling.profile_trace`` — wall time on the
span, device time in the profile, correlated by name. JAX is imported
lazily and only then; this module must import with no backend.

Cross-thread propagation: ``contextvars`` do not cross ``threading``
boundaries, so hand the parent over explicitly —
``tracer.span("work", parent=parent_span)`` — exactly what the serving
worker pool does per batch.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field

from .metrics import registry as _registry

# the BasicLogging sink, by name (NOT by import: core imports obs for
# span linkage, so obs importing core back would cycle)
_TELEMETRY = logging.getLogger("mmlspark_tpu.telemetry")

_ids = itertools.count(1)
_id_lock = threading.Lock()
_PROC = f"{os.getpid():x}"


def _new_id() -> str:
    with _id_lock:
        return f"{_PROC}-{next(_ids):x}"


@dataclass
class Span:
    """One named, timed region. ``seconds`` is None until the span ends."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str | None = None
    attrs: dict = field(default_factory=dict)
    start_wall: float = 0.0       # epoch seconds (event timestamps)
    seconds: float | None = None  # wall duration, set at end
    error: str | None = None
    _t0: float = 0.0              # perf_counter anchor

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value


_current_span: contextvars.ContextVar[Span | None] = \
    contextvars.ContextVar("mmlspark_tpu_obs_span", default=None)

_UNSET = object()


class Tracer:
    """Creates spans, propagates parentage, emits span events.

    ``metric`` (a histogram name) records each span's wall seconds into
    the metrics registry labeled by span name — tracing and metrics stay
    one subsystem, not two."""

    def __init__(self, registry=None, metric: str | None = None):
        self.registry = registry if registry is not None else _registry
        self.metric = metric

    # -- context -----------------------------------------------------------
    def current_span(self) -> Span | None:
        return _current_span.get()

    # -- span lifecycle ----------------------------------------------------
    def start_span(self, name: str, *, parent=_UNSET,
                   current: bool = True, **attrs) -> Span:
        """Begin a span. Prefer the ``span(...)`` context manager; this
        begin/end surface exists for regions that cannot nest a ``with``
        block (e.g. a loop body with breaks). Every ``start_span`` must
        be paired with ``end_span``. ``current=False`` records parentage
        without touching the ambient context — children must then name
        this span as ``parent=`` explicitly, but an unpaired end can
        never corrupt the context of unrelated spans."""
        if parent is _UNSET:
            parent = _current_span.get()
        if isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = _new_id(), None
        span = Span(name=name, trace_id=trace_id, span_id=_new_id(),
                    parent_id=parent_id, attrs=dict(attrs),
                    start_wall=time.time(), _t0=time.perf_counter())
        if current:
            span._token = _current_span.set(span)
        return span

    def end_span(self, span: Span, error: BaseException | None = None,
                 emit: bool = True) -> Span:
        if getattr(span, "_done", False):
            return span  # already ended (loop break + fallthrough)
        span._done = True
        span.seconds = time.perf_counter() - span._t0
        if error is not None:
            span.error = repr(error)
        token = getattr(span, "_token", None)
        if token is not None:
            span._token = None
            try:
                _current_span.reset(token)
            except ValueError:
                # ended from a different context than it started in
                # (cross-thread hand-off); parentage is already recorded
                pass
        if emit:
            self._emit(span)
        if self.metric is not None:
            self.registry.histogram(
                self.metric, "span wall seconds").observe(
                    span.seconds, span=span.name)
        return span

    @contextlib.contextmanager
    def span(self, name: str, *, parent=_UNSET, device: bool = False,
             **attrs):
        """``with tracer.span("stage.fit", rows=n) as sp: ...``

        ``parent``: explicit parent Span (or None to force a new root) —
        required when crossing a thread boundary. ``device=True`` also
        annotates the region for XProf device traces."""
        span = self.start_span(name, parent=parent, **attrs)
        ann = None
        if device:
            try:
                import jax
                ann = jax.profiler.TraceAnnotation(name)
                ann.__enter__()
            except Exception:
                ann = None
        try:
            yield span
        except BaseException as e:
            self.end_span(span, error=e)
            raise
        finally:
            if ann is not None:
                ann.__exit__(None, None, None)
            self.end_span(span)

    # -- emission ----------------------------------------------------------
    def _emit(self, span: Span) -> None:
        # same gate BasicLogging rides on: when nothing listens at INFO
        # the span costs two clock reads and a few dict ops, no json
        if not _TELEMETRY.isEnabledFor(logging.INFO):
            return
        payload = {
            "event": "span",
            "name": span.name,
            "traceId": span.trace_id,
            "spanId": span.span_id,
            "parentId": span.parent_id,
            "startWall": span.start_wall,
            "seconds": span.seconds,
        }
        if span.attrs:
            payload["attrs"] = {k: v for k, v in span.attrs.items()
                                if isinstance(v, (str, int, float, bool,
                                                  type(None)))}
        if span.error is not None:
            payload["error"] = span.error
        _TELEMETRY.info(json.dumps(payload))


# THE process-wide tracer (parallel to ``metrics.registry``).
tracer = Tracer()


class StageTimer:
    """Accumulate named wall-clock spans (the VW ``TrainingStats``
    nanosecond-timing surface, ``vw/VowpalWabbitBase.scala:27-49``).

    Subsumed by the obs tracer: each ``span`` both nests in the ambient
    trace (so it shows up in the telemetry sink with parentage) and
    accumulates into ``totals_ns`` — the original surface callers keep.
    """

    def __init__(self, tracer_: Tracer | None = None):
        self.totals_ns: dict[str, int] = {}
        self._tracer = tracer_ or tracer

    @contextlib.contextmanager
    def span(self, name: str):
        t0 = time.perf_counter_ns()
        try:
            with self._tracer.span(name):
                yield
        finally:
            self.totals_ns[name] = self.totals_ns.get(name, 0) + \
                time.perf_counter_ns() - t0

    def as_dict(self) -> dict[str, float]:
        return {k: v / 1e9 for k, v in self.totals_ns.items()}
