"""Spans and the process-wide tracer.

The reference has no tracer (SURVEY §5) — only the ``Timer`` transformer
and VW's stopwatches. This is the structured replacement: a
:class:`Span` is a named, timed region with a trace id, a span id, and a
parent id propagated through ``contextvars`` — nest ``tracer.span``
calls and the tree falls out. Spans emit as JSON events through the SAME
logger ``BasicLogging`` writes stage telemetry to
(``mmlspark_tpu.telemetry``), so one sink carries both: a traced
LightGBM ``fit`` shows the stage event and its nested boosting-round
spans side by side.

Device time: a span with ``device=True`` additionally wraps the region
in ``jax.profiler.TraceAnnotation`` so it shows up named in XProf
traces captured by ``utils.profiling.profile_trace`` — wall time on the
span, device time in the profile, correlated by name. JAX is imported
lazily and only then; this module must import with no backend.

Cross-thread propagation: ``contextvars`` do not cross ``threading``
boundaries, so hand the parent over explicitly —
``tracer.span("work", parent=parent_span)`` — exactly what the serving
worker pool does per batch.

Cross-PROCESS propagation lives in :mod:`.propagation` (W3C-style
``traceparent`` headers / lease metadata): ``start_span`` accepts any
parent carrying ``trace_id``/``span_id`` attributes, so an extracted
remote context parents a local span directly. Ids are pure lowercase
hex for exactly that reason — they must survive a ``-``-delimited
header field.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field

from .metrics import registry as _registry

# the BasicLogging sink, by name (NOT by import: core imports obs for
# span linkage, so obs importing core back would cycle)
_TELEMETRY = logging.getLogger("mmlspark_tpu.telemetry")

_ids = itertools.count(1)
_id_lock = threading.Lock()
_PROC = f"{os.getpid():x}"

# Wall-clock anchor taken ONCE at import: span timestamps are civil time
# for trace viewers, but deriving them from the monotonic clock after
# this single read means an NTP step mid-run can never make a child span
# appear to start before its parent (and no deadline-path code ever
# reads time.time()).
_WALL0 = time.time()
_PERF0 = time.perf_counter()


def wall_now() -> float:
    """Epoch seconds derived from the monotonic clock (one wall read at
    import, monotonic deltas after) — the timestamp base for every span."""
    return _WALL0 + (time.perf_counter() - _PERF0)


def _new_id() -> str:
    # pure hex (no separators): ids travel inside W3C-style traceparent
    # headers where "-" delimits fields. The zero-padded counter keeps
    # pid-prefix + counter concatenation collision-free per process.
    with _id_lock:
        return f"{_PROC}{next(_ids):06x}"


@dataclass
class Span:
    """One named, timed region. ``seconds`` is None until the span ends."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str | None = None
    attrs: dict = field(default_factory=dict)
    start_wall: float = 0.0       # epoch seconds (event timestamps)
    seconds: float | None = None  # wall duration, set at end
    error: str | None = None
    proc: str = ""                # emitting process (hex pid)
    _t0: float = 0.0              # perf_counter anchor

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def to_dict(self) -> dict:
        """Wire/export form — the same field names ``Tracer._emit``
        writes to the telemetry log, so a span serialized into a mesh
        reply and a span grepped from the log read identically."""
        payload = {
            "event": "span",
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "startWall": self.start_wall,
            "seconds": self.seconds,
            "proc": self.proc or _PROC,
        }
        if self.attrs:
            payload["attrs"] = {k: v for k, v in self.attrs.items()
                                if isinstance(v, (str, int, float, bool,
                                                  type(None)))}
        if self.error is not None:
            payload["error"] = self.error
        return payload


_current_span: contextvars.ContextVar[Span | None] = \
    contextvars.ContextVar("mmlspark_tpu_obs_span", default=None)

_UNSET = object()


class Tracer:
    """Creates spans, propagates parentage, emits span events.

    ``metric`` (a histogram name) records each span's wall seconds into
    the metrics registry labeled by span name — tracing and metrics stay
    one subsystem, not two."""

    def __init__(self, registry=None, metric: str | None = None):
        self.registry = registry if registry is not None else _registry
        self.metric = metric
        # finished-span sinks (the flight recorder / test collectors):
        # called on EVERY end_span regardless of the logging gate
        self._sinks: list = []

    # -- context -----------------------------------------------------------
    def current_span(self) -> Span | None:
        return _current_span.get()

    # -- sinks -------------------------------------------------------------
    def add_sink(self, sink) -> None:
        """Register ``sink(span)`` to receive every finished span
        (idempotent). Sinks run on the ending thread and must be cheap
        and never raise — the flight recorder's collection hook."""
        if sink not in self._sinks:
            self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        try:
            self._sinks.remove(sink)
        except ValueError:
            pass

    # -- span lifecycle ----------------------------------------------------
    def start_span(self, name: str, *, parent=_UNSET,
                   current: bool = True, **attrs) -> Span:
        """Begin a span. Prefer the ``span(...)`` context manager; this
        begin/end surface exists for regions that cannot nest a ``with``
        block (e.g. a loop body with breaks). Every ``start_span`` must
        be paired with ``end_span``. ``current=False`` records parentage
        without touching the ambient context — children must then name
        this span as ``parent=`` explicitly, but an unpaired end can
        never corrupt the context of unrelated spans."""
        if parent is _UNSET:
            parent = _current_span.get()
        # duck-typed parentage: a Span OR any context carrying
        # trace_id/span_id (a propagation.TraceContext extracted from a
        # remote hop) parents this span into its trace
        tid = getattr(parent, "trace_id", None)
        if tid is not None:
            trace_id, parent_id = tid, getattr(parent, "span_id", None)
        else:
            trace_id, parent_id = _new_id(), None
        span = Span(name=name, trace_id=trace_id, span_id=_new_id(),
                    parent_id=parent_id, attrs=dict(attrs),
                    start_wall=wall_now(), proc=_PROC,
                    _t0=time.perf_counter())
        if current:
            span._token = _current_span.set(span)
        return span

    def end_span(self, span: Span, error: BaseException | None = None,
                 emit: bool = True) -> Span:
        if getattr(span, "_done", False):
            return span  # already ended (loop break + fallthrough)
        span._done = True
        span.seconds = time.perf_counter() - span._t0
        if error is not None:
            span.error = repr(error)
        token = getattr(span, "_token", None)
        if token is not None:
            span._token = None
            try:
                _current_span.reset(token)
            except ValueError:
                # ended from a different context than it started in
                # (cross-thread hand-off); parentage is already recorded
                pass
        if emit:
            self._emit(span)
        if self.metric is not None:
            self.registry.histogram(
                self.metric, "span wall seconds").observe(
                    span.seconds, span=span.name)
        return span

    @contextlib.contextmanager
    def span(self, name: str, *, parent=_UNSET, device: bool = False,
             **attrs):
        """``with tracer.span("stage.fit", rows=n) as sp: ...``

        ``parent``: explicit parent Span (or None to force a new root) —
        required when crossing a thread boundary. ``device=True`` also
        annotates the region for XProf device traces."""
        span = self.start_span(name, parent=parent, **attrs)
        ann = None
        if device:
            try:
                import jax
                ann = jax.profiler.TraceAnnotation(name)
                ann.__enter__()
            except Exception:
                ann = None
        try:
            yield span
        except BaseException as e:
            self.end_span(span, error=e)
            raise
        finally:
            if ann is not None:
                ann.__exit__(None, None, None)
            self.end_span(span)

    # -- retroactive spans -------------------------------------------------
    def emit_span(self, name: str, *, parent, seconds: float,
                  start_wall: float | None = None,
                  error: str | None = None, **attrs) -> Span:
        """Synthesize an already-measured span — for durations observed
        after the fact (a queue wait known only at pop time, a worker's
        share of a batch). ``parent`` is a Span / TraceContext / None;
        ``start_wall`` defaults to ``now - seconds``."""
        tid = getattr(parent, "trace_id", None)
        if tid is not None:
            trace_id, parent_id = tid, getattr(parent, "span_id", None)
        else:
            trace_id, parent_id = _new_id(), None
        seconds = max(float(seconds), 0.0)
        span = Span(name=name, trace_id=trace_id, span_id=_new_id(),
                    parent_id=parent_id, attrs=dict(attrs),
                    start_wall=(wall_now() - seconds
                                if start_wall is None else start_wall),
                    seconds=seconds, error=error, proc=_PROC)
        span._done = True
        self._emit(span)
        if self.metric is not None:
            self.registry.histogram(
                self.metric, "span wall seconds").observe(
                    span.seconds, span=span.name)
        return span

    # -- emission ----------------------------------------------------------
    def _emit(self, span: Span) -> None:
        # sinks first, and unconditionally: the flight recorder must see
        # spans even when nobody listens to the telemetry log
        for sink in self._sinks:
            try:
                sink(span)
            except Exception:
                pass  # a broken sink must never kill the traced code
        # same gate BasicLogging rides on: when nothing listens at INFO
        # the span costs two clock reads and a few dict ops, no json
        if not _TELEMETRY.isEnabledFor(logging.INFO):
            return
        _TELEMETRY.info(json.dumps(span.to_dict()))


# THE process-wide tracer (parallel to ``metrics.registry``).
tracer = Tracer()


class StageTimer:
    """Accumulate named wall-clock spans (the VW ``TrainingStats``
    nanosecond-timing surface, ``vw/VowpalWabbitBase.scala:27-49``).

    Subsumed by the obs tracer: each ``span`` both nests in the ambient
    trace (so it shows up in the telemetry sink with parentage) and
    accumulates into ``totals_ns`` — the original surface callers keep.
    """

    def __init__(self, tracer_: Tracer | None = None):
        self.totals_ns: dict[str, int] = {}
        self._tracer = tracer_ or tracer

    @contextlib.contextmanager
    def span(self, name: str):
        t0 = time.perf_counter_ns()
        try:
            with self._tracer.span(name):
                yield
        finally:
            self.totals_ns[name] = self.totals_ns.get(name, 0) + \
                time.perf_counter_ns() - t0

    def as_dict(self) -> dict[str, float]:
        return {k: v / 1e9 for k, v in self.totals_ns.items()}
