"""Unified observability: process-wide metrics + tracing + profiling.

One registry (``registry``), one tracer (``tracer``), one flight
recorder (``flight_recorder``), one compile tracker
(``compile_tracker``) shared by every layer — serving fronts, the
distributed worker mesh, the resilience subsystem (retry/breaker/
fault-injection series), collectives, the LightGBM boosting loop, and
the bench suite — replacing the fragmented per-component stopwatches
the reference inherited (per-stage JSON telemetry + VW nanosecond
timers, SURVEY §5). Cross-process trace propagation lives in
``obs.propagation`` (W3C-style traceparent), Chrome-trace export and
the flight recorder in ``obs.export``, the continuous compile/step
profiler and cost-model feature log in ``obs.profile``. The telemetry
HISTORY plane (ISSUE 16) lives in ``obs.timeseries`` — one bounded
in-process time-series store (``timeseries_store``) fed by a
``Recorder`` tick over the registry, served at ``GET /debug/timeline``
— and ``obs.regression`` watches it live (CUSUM step-change sentinel)
and gates bench trajectories offline. See docs/observability.md.

Import is side-effect-free and backend-free: safe under
``JAX_PLATFORMS=cpu`` before (or without) JAX initialization.
"""

from .metrics import (DEFAULT_LATENCY_BUCKETS, Counter, Gauge, Histogram,
                      MetricsRegistry, registry)
from .tracing import Span, StageTimer, Tracer, tracer, wall_now
from .propagation import TraceContext, extract, inject
from .export import (FlightRecorder, SpanCollector, chrome_trace,
                     flight_recorder)
from .profile import (FEATURE_SCHEMA_VERSION, CompileTracker, FeatureLog,
                      StepProfiler, compile_tracker, feature_log,
                      step_profiler)
from .memory import MemoryProfiler, device_memory_stats, memory_profiler
from .timeseries import (Recorder, TimeSeriesStore, recorder,
                         timeline_payload, timeseries_store)
from .fleet import (BurnRateMonitor, FleetAggregator, FleetHealth,
                    StragglerDetector, fleet_aggregator, fleet_health,
                    local_fleet_snapshot, parse_exposition, parse_sample,
                    straggler_workers)
from .regression import (CusumDetector, RegressionSentinel, compare_benches,
                         sentinel)
from .attribution import (PEAK_SPECS, CostAttribution, PeakSpec,
                          cost_attribution, peak_spec)
from .goodput import (WASTE_CAUSES, GoodputLedger, goodput_ledger,
                      goodput_payload)
from .xprof import XprofCaptures, xprof_captures

__all__ = ["registry", "tracer", "MetricsRegistry", "Counter", "Gauge",
           "Histogram", "Tracer", "Span", "StageTimer", "wall_now",
           "DEFAULT_LATENCY_BUCKETS",
           "TraceContext", "extract", "inject",
           "FlightRecorder", "SpanCollector", "chrome_trace",
           "flight_recorder",
           "CompileTracker", "FeatureLog", "StepProfiler",
           "FEATURE_SCHEMA_VERSION",
           "compile_tracker", "feature_log", "step_profiler",
           "MemoryProfiler", "device_memory_stats", "memory_profiler",
           "FleetAggregator", "FleetHealth", "StragglerDetector",
           "BurnRateMonitor", "fleet_aggregator", "fleet_health",
           "local_fleet_snapshot", "parse_exposition", "parse_sample",
           "straggler_workers",
           "TimeSeriesStore", "Recorder", "timeseries_store", "recorder",
           "timeline_payload",
           "CusumDetector", "RegressionSentinel", "compare_benches",
           "sentinel",
           "PeakSpec", "PEAK_SPECS", "CostAttribution", "peak_spec",
           "cost_attribution",
           "GoodputLedger", "WASTE_CAUSES", "goodput_ledger",
           "goodput_payload",
           "XprofCaptures", "xprof_captures"]
