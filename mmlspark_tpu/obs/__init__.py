"""Unified observability: process-wide metrics + tracing.

One registry (``registry``) and one tracer (``tracer``) shared by every
layer — serving fronts, the distributed worker mesh, the resilience
subsystem (retry/breaker/fault-injection series), collectives, the
LightGBM boosting loop, and the bench suite — replacing the fragmented
per-component stopwatches the reference inherited (per-stage JSON
telemetry + VW nanosecond timers, SURVEY §5). See docs/observability.md.

Import is side-effect-free and backend-free: safe under
``JAX_PLATFORMS=cpu`` before (or without) JAX initialization.
"""

from .metrics import (DEFAULT_LATENCY_BUCKETS, Counter, Gauge, Histogram,
                      MetricsRegistry, registry)
from .tracing import Span, StageTimer, Tracer, tracer

__all__ = ["registry", "tracer", "MetricsRegistry", "Counter", "Gauge",
           "Histogram", "Tracer", "Span", "StageTimer",
           "DEFAULT_LATENCY_BUCKETS"]
