"""Fleet telemetry plane: metric federation, stragglers, SLO health.

Every observability surface before this PR was strictly per-process —
on a pod each rank owns a private ``MetricsRegistry`` and there is no
single place to see the fleet. This module is that place:

- :class:`FleetAggregator` merges remote registry snapshots into one
  exposition. Sources push over the channels the mesh already has:
  pod ranks embed ``local_fleet_snapshot()`` in their
  ``MULTIHOST_RESULT`` payloads (``ingest_pod_results``), mesh workers
  ride the ``__fleet__`` heartbeat next to ``__lease__``/``__reply__``
  (``serving/distributed.py``), and ingest peers can be pulled via
  their ``/metrics`` text (:func:`parse_exposition`). Merged samples
  carry ``process``/``worker`` identity labels so two ranks' series
  never collide; per-source staleness is a gauge and dead ranks are
  evicted boundedly (reusing ``Gauge.remove_matching``).
- :class:`StragglerDetector` watches the per-rank
  ``profile_step_seconds{process=...}`` (or per-worker ``worker=...``)
  family and flags ranks sitting > k·MAD above the fleet median:
  ``fleet_straggler{...}`` gauge, a ``fleet.straggler`` span on the
  flip, and a replace signal the autoscaler consumes.
- :class:`BurnRateMonitor` turns the ``sched_tenant_*`` counters into
  multi-window error-budget burn rates (``slo_burn_rate{tenant,
  window}``), and :class:`FleetHealth` folds burn + stragglers into
  the single ``GET /healthz`` verdict (ok/degraded/critical) that the
  autoscaler and ``pick_least_loaded`` consult.

History (ISSUE 16): the ad-hoc private histories this module used to
keep — the burn monitor's tick list, the detector's last-flag-only
memory — are re-based on the shared
:class:`~mmlspark_tpu.obs.timeseries.TimeSeriesStore`: burn windows
are store-window deltas over ``slo_tenant_*`` series, and straggler
flap suppression debounces re-flags against ``mad_over_time`` of the
rank's recorded score trajectory. Components built against the
process-wide registry share the process-wide store (one queryable
substrate); a private registry (test isolation) gets a private store.

Clock discipline: everything here uses ``time.monotonic`` (graftcheck's
wallclock pass holds for ``obs/``); burn-rate windows are monotonic
spans, never wall timestamps. All shared state (source tables, flagged
sets, burn histories) mutates under a lock; registry handles do their
own locking.
"""

from __future__ import annotations

import json
import threading
import time

from .metrics import _escape, registry as _registry
from .timeseries import TimeSeriesStore, timeseries_store as _shared_store
from .tracing import tracer as _tracer

__all__ = [
    "BurnRateMonitor",
    "FleetAggregator",
    "FleetHealth",
    "StragglerDetector",
    "fleet_aggregator",
    "fleet_health",
    "ingest_pod_results",
    "local_fleet_snapshot",
    "own_worker_samples",
    "parse_exposition",
    "parse_sample",
    "render_sample",
    "straggler_workers",
]

#: registry families worth federating — bounds what a worker heartbeat
#: or a pod result ships (nobody needs a remote rank's http histograms
#: twice; the ingest already observed the request side).
FEDERATED_PREFIXES = (
    "profile_", "collective_", "mem_", "sched_", "serving_", "aot_",
    "kv_", "gen_", "deploy_", "goodput_",
)


def _store_for(store, registry, clock=time.monotonic):
    """The history substrate a component should use: an explicit one
    wins; the process-wide registry pairs with the process-wide store
    (ONE queryable history plane); a private registry or custom clock
    (test isolation) gets a private store on the same clock."""
    if store is not None:
        return store
    if registry is None and clock is time.monotonic:
        return _shared_store
    return TimeSeriesStore(
        registry if registry is not None else _registry, clock=clock)

# ---------------------------------------------------------------------------
# sample-name parsing — the inverse of metrics._render, so snapshots and
# expositions can be relabelled and re-merged without guessing.


def parse_sample(sample: str) -> tuple[str, dict]:
    """Split a rendered sample name into ``(family, labels)``.

    Understands exactly what ``metrics._render`` emits (sorted
    ``k="v"`` pairs, ``_escape``'d values). Anything that does not
    parse comes back opaque — ``(sample, {})`` — so foreign text can
    still be merged verbatim."""
    if "{" not in sample:
        return sample, {}
    name, _, rest = sample.partition("{")
    if not rest.endswith("}"):
        return sample, {}
    body = rest[:-1]
    labels: dict = {}
    i, n = 0, len(body)
    while i < n:
        j = body.find("=", i)
        if j < 0 or j + 1 >= n or body[j + 1] != '"':
            return sample, {}
        key = body[i:j]
        i = j + 2
        out: list = []
        closed = False
        while i < n:
            c = body[i]
            if c == "\\" and i + 1 < n:
                nxt = body[i + 1]
                out.append("\n" if nxt == "n" else nxt)
                i += 2
                continue
            if c == '"':
                closed = True
                break
            out.append(c)
            i += 1
        if not closed:
            return sample, {}
        labels[key] = "".join(out)
        i += 1
        if i < n:
            if body[i] != ",":
                return sample, {}
            i += 1
    return name, labels


def render_sample(name: str, labels: dict) -> str:
    """Re-render a parsed sample the way ``metrics._render`` would."""
    if not labels:
        return name
    inner = ",".join(
        f'{k}="{_escape(str(v))}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def parse_exposition(text: str) -> dict:
    """Prometheus text → ``{sample_name: float}`` (HELP/TYPE dropped).
    This is the pull half of federation: point it at a peer ingest's
    ``/metrics`` body and hand the result to ``ingest_snapshot``."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(None, 1)
        if len(parts) != 2:
            continue
        try:
            out[parts[0]] = float(parts[1])
        except ValueError:
            continue
    return out


def local_fleet_snapshot(registry=None, prefixes=FEDERATED_PREFIXES) -> dict:
    """This process's registry samples worth federating, by prefix.
    Pod ranks embed this in their MULTIHOST_RESULT payload; standalone
    workers push it over the ``__fleet__`` heartbeat."""
    reg = registry if registry is not None else _registry
    return {k: v for k, v in reg.snapshot().items() if k.startswith(prefixes)}


def own_worker_samples(worker_id: str, registry=None) -> dict:
    """The series a mesh worker THREAD owns: samples already labelled
    ``worker="<id>"``. Thread workers share the ingest's registry, so
    pushing a full snapshot would re-merge the ingest's own series back
    at itself with a bogus worker label — this filter keeps the
    heartbeat honest (process workers push the full snapshot instead,
    see ``distributed._worker_fleet_payload``)."""
    reg = registry if registry is not None else _registry
    tag = f'worker="{_escape(str(worker_id))}"'
    return {k: v for k, v in reg.snapshot().items() if tag in k}


# ---------------------------------------------------------------------------
# federation


class FleetAggregator:
    """Merges remote registry snapshots into one fleet exposition.

    Each source (a pod rank, a mesh worker, a peer ingest) is keyed by
    identity; its latest snapshot replaces the previous one wholesale
    (registries are cumulative, so last-write-wins is exact). Identity
    labels are stamped into every sample that does not already carry
    them, which is what makes the merged exposition collision-free.

    Staleness is CONSUMED here too (ISSUE 16 satellite), not just
    exported: each source's push cadence is learned as an EWMA of its
    inter-arrival gaps, and :meth:`check_staleness` flags sources whose
    age exceeds ``STALE_FACTOR`` × that cadence —
    ``fleet_sources_stale_total`` counts the flips and
    :class:`FleetHealth` folds the flags into a DEGRADED (never
    critical) verdict: a quiet rank is a telemetry gap, not proof the
    service is failing its SLO.
    """

    #: a source older than this multiple of its learned cadence is stale
    STALE_FACTOR = 3.0
    #: absolute grace floor: sub-second cadences (in-thread mesh
    #: heartbeats) would otherwise flag on routine GIL/scheduler jitter
    MIN_STALE_S = 1.0

    def __init__(self, registry=None, *, max_sources: int = 64,
                 clock=time.monotonic):
        self._reg = registry if registry is not None else _registry
        self._clock = clock
        self._max_sources = max_sources
        self._lock = threading.Lock()
        # source -> {"samples": dict, "at": t, "process": str|None,
        #            "worker": str|None, "channel": str}
        self._sources: dict = {}
        self._channels: set = set()
        self._g_sources = self._reg.gauge(
            "fleet_sources",
            "remote telemetry sources currently merged, by channel")
        self._g_staleness = self._reg.gauge(
            "fleet_source_staleness_seconds",
            "seconds since each fleet source's last snapshot")
        self._c_merges = self._reg.counter(
            "fleet_merges_total", "snapshot ingests, by channel")
        self._c_evicted = self._reg.counter(
            "fleet_sources_evicted_total",
            "fleet sources dropped, by reason (death|bound)")
        self._stale: set = set()   # sources currently flagged stale
        self._c_stale = self._reg.counter(
            "fleet_sources_stale_total",
            "fleet sources that went stale (age > 3x learned cadence), "
            "by source")

    # -- ingest -----------------------------------------------------------

    def ingest_snapshot(self, samples: dict, *, process=None, worker=None,
                        channel: str = "push") -> str:
        """Merge one source's snapshot. ``process``/``worker`` become
        the source identity AND get stamped into any sample missing
        them. Returns the source key."""
        proc = None if process is None else str(process)
        wid = None if worker is None else str(worker)
        source = (f"worker:{wid}" if wid is not None
                  else f"proc:{proc}" if proc is not None else "anon")
        relabelled: dict = {}
        for sample, value in samples.items():
            try:
                value = float(value)
            except (TypeError, ValueError):
                continue
            name, labels = parse_sample(sample)
            if name == sample and "{" in sample:
                # opaque foreign line — keep verbatim, collision risk
                # is the pusher's problem
                relabelled[sample] = value
                continue
            if proc is not None:
                labels.setdefault("process", proc)
            if wid is not None:
                labels.setdefault("worker", wid)
            relabelled[render_sample(name, labels)] = value
        now = self._clock()
        evicted = []
        with self._lock:
            prev = self._sources.get(source)
            cadence = None if prev is None else prev.get("cadence")
            if prev is not None:
                gap = max(0.0, now - prev["at"])
                # EWMA of inter-arrival gaps: adapts to a source that
                # legitimately slows its push rate without a restart
                cadence = gap if cadence is None \
                    else 0.5 * cadence + 0.5 * gap
            self._sources[source] = {
                "samples": relabelled, "at": now, "process": proc,
                "worker": wid, "channel": channel, "cadence": cadence,
            }
            self._stale.discard(source)   # fresh push clears the flag
            self._channels.add(channel)
            while len(self._sources) > self._max_sources:
                oldest = min(self._sources, key=lambda s:
                             self._sources[s]["at"])
                evicted.append((oldest, self._sources.pop(oldest)))
        self._c_merges.inc(channel=channel)
        for key, info in evicted:
            self._scrub(key, info)
            self._c_evicted.inc(reason="bound")
        return source

    def ingest_exposition(self, text: str, **kw) -> str:
        return self.ingest_snapshot(parse_exposition(text), **kw)

    # -- eviction ---------------------------------------------------------

    def evict(self, source: str, reason: str = "death") -> bool:
        """Drop a dead source and its registry residue. The mesh calls
        this from the same paths that detect worker death (registry
        eviction, lease monitor) so a dead rank's staleness gauge and
        straggler flag do not linger forever."""
        with self._lock:
            info = self._sources.pop(source, None)
            self._stale.discard(source)
        if info is None:
            return False
        self._scrub(source, info)
        self._c_evicted.inc(reason=reason)
        return True

    def evict_worker(self, worker_id) -> bool:
        return self.evict(f"worker:{worker_id}")

    def _scrub(self, source: str, info: dict) -> None:
        """remove_matching sweep for one departed source: its staleness
        series, any fleet_* series keyed by its identity, and — for
        thread-mode workers that record straight into the shared local
        registry — the federated families carrying its label, so a dead
        worker's step histogram stops feeding the straggler median."""
        self._g_staleness.remove_matching(source=source)
        ident = {}
        if info.get("worker") is not None:
            ident = {"worker": info["worker"]}
        elif info.get("process") is not None:
            ident = {"process": info["process"]}
        if ident:
            for prefix in ("fleet_",) + FEDERATED_PREFIXES:
                for m in self._reg.metrics(prefix):
                    m.remove_matching(**ident)

    # -- merge / exposition ----------------------------------------------

    def sources(self) -> dict:
        """Per-source summary (age, identity, size) for /debug/fleet."""
        now = self._clock()
        with self._lock:
            return {
                key: {
                    "age_s": round(now - info["at"], 3),
                    "process": info["process"],
                    "worker": info["worker"],
                    "channel": info["channel"],
                    "samples": len(info["samples"]),
                    "cadence_s": (None if info.get("cadence") is None
                                  else round(info["cadence"], 3)),
                    "stale": key in self._stale,
                }
                for key, info in self._sources.items()
            }

    def check_staleness(self, factor: float | None = None) -> dict:
        """Flag sources whose age exceeds ``factor`` × learned cadence
        (default :data:`STALE_FACTOR`), with :data:`MIN_STALE_S` as an
        absolute grace floor. A source with no learned cadence yet
        (single push) is never stale — one push proves nothing about
        its rhythm. Rising edges count into
        ``fleet_sources_stale_total``; a fresh push clears the flag.
        Returns ``{source: {"age_s", "cadence_s"}}`` of current
        flags."""
        factor = self.STALE_FACTOR if factor is None else float(factor)
        now = self._clock()
        stale: dict = {}
        newly: list = []
        with self._lock:
            for key, info in self._sources.items():
                cadence = info.get("cadence")
                if not cadence or cadence <= 0:
                    continue
                age = now - info["at"]
                if age > max(factor * cadence, self.MIN_STALE_S):
                    stale[key] = {"age_s": round(age, 3),
                                  "cadence_s": round(cadence, 3)}
                    if key not in self._stale:
                        self._stale.add(key)
                        newly.append(key)
                else:
                    self._stale.discard(key)
        for key in newly:
            self._c_stale.inc(source=key)
        return stale

    def merged_samples(self, *, include_local: bool = False,
                       update_gauges: bool = True) -> dict:
        """One flat ``{sample: value}`` across every live source (local
        registry last when ``include_local`` — its values win ties,
        which only arise when a process pushes to itself)."""
        now = self._clock()
        with self._lock:
            snap = [(k, dict(v, samples=v["samples"]))
                    for k, v in self._sources.items()]
            channels = set(self._channels)
        if update_gauges:
            counts = {c: 0 for c in channels}
            for key, info in snap:
                self._g_staleness.set(
                    max(0.0, now - info["at"]), source=key)
                counts[info["channel"]] = counts.get(info["channel"], 0) + 1
            for channel, n in counts.items():
                self._g_sources.set(n, channel=channel)
        merged: dict = {}
        for _, info in snap:
            merged.update(info["samples"])
        if include_local:
            merged.update(self._reg.snapshot())
        return merged

    def exposition(self) -> str:
        """The fleet-scoped scrape body: the local registry's full
        exposition (HELP/TYPE intact) followed by every remote sample
        the local registry does not already carry, as bare lines."""
        merged = self.merged_samples()
        head = self._reg.exposition()
        local = set(self._reg.snapshot())
        remote = {k: v for k, v in merged.items() if k not in local}
        if not remote:
            return head
        lines = [f"# fleet: {len(remote)} remote samples from "
                 f"{len(self.sources())} sources"]
        for name in sorted(remote):
            v = remote[name]
            rendered = "+Inf" if v == float("inf") else f"{v:.10g}"
            lines.append(f"{name} {rendered}")
        return head + "\n".join(lines) + "\n"


def ingest_pod_results(results, aggregator=None, *,
                       channel: str = "pod") -> int:
    """Merge ``launch_pod`` result dicts (built by
    ``parallel.multihost.fleet_result``) into the aggregator. Returns
    how many ranks carried a snapshot."""
    agg = aggregator if aggregator is not None else fleet_aggregator
    n = 0
    for r in results or []:
        if not isinstance(r, dict) or "snapshot" not in r:
            continue
        agg.ingest_snapshot(
            r["snapshot"], process=r.get("process"), channel=channel)
        n += 1
    return n


# ---------------------------------------------------------------------------
# straggler / skew detection


class StragglerDetector:
    """Flags ranks whose mean step time sits > k·MAD above the fleet
    median of ``profile_step_seconds``.

    Identity comes from the ``worker`` label when present (in-process
    mesh workers) else ``process`` (pod ranks); the two populations are
    detected independently so a slow pod rank is never compared against
    a serving thread. With exactly two members MAD is degenerate, so a
    ratio test applies (slower/faster > ``ratio_floor``). The MAD is
    floored at ``mad_floor_frac``·median so a perfectly uniform fleet
    with microscopic jitter does not page.

    Flap suppression (ISSUE 16): each rank's score (mean / fleet
    median) is recorded into the history store every tick. A rank that
    RE-flags within ``flap_window_s`` of its last unflag is debounced:
    the re-flag only lands immediately when its excess over the group
    threshold clears ``flap_k`` × ``mad_over_time`` of its own recorded
    score trajectory — i.e. the breach is large against the rank's own
    recent noise. A threshold-straddling jitterer is held back (counted
    in ``fleet_straggler_flaps_suppressed_total``) until it breaches on
    two CONSECUTIVE ticks, so a genuine relapse is delayed by at most
    one tick while alert flapping stops. First-ever flags are never
    delayed (no unflag history — nothing to debounce against)."""

    #: sample families whose per-rank sums/counts define "step time"
    FAMILIES = ("profile_step_seconds",)

    def __init__(self, aggregator=None, registry=None, *, k: float = 3.0,
                 ratio_floor: float = 2.0, mad_floor_frac: float = 0.05,
                 min_count: float = 1.0, store=None,
                 flap_window_s: float = 120.0, flap_k: float = 3.0):
        self._agg = aggregator if aggregator is not None else fleet_aggregator
        self._reg = registry if registry is not None else _registry
        self._store = _store_for(store, registry)
        self.k = float(k)
        self.ratio_floor = float(ratio_floor)
        self.mad_floor_frac = float(mad_floor_frac)
        self.min_count = float(min_count)
        self.flap_window_s = float(flap_window_s)
        self.flap_k = float(flap_k)
        self._lock = threading.Lock()
        self._flagged: set = set()   # {(label, value)}
        self._known: set = set()
        self._unflag_at: dict = {}   # {(label, value): t of last unflag}
        self._pending: dict = {}     # {(label, value): raw-flag streak}
        self._g = self._reg.gauge(
            "fleet_straggler",
            "1 while a rank's mean step time exceeds median + k*MAD "
            "(or the 2-rank ratio floor), by process/worker")
        self._g_score = self._reg.gauge(
            "fleet_straggler_score",
            "mean step seconds over fleet median, by process/worker")
        self._c_flaps = self._reg.counter(
            "fleet_straggler_flaps_suppressed_total",
            "re-flags debounced by the score-history noise gate")

    def rank_means(self, samples: dict) -> dict:
        """``{(label, value): mean_step_seconds}`` from the merged
        ``profile_step_seconds_sum/_count`` series."""
        sums: dict = {}
        counts: dict = {}
        for sample, v in samples.items():
            name, labels = parse_sample(sample)
            fam = kind = None
            for f in self.FAMILIES:
                if name == f + "_sum":
                    fam, kind = f, "sum"
                elif name == f + "_count":
                    fam, kind = f, "count"
            if fam is None:
                continue
            if "worker" in labels:
                ident = ("worker", labels["worker"])
            elif "process" in labels:
                ident = ("process", labels["process"])
            else:
                continue
            bucket = sums if kind == "sum" else counts
            bucket[ident] = bucket.get(ident, 0.0) + float(v)
        return {
            ident: sums[ident] / counts[ident]
            for ident in sums
            if counts.get(ident, 0.0) >= self.min_count
        }

    @staticmethod
    def _median(vals) -> float:
        vals = sorted(vals)
        n = len(vals)
        mid = n // 2
        return vals[mid] if n % 2 else (vals[mid - 1] + vals[mid]) / 2.0

    def _detect_group(self, means: dict) -> tuple:
        """(flagged idents, flag threshold in raw mean-seconds). The
        threshold is what flap suppression measures excess against;
        None when the group is too small to judge."""
        if len(means) < 2:
            return set(), None
        vals = [v for v in means.values()]
        med = self._median(vals)
        if len(means) == 2:
            (i1, v1), (i2, v2) = sorted(means.items(), key=lambda kv: kv[1])
            thr = v1 * self.ratio_floor if v1 > 0 else None
            if v1 > 0 and v2 / v1 > self.ratio_floor:
                return {i2}, thr
            return set(), thr
        mad = self._median([abs(v - med) for v in vals])
        thr = med + self.k * max(mad, self.mad_floor_frac * med, 1e-9)
        return {ident for ident, v in means.items() if v > thr}, thr

    def tick(self, samples=None) -> set:
        """Recompute flags from the merged fleet view. Returns the
        flagged identity set ``{(label, value), ...}``."""
        if samples is None:
            samples = self._agg.merged_samples(include_local=True)
        means = self.rank_means(samples)
        groups: dict = {}
        for ident, mean in means.items():
            groups.setdefault(ident[0], {})[ident] = mean
        raw: set = set()
        medians: dict = {}
        thresholds: dict = {}
        for label, group in groups.items():
            got, thr = self._detect_group(group)
            raw |= got
            medians[label] = self._median(list(group.values()))
            thresholds[label] = thr
        scores = {
            (label, value): (mean / medians[label]
                             if medians.get(label, 0.0) > 0 else 1.0)
            for (label, value), mean in means.items()}
        now = self._store.now()
        # record every rank's score trajectory — the flap-suppression
        # history AND an operator-queryable /debug/timeline series
        self._store.append_many(
            {render_sample("fleet_straggler_score", {lab: val}): s
             for (lab, val), s in scores.items()}, t=now)
        suppressed: list = []
        with self._lock:
            prev = set(self._flagged)
            flagged = set(raw)
            for ident in sorted(raw - prev):
                label, value = ident
                streak = self._pending.get(ident, 0) + 1
                self._pending[ident] = streak
                thr, med = thresholds.get(label), medians.get(label, 0.0)
                last_unflag = self._unflag_at.get(ident)
                if (last_unflag is None
                        or now - last_unflag > self.flap_window_s
                        or streak >= 2 or thr is None or med <= 0):
                    continue   # not a flap (or sustained): flag lands
                vol = self._store.mad_over_time(
                    render_sample("fleet_straggler_score",
                                  {label: value}),
                    self.flap_window_s)
                excess = (means[ident] - thr) / med
                if excess <= self.flap_k * vol:
                    flagged.discard(ident)
                    suppressed.append(ident)
            for ident in [i for i in self._pending if i not in raw]:
                self._pending.pop(ident)
            for ident in prev - flagged:
                self._unflag_at[ident] = now
            for ident in [i for i in self._unflag_at
                          if i not in means]:
                self._unflag_at.pop(ident)
            newly = flagged - prev
            gone = self._known - set(means)
            self._flagged = flagged
            self._known = set(means)
        for label, value in sorted(suppressed):
            self._c_flaps.inc(**{label: value})
        for (label, value), score in scores.items():
            self._g_score.set(score, **{label: value})
            self._g.set(1.0 if (label, value) in flagged else 0.0,
                        **{label: value})
        for label, value in gone:
            self._g.remove_matching(**{label: value})
            self._g_score.remove_matching(**{label: value})
        for label, value in newly:
            med = medians.get(label) or 0.0
            _tracer.emit_span(
                "fleet.straggler", parent=None,
                seconds=means[(label, value)],
                **{label: value, "fleet_median_s": med,
                   "mean_step_s": means[(label, value)]})
        return flagged

    def flagged(self) -> frozenset:
        """Current ``{(label, value)}`` flags (no recompute)."""
        with self._lock:
            return frozenset(self._flagged)

    def flagged_workers(self) -> frozenset:
        """Just the worker ids — what ``pick_least_loaded`` avoids."""
        with self._lock:
            return frozenset(v for (lab, v) in self._flagged
                             if lab == "worker")


# ---------------------------------------------------------------------------
# SLO burn-rate health

#: error-budget fraction (allowed shed/fail ratio) per SLO tier — gold
#: pages at a thousandth, best-effort tolerates an order of magnitude
#: more. sched.tenancy maps tenants onto these through error_budget_for.
TIER_ERROR_BUDGETS = {"gold": 0.001, "silver": 0.01, "best_effort": 0.1}

#: fallback budget for tenants nobody registered a tier for
DEFAULT_ERROR_BUDGET = 0.05

#: burn-rate windows in seconds; fast catches an active incident,
#: slow keeps a brief blip from paging
DEFAULT_WINDOWS = {"fast": 30.0, "slow": 180.0}


class BurnRateMonitor:
    """Multi-window error-budget burn over the ``sched_tenant_*``
    counters.

    Each ``tick`` appends per-tenant (admitted, shed) totals as
    ``slo_tenant_admitted`` / ``slo_tenant_shed`` series in the history
    store (ISSUE 16: the store IS the history — no private tick list),
    plus a ``slo_burn_ticks`` marker series recording when the monitor
    looked; the burn for a window is ``(shed / total) / budget`` over
    that window's store delta — burn 1.0 means the tenant is consuming
    budget exactly as fast as the SLO allows, ``page_burn`` (default
    10×) means an incident."""

    def __init__(self, registry=None, *, windows=None, budget_for=None,
                 service: str = "", clock=time.monotonic, store=None):
        self._reg = registry if registry is not None else _registry
        self._clock = clock
        self._store = _store_for(store, registry, clock)
        self.windows = dict(windows) if windows else dict(DEFAULT_WINDOWS)
        self._budget_for = budget_for
        self._service = service
        self._lock = threading.Lock()
        self._latest: dict = {}    # {tenant: {window: burn}}
        self._g_burn = self._reg.gauge(
            "slo_burn_rate",
            "error-budget burn multiple, by tenant and window "
            "(1.0 = burning exactly at the SLO rate)")

    def _series(self, family: str, tenant: str | None = None) -> str:
        labels = {"service": self._service} if self._service else {}
        if tenant is not None:
            labels["tenant"] = tenant
        return render_sample(family, labels)

    def set_budget_for(self, fn) -> None:
        self._budget_for = fn

    def budget(self, tenant: str) -> float:
        if self._budget_for is not None:
            try:
                b = float(self._budget_for(tenant))
                if b > 0:
                    return b
            except Exception:
                pass
        return DEFAULT_ERROR_BUDGET

    def _totals(self, samples: dict) -> dict:
        """{tenant: (admitted, bad)} from the tenant counters,
        optionally filtered to one service. The bad side folds sheds
        (``sched_tenant_shed_total``) together with server-side 5xx
        (``serving_tenant_requests_total{code=5xx}``) — a canary build
        answering 500s burns its error budget exactly like one being
        shed, which is what lets the rollout controller (deploy plane)
        act on burn alone. Admissions stay the denominator: every
        answered request was admitted, so the two families never
        double-count the good side."""
        out: dict = {}
        for sample, v in samples.items():
            name, labels = parse_sample(sample)
            if name not in ("sched_tenant_admitted_total",
                            "sched_tenant_shed_total",
                            "serving_tenant_requests_total"):
                continue
            if self._service and labels.get("service") != self._service:
                continue
            tenant = labels.get("tenant")
            if tenant is None:
                continue
            if name == "serving_tenant_requests_total":
                try:
                    if int(labels.get("code", "0")) < 500:
                        continue
                except ValueError:
                    continue
            adm, bad = out.get(tenant, (0.0, 0.0))
            if name == "sched_tenant_admitted_total":
                adm += float(v)
            else:
                bad += float(v)
            out[tenant] = (adm, bad)
        return out

    def tick(self, samples=None) -> dict:
        """Sample the counters and recompute ``slo_burn_rate`` for
        every tenant × window. Returns ``{tenant: {window: burn}}``."""
        if samples is None:
            samples = self._reg.snapshot()
        totals = self._totals(samples)
        now = self._clock()
        horizon = max(self.windows.values()) * 1.5 + 1.0
        # one batch append at one timestamp: the tick marker plus every
        # tenant's cumulative totals. Retention = the burn horizon, so
        # the store prunes exactly what the old private list did.
        batch = {self._series("slo_burn_ticks"): now}
        for tenant, (adm, shed) in totals.items():
            batch[self._series("slo_tenant_admitted", tenant)] = adm
            batch[self._series("slo_tenant_shed", tenant)] = shed
        self._store.append_many(batch, t=now, retention_s=horizon)
        burns: dict = {}
        for tenant, (adm_now, shed_now) in totals.items():
            budget = self.budget(tenant)
            adm_series = self._series("slo_tenant_admitted", tenant)
            shed_series = self._series("slo_tenant_shed", tenant)
            per_window: dict = {}
            for wname, wsec in self.windows.items():
                # base = the tenant's totals at the oldest tick inside
                # the window; a tenant that first appeared later than
                # that tick has no point there — its whole total is
                # in-window (base 0), same as the old history list
                ticks = self._store.points(self._series("slo_burn_ticks"),
                                           wsec, now=now)
                t0 = ticks[0][0] if ticks else now
                base_adm = base_shed = 0.0
                adm_pts = self._store.points(adm_series, wsec, now=now)
                if adm_pts and adm_pts[0][0] <= t0 + 1e-9:
                    base_adm = adm_pts[0][1]
                shed_pts = self._store.points(shed_series, wsec, now=now)
                if shed_pts and shed_pts[0][0] <= t0 + 1e-9:
                    base_shed = shed_pts[0][1]
                d_adm = max(0.0, adm_now - base_adm)
                d_shed = max(0.0, shed_now - base_shed)
                total = d_adm + d_shed
                rate = (d_shed / total) if total > 0 else 0.0
                burn = rate / budget
                per_window[wname] = burn
                self._g_burn.set(burn, tenant=tenant, window=wname)
            burns[tenant] = per_window
        with self._lock:
            self._latest = burns
        return burns

    def latest(self) -> dict:
        with self._lock:
            return {t: dict(w) for t, w in self._latest.items()}


class FleetHealth:
    """Folds burn rates + stragglers + source staleness into the one
    verdict ``GET /healthz`` serves: ``ok`` / ``degraded`` /
    ``critical``. Degraded still answers 200 (load balancers must not
    drain a merely-slow fleet); only critical returns 503."""

    #: verdict → (gauge value, http status)
    VERDICTS = {"ok": (0, 200), "degraded": (1, 200), "critical": (2, 503)}

    def __init__(self, aggregator=None, registry=None, *,
                 page_burn: float = 10.0, degraded_burn: float = 1.0,
                 windows=None, service: str = "", store=None):
        self._reg = registry if registry is not None else _registry
        self._store = _store_for(store, registry)
        self.aggregator = (aggregator if aggregator is not None
                           else fleet_aggregator)
        self.stragglers = StragglerDetector(self.aggregator,
                                            registry=self._reg,
                                            store=self._store)
        self.burn = BurnRateMonitor(registry=self._reg, windows=windows,
                                    service=service, store=self._store)
        self.page_burn = float(page_burn)
        self.degraded_burn = float(degraded_burn)
        self._lock = threading.Lock()
        self._verdict = "ok"
        self._reasons: list = []
        self._sentinel = None
        self._deploy_reasons = None
        self._g_health = self._reg.gauge(
            "fleet_health",
            "healthz verdict: 0 ok, 1 degraded, 2 critical")

    def attach_tenancy(self, tenancy) -> None:
        """Point burn budgets at a TenancyPolicy's tier table (its
        ``error_budget_for``); absent tiers keep the default budget."""
        fn = getattr(tenancy, "error_budget_for", None)
        if callable(fn):
            self.burn.set_budget_for(fn)

    def attach_sentinel(self, sentinel) -> None:
        """Point the verdict at a perf-regression sentinel
        (``obs.regression.RegressionSentinel``): series with a
        SUSTAINED live regression mark the fleet degraded — slower than
        it was is sick, but never load-balancer-drain critical. The
        sentinel module attaches the process-wide pair on import."""
        self._sentinel = sentinel

    def attach_deploy(self, reasons_fn) -> None:
        """Point the verdict at the deploy plane
        (``serving.deploy.RolloutController.deploy_reasons``): while a
        rollback flap is in progress the fleet reads degraded — traffic
        is snapping back to the prior version, so "slow but serving",
        never load-balancer-drain critical."""
        self._deploy_reasons = reasons_fn

    def tick(self) -> str:
        """One health evaluation: refresh memory gauges, detect
        stragglers over the merged fleet view, recompute burn rates,
        and derive the verdict."""
        from .memory import memory_profiler
        memory_profiler.update()
        merged = self.aggregator.merged_samples(include_local=True)
        flagged = self.stragglers.tick(merged)
        burns = self.burn.tick(merged)
        verdict = "ok"
        reasons = []
        if flagged:
            verdict = "degraded"
            reasons.append("stragglers=%d" % len(flagged))
        for tenant, per_window in burns.items():
            fast = per_window.get("fast", 0.0)
            slow = per_window.get("slow", 0.0)
            if fast >= self.page_burn and slow >= self.page_burn / 2.0:
                verdict = "critical"
                reasons.append(f"{tenant} paging (fast burn {fast:.1f})")
            elif fast >= self.degraded_burn and verdict != "critical":
                verdict = "degraded"
                reasons.append(f"{tenant} burning (fast burn {fast:.1f})")
        stale = self.aggregator.check_staleness()
        if stale:
            # a source that stopped reporting is a blind spot, not an
            # outage: never escalate past degraded on staleness alone
            if verdict == "ok":
                verdict = "degraded"
            reasons.append("stale_sources=%d" % len(stale))
        sentinel = self._sentinel
        if sentinel is not None:
            sustained = sentinel.sustained()
            if sustained:
                if verdict == "ok":
                    verdict = "degraded"
                reasons.append(
                    "regression=" + ",".join(sorted(sustained)))
        deploy_fn = self._deploy_reasons
        if deploy_fn is not None:
            try:
                flapping = list(deploy_fn())
            except Exception:
                flapping = []
            if flapping:
                if verdict == "ok":
                    verdict = "degraded"
                reasons.extend(flapping)
        with self._lock:
            self._verdict = verdict
            self._reasons = reasons
        self._g_health.set(self.VERDICTS[verdict][0])
        return verdict

    def verdict(self) -> str:
        with self._lock:
            return self._verdict

    def healthz_payload(self) -> tuple:
        """(http_status, json_bytes) for the /healthz route — runs a
        fresh tick so the verdict is never staler than the request."""
        verdict = self.tick()
        body = {
            "status": verdict,
            "reasons": list(getattr(self, "_reasons", [])),
            "stragglers": sorted(
                f"{lab}:{val}" for lab, val in self.stragglers.flagged()),
            "burn": self.burn.latest(),
            "sources": len(self.aggregator.sources()),
            "stale_sources": sorted(
                k for k, v in self.aggregator.sources().items()
                if v.get("stale")),
        }
        return self.VERDICTS[verdict][1], json.dumps(body, indent=1).encode()

    def debug_payload(self) -> bytes:
        """The /debug/fleet body: verdict + per-source detail."""
        self.tick()
        body = {
            "status": self.verdict(),
            "sources": self.aggregator.sources(),
            "stragglers": sorted(
                f"{lab}:{val}" for lab, val in self.stragglers.flagged()),
            "burn": self.burn.latest(),
        }
        return json.dumps(body, indent=1).encode()


#: THE process-wide federation point — the serving fronts, the mesh
#: heartbeat ingest, and the pod launcher all merge into this one.
fleet_aggregator = FleetAggregator()

#: THE process-wide health view over it.
fleet_health = FleetHealth(fleet_aggregator)


def straggler_workers() -> frozenset:
    """Worker ids currently flagged as stragglers — consumed by
    ``serving.distributed.pick_least_loaded`` (cheap: no recompute)."""
    return fleet_health.stragglers.flagged_workers()
