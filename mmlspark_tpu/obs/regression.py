"""Perf-regression sentinel: the history plane grown teeth.

Two halves, one contract — "slower than it was" is detected, not
discovered in a postmortem:

- **Offline trajectory gate** (``python -m mmlspark_tpu.obs.regression
  compare OLD.json NEW.json`` / ``... gate [FILES...]``): diffs two
  banked bench JSONs metric by metric, with the good/bad direction
  inferred from the metric name (images_per_sec up is good; _ms up is
  bad) and a noise-aware tolerance — MAD over the full banked
  ``BENCH_r0*`` trajectory when it is deep enough, a relative floor
  when it is not, plus an absolute floor for sub-millisecond latency
  jitter. Exit status is the verdict, so CI wires it straight in as
  the RegressionGate job.
- **Live CUSUM sentinel** (:class:`RegressionSentinel`): watches the
  time-series store (``obs.timeseries``) for step changes in
  ``profile_mfu``, the windowed serving p99, and the cost model's
  prediction error. CUSUM accumulates standardized drift beyond a
  slack ``k`` and alarms at threshold ``h`` — a pure function of the
  value sequence, so a same-seed healthy replay alarms exactly never.
  Alarms export ``obs_regression_active{series}`` /
  ``obs_regression_events_total``, fire one ``obs.regression`` span
  per rising edge, and — sustained — turn ``GET /healthz`` DEGRADED
  via :meth:`~mmlspark_tpu.obs.fleet.FleetHealth.attach_sentinel`
  (never critical: a slow fleet must not be drained).

Import is stdlib-only; the module attaches the process-wide sentinel
to ``fleet_health`` on import so serving processes get the live watch
for free.
"""

from __future__ import annotations

import glob as _glob
import json
import re
import sys
import threading

from .fleet import fleet_health
from .metrics import registry as _registry
from .timeseries import TimeSeriesStore, timeseries_store
from .tracing import tracer as _tracer

__all__ = [
    "CusumDetector",
    "RegressionSentinel",
    "SeriesWatch",
    "compare_benches",
    "format_table",
    "load_bench",
    "sentinel",
]


# ---------------------------------------------------------------------------
# offline: bench trajectory loader


#: bench-wrapper / bookkeeping keys that are not metrics
_NON_METRIC_KEYS = frozenset({
    "n", "rc", "value", "vs_baseline", "stale", "timeout",
})

_NUM_RE = re.compile(r'"([A-Za-z_][A-Za-z0-9_]*)":\s*(-?\d[\d.eE+-]*)')


def _harvest(obj, out: dict) -> None:
    """Pull numeric leaves out of a (possibly nested) parsed dict."""
    if not isinstance(obj, dict):
        return
    metric = obj.get("metric")
    for k, v in obj.items():
        if isinstance(v, dict):
            _harvest(v, out)
        elif isinstance(v, bool):
            continue
        elif isinstance(v, (int, float)):
            if k == "value" and isinstance(metric, str) and metric:
                out[metric] = float(v)
            elif k not in _NON_METRIC_KEYS:
                out[_norm(k)] = float(v)


def _norm(key: str) -> str:
    """One metric, one name across runs: the stale-reuse banker
    prefixes carried-over metrics with ``last_measured_``."""
    return key[14:] if key.startswith("last_measured_") else key


def _harvest_text(text: str, out: dict) -> None:
    """Recover metrics from a bench run's captured tail: try each line
    as a JSON object first (the bench emits one metrics line), then
    fall back to a regex sweep — the tail is the LAST 2000 chars of
    output, so the metrics line is routinely beheaded mid-JSON and
    only the pair-by-pair sweep still reads it."""
    for line in text.splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            _harvest(json.loads(line), out)
            return
        except ValueError:
            pass
    for key, num in _NUM_RE.findall(text):
        if key in _NON_METRIC_KEYS:
            continue
        try:
            out.setdefault(_norm(key), float(num))
        except ValueError:
            continue


def load_bench(path: str) -> dict:
    """One banked bench JSON → flat ``{metric: value}``.

    Accepts the banker's wrapper (``{"n","cmd","rc","tail","parsed"}``
    — ``parsed`` may be null with the real metrics line truncated in
    the tail) or a plain flat dict of numbers (synthetic fixtures)."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    out: dict = {}
    if isinstance(doc, dict) and "tail" in doc:
        _harvest_text(str(doc.get("tail") or ""), out)
        if isinstance(doc.get("parsed"), dict):
            _harvest(doc["parsed"], out)
    elif isinstance(doc, dict):
        _harvest(doc, out)
    return out


# ---------------------------------------------------------------------------
# offline: direction + tolerance + compare


#: name tokens whose metric is good-when-HIGHER
_HIGHER_TOKENS = ("per_sec", "_rps", "throughput", "mfu", "qps",
                  "hit_rate", "speedup", "concurrency", "samples_sec",
                  "rows_per")
#: name tokens whose metric is good-when-LOWER
_LOWER_TOKENS = ("_ms", "_seconds", "latency", "_rtt", "overhead",
                 "error", "stall", "_bytes", "evicted", "failures")


def direction(metric: str) -> str | None:
    """'higher' / 'lower' = which way is GOOD; None = unknowable from
    the name (reported as info, never gated)."""
    m = metric.lower()
    hi = any(t in m for t in _HIGHER_TOKENS)
    lo = any(t in m for t in _LOWER_TOKENS)
    if hi == lo:
        return None
    return "higher" if hi else "lower"


def _median(vals):
    vals = sorted(vals)
    n = len(vals)
    mid = n // 2
    return vals[mid] if n % 2 else (vals[mid - 1] + vals[mid]) / 2.0


def _mad(vals):
    med = _median(vals)
    return _median([abs(v - med) for v in vals])


def compare_benches(old: dict, new: dict, history=None, *,
                    rel_floor: float = 0.10, mad_k: float = 3.0,
                    abs_floor_ms: float = 0.25) -> list:
    """Diff two flat bench dicts into verdict rows.

    Tolerance per metric = ``max(rel_floor, mad_k·MAD/|median|)`` over
    that metric's banked ``history`` values when ≥3 exist (the
    trajectory prices its own noise), else the bare ``rel_floor`` — a
    2-sample history proves nothing about variance. ``_ms`` metrics
    additionally get ``abs_floor_ms``: sub-quarter-millisecond swings
    on a loopback serving bench are host jitter, not regressions.
    Zero/negative values mark a FAILED measurement on that side and
    the metric is skipped, never gated."""
    history = history or {}
    rows = []
    for metric in sorted(set(old) & set(new)):
        a, b = float(old[metric]), float(new[metric])
        row = {"metric": metric, "old": a, "new": b,
               "direction": direction(metric)}
        if a <= 0 or b <= 0:
            row.update(delta_pct=0.0, tol_pct=0.0, verdict="skipped")
            rows.append(row)
            continue
        delta = (b - a) / a
        tol = rel_floor
        hist = [v for v in history.get(metric, []) if v > 0]
        if len(hist) >= 3:
            med = _median(hist)
            if med > 0:
                tol = max(rel_floor, mad_k * _mad(hist) / med)
        row.update(delta_pct=delta * 100.0, tol_pct=tol * 100.0)
        d = row["direction"]
        if d is None:
            row["verdict"] = "info"
        elif metric.endswith("_ms") and abs(b - a) <= abs_floor_ms:
            row["verdict"] = "ok"
        elif (d == "higher" and delta < -tol) or \
                (d == "lower" and delta > tol):
            row["verdict"] = "regression"
        elif (d == "higher" and delta > tol) or \
                (d == "lower" and delta < -tol):
            row["verdict"] = "improved"
        else:
            row["verdict"] = "ok"
        rows.append(row)
    return rows


def history_from_files(paths) -> dict:
    """``{metric: [value, ...]}`` across a trajectory of bench files
    (file order = time order; failed measurements dropped)."""
    hist: dict = {}
    for p in paths:
        for metric, v in load_bench(p).items():
            hist.setdefault(metric, []).append(v)
    return hist


def format_table(rows) -> str:
    """The human diff table ``compare`` prints and ``bench.py
    --compare`` appends a verdict from."""
    if not rows:
        return "(no common metrics)"
    head = f"{'metric':<34} {'old':>12} {'new':>12} " \
           f"{'delta':>8} {'tol':>6}  verdict"
    lines = [head, "-" * len(head)]
    for r in rows:
        lines.append(
            f"{r['metric']:<34} {r['old']:>12.4g} {r['new']:>12.4g} "
            f"{r['delta_pct']:>+7.1f}% {r['tol_pct']:>5.1f}%  "
            f"{r['verdict']}")
    return "\n".join(lines)


def gate_verdict(rows) -> str:
    bad = [r["metric"] for r in rows if r["verdict"] == "regression"]
    if bad:
        return "REGRESSION: " + ", ".join(bad)
    n_ok = sum(r["verdict"] in ("ok", "improved") for r in rows)
    return f"PASS ({n_ok} metrics within tolerance)"


# ---------------------------------------------------------------------------
# live: CUSUM step-change detection


class CusumDetector:
    """One-sided CUSUM over a standardized series.

    The first ``warmup`` values establish the reference (median) and
    scale (1.4826·MAD, floored at 5% of |median| so a perfectly steady
    warmup cannot make the detector infinitely touchy). Each later
    value contributes its standardized drift in the BAD direction
    beyond the slack ``k``; the accumulated statistic alarms at ``h``.
    Everything is a pure fold over the value sequence — replaying the
    same values gives bit-identical alarm history."""

    def __init__(self, *, warmup: int = 8, k: float = 0.5,
                 h: float = 5.0, direction: str = "lower_bad"):
        if direction not in ("lower_bad", "higher_bad"):
            raise ValueError(f"bad direction: {direction!r}")
        self.warmup = max(int(warmup), 2)
        self.k = float(k)
        self.h = float(h)
        self.direction = direction
        self._warmup_vals: list = []
        self.ref: float | None = None
        self.scale: float | None = None
        self.stat = 0.0
        self.alarm = False

    def update(self, x: float) -> bool:
        """Fold one value; returns the current alarm state."""
        x = float(x)
        if self.ref is None:
            self._warmup_vals.append(x)
            if len(self._warmup_vals) >= self.warmup:
                self.ref = _median(self._warmup_vals)
                self.scale = max(1.4826 * _mad(self._warmup_vals),
                                 0.05 * abs(self.ref), 1e-9)
                self._warmup_vals = []
            return False
        z = (x - self.ref) / self.scale
        drift = -z if self.direction == "lower_bad" else z
        self.stat = max(0.0, self.stat + drift - self.k)
        self.alarm = self.stat >= self.h
        return self.alarm


class SeriesWatch:
    """One sentinel watch: a name, a store → value pull (None = no
    signal this tick, the detector is not fed), and the bad
    direction."""

    def __init__(self, name: str, pull, *, direction: str = "lower_bad",
                 warmup: int = 8, k: float = 0.5, h: float = 5.0):
        self.name = name
        self.pull = pull
        self.detector = CusumDetector(warmup=warmup, k=k, h=h,
                                      direction=direction)


def _pull_mfu(store: TimeSeriesStore):
    vals = [p[1] for name in store.series_names("profile_mfu")
            if (name == "profile_mfu" or name.startswith("profile_mfu{"))
            for p in [store.latest(name)] if p is not None]
    return sum(vals) / len(vals) if vals else None


def _pull_serving_p99(window: float):
    def pull(store: TimeSeriesStore):
        v = store.quantile_over_time("serving_request_seconds", 0.99,
                                     window)
        return v if v > 0 else None
    return pull


def _pull_costmodel_error(window: float):
    def pull(store: TimeSeriesStore):
        num = sum(store.increase(n, window) for n in
                  store.series_names("sched_costmodel_error_ms_sum"))
        den = sum(store.increase(n, window) for n in
                  store.series_names("sched_costmodel_error_ms_count"))
        return num / den if den > 0 else None
    return pull


def default_watches(window: float = 120.0) -> list:
    """The stock watch set: training MFU (lower = bad), the WINDOWED
    serving p99 rebuilt from recorded bucket deltas (higher = bad),
    and the cost model's mean absolute error (higher = bad — the
    scheduler is being priced wrong)."""
    return [
        SeriesWatch("profile_mfu", _pull_mfu, direction="lower_bad"),
        SeriesWatch("serving_p99_seconds", _pull_serving_p99(window),
                    direction="higher_bad"),
        SeriesWatch("sched_costmodel_error_ms",
                    _pull_costmodel_error(window),
                    direction="higher_bad"),
    ]


class RegressionSentinel:
    """Ticks the watch set against the store and exports the alarms.

    Per watch: ``obs_regression_active{series}`` (0/1 gauge), one
    ``obs_regression_events_total{series}`` count plus one
    ``obs.regression`` span per RISING edge, and — once an alarm has
    held for ``sustain_ticks`` consecutive ticks — membership in
    :meth:`sustained`, which is what FleetHealth folds into the
    degraded verdict (one noisy tick must not flip healthz)."""

    def __init__(self, store: TimeSeriesStore | None = None,
                 registry=None, *, watches=None, sustain_ticks: int = 3,
                 window: float = 120.0):
        self._reg = registry if registry is not None else _registry
        self.store = store if store is not None else timeseries_store
        self.watches = (list(watches) if watches is not None
                        else default_watches(window))
        self.sustain_ticks = max(int(sustain_ticks), 1)
        self._lock = threading.Lock()
        self._streak: dict = {}
        self._active: set = set()
        self._g_active = self._reg.gauge(
            "obs_regression_active",
            "live CUSUM regression alarm, by series (0/1)")
        self._c_events = self._reg.counter(
            "obs_regression_events_total",
            "regression alarm rising edges, by series")

    def tick(self) -> frozenset:
        """Evaluate every watch once; returns the active alarm set."""
        edges = []
        readings = [(w, w.pull(self.store)) for w in self.watches]
        with self._lock:
            for w, value in readings:
                if value is None:
                    continue
                alarm = w.detector.update(value)
                was = w.name in self._active
                if alarm:
                    self._active.add(w.name)
                    self._streak[w.name] = self._streak.get(w.name, 0) + 1
                    if not was:
                        edges.append((w.name, value, w.detector))
                else:
                    self._active.discard(w.name)
                    self._streak[w.name] = 0
            active = frozenset(self._active)
        for w, value in readings:
            if value is not None:
                self._g_active.set(1.0 if w.name in active else 0.0,
                                   series=w.name)
        for name, value, det in edges:
            self._c_events.inc(series=name)
            _tracer.emit_span(
                "obs.regression", parent=None, seconds=0.0, series=name,
                value=value, reference=det.ref, cusum=round(det.stat, 3))
        return active

    def active(self) -> frozenset:
        with self._lock:
            return frozenset(self._active)

    def sustained(self) -> frozenset:
        """Watches alarmed for ≥ ``sustain_ticks`` consecutive ticks —
        the healthz-degrading subset."""
        with self._lock:
            return frozenset(
                name for name in self._active
                if self._streak.get(name, 0) >= self.sustain_ticks)


#: THE process-wide sentinel over the shared store, attached to the
#: shared health view at import: any process that imports obs gets the
#: live watch wired into /healthz for free.
sentinel = RegressionSentinel(timeseries_store)
fleet_health.attach_sentinel(sentinel)


# ---------------------------------------------------------------------------
# CLI


def _default_trajectory() -> list:
    return sorted(_glob.glob("BENCH_r0*.json") or
                  _glob.glob("BENCH_r*.json"))


def main(argv=None) -> int:
    """``compare OLD NEW [--history F...]`` diffs two runs; ``gate
    [FILES...]`` diffs the newest banked run against its predecessor
    with the whole trajectory pricing the noise. Exit 0 = pass, 1 =
    regression, 2 = not enough data."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] not in ("compare", "gate"):
        print("usage: python -m mmlspark_tpu.obs.regression "
              "compare OLD.json NEW.json [--history FILE...]\n"
              "       python -m mmlspark_tpu.obs.regression "
              "gate [FILES...]", file=sys.stderr)
        return 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "compare":
        hist_files: list = []
        if "--history" in rest:
            i = rest.index("--history")
            hist_files = rest[i + 1:]
            rest = rest[:i]
        if len(rest) != 2:
            print("compare needs exactly OLD.json NEW.json",
                  file=sys.stderr)
            return 2
        old_p, new_p = rest
        files = hist_files
    else:
        files = rest or _default_trajectory()
        if len(files) < 2:
            print(f"gate: need >= 2 trajectory files, got {len(files)}",
                  file=sys.stderr)
            return 2
        old_p, new_p = files[-2], files[-1]
    rows = compare_benches(load_bench(old_p), load_bench(new_p),
                           history_from_files(files))
    print(f"{old_p} -> {new_p}")
    print(format_table(rows))
    verdict = gate_verdict(rows)
    print(verdict)
    return 1 if verdict.startswith("REGRESSION") else 0


if __name__ == "__main__":
    sys.exit(main())
