"""Device-memory telemetry: always-on HBM gauges + watermark deltas.

PR 12 made the data plane pod-scale, but nothing in the stack consults
``device.memory_stats()`` — an OOM on a v5e rank is invisible until XLA
aborts. This module turns the runtime's allocator counters into
registry series every scrape sees:

- ``mem_hbm_bytes_in_use{device=...}`` / ``mem_hbm_peak_bytes`` /
  ``mem_hbm_limit_bytes`` — per local device, ``process``-labelled on a
  pod (same labelling contract as ``profile_step_seconds``), refreshed
  by :meth:`MemoryProfiler.update` (the serving fronts refresh on every
  ``/metrics`` scrape via ``obs.fleet``).
- ``mem_segment_delta_bytes{stage=...}`` — the live-buffer delta one
  profiled stage left behind (StepProfiler samples the watermark around
  every ``step``), so a FusedSegment that leaks device buffers shows up
  as a growing delta, per segment.
- ``mem_event_watermark_bytes{event=...}`` — the watermark at named
  lifecycle events (AOT warm boot, autoscaler scale-up), so "what did
  warm-loading the store cost in HBM" is one scrape.

Degradation contract (the CI no-JAX smoke asserts it): with no jax in
the process, or a backend whose devices expose no ``memory_stats``
(CPU), every function returns ``[]``/``None`` and the gauges are simply
ABSENT — never an exception, never a zero sample that looks like a
measurement. The guard never imports jax and never initializes a
backend (same discipline as :func:`~.profile.device_platform`).
"""

from __future__ import annotations

import sys
import threading

from .metrics import registry as _registry
from .profile import process_label

__all__ = ["MemoryProfiler", "device_memory_stats", "memory_profiler"]

# allocator-stat key -> (our metric suffix). Runtimes differ slightly in
# what they report; only keys that exist become samples.
_STAT_KEYS = (
    ("bytes_in_use", "mem_hbm_bytes_in_use"),
    ("peak_bytes_in_use", "mem_hbm_peak_bytes"),
    ("bytes_limit", "mem_hbm_limit_bytes"),
)


def _live_devices() -> list:
    """``jax.local_devices()`` ONLY when a backend is already live.
    Never imports jax, never initializes a backend — the same guard as
    ``profile.device_platform`` (a host-only serving process must not
    pay backend bring-up for a metrics scrape)."""
    mod = sys.modules.get("jax")
    if mod is None:
        return []
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None or not getattr(xb, "_backends", None):
        return []
    try:
        return list(mod.local_devices())
    except Exception:
        return []


def device_memory_stats() -> list[dict]:
    """Per-device allocator stats: ``[{"device": "0", "bytes_in_use":
    ..., ...}]`` with only the keys the runtime reports. ``[]`` when no
    live backend, or when no device exposes ``memory_stats`` (CPU) —
    the documented fallback the fleet exposition carries on hosts
    without HBM."""
    out: list[dict] = []
    for d in _live_devices():
        stats = None
        try:
            fn = getattr(d, "memory_stats", None)
            stats = fn() if callable(fn) else None
        except Exception:
            stats = None
        if not stats:
            continue
        rec = {"device": str(getattr(d, "id", len(out)))}
        for key, _ in _STAT_KEYS:
            v = stats.get(key)
            if v is not None:
                rec[key] = int(v)
        if len(rec) > 1:
            out.append(rec)
    return out


class MemoryProfiler:
    """Registry-backed view over :func:`device_memory_stats`.

    Stateless apart from its gauge handles; every method tolerates a
    backend-free process by doing nothing (gauges stay absent).
    """

    def __init__(self, registry=None):
        reg = registry if registry is not None else _registry
        self._lock = threading.Lock()
        self._gauges = {
            suffix: reg.gauge(suffix, help_)
            for suffix, help_ in (
                ("mem_hbm_bytes_in_use",
                 "allocator bytes currently live, per local device"),
                ("mem_hbm_peak_bytes",
                 "allocator peak bytes since process start, per device"),
                ("mem_hbm_limit_bytes",
                 "allocator capacity, per local device"),
            )}
        self._g_segment = reg.gauge(
            "mem_segment_delta_bytes",
            "live-buffer delta across one profiled stage execution, "
            "by stage")
        self._g_event = reg.gauge(
            "mem_event_watermark_bytes",
            "total live bytes at a named lifecycle event "
            "(aot_warm, scale_up, ...)")
        #: devices whose gauges were ever set — so a device that stops
        #: reporting (runtime drift) does not leave a stale sample
        self._seen_devices: set[str] = set()

    def _plab(self) -> dict:
        pl = process_label()
        return {"process": pl} if pl is not None else {}

    def update(self) -> list[dict]:
        """Refresh the ``mem_hbm_*`` gauges from the live allocator;
        returns the raw stats (``[]`` on CPU/no-JAX — gauges absent)."""
        stats = device_memory_stats()
        plab = self._plab()
        reported: set[str] = set()
        for rec in stats:
            dev = rec["device"]
            reported.add(dev)
            for key, suffix in _STAT_KEYS:
                if key in rec:
                    self._gauges[suffix].set(rec[key], device=dev, **plab)
        with self._lock:
            gone = self._seen_devices - reported
            self._seen_devices |= reported
        for dev in gone:
            for g in self._gauges.values():
                g.remove_matching(device=dev)
        return stats

    def watermark(self) -> int | None:
        """Total live bytes across local devices, or None when the
        backend reports no memory stats (the delta hooks skip instead
        of recording a fake zero)."""
        vals = [r["bytes_in_use"] for r in device_memory_stats()
                if "bytes_in_use" in r]
        return sum(vals) if vals else None

    def segment_delta(self, stage: str, before: int | None,
                      after: int | None) -> int | None:
        """Record the live-buffer delta one profiled stage left behind
        (StepProfiler samples ``watermark()`` around the step and lands
        both ends here). None in, nothing recorded."""
        if before is None or after is None:
            return None
        delta = int(after) - int(before)
        self._g_segment.set(delta, stage=stage, **self._plab())
        return delta

    def note_event(self, event: str) -> int | None:
        """Stamp the current watermark for a lifecycle event (AOT warm
        boot, autoscaler scale-up) and refresh the per-device gauges, so
        the event's memory cost is scrapeable next to its latency."""
        self.update()
        wm = self.watermark()
        if wm is not None:
            self._g_event.set(wm, event=event, **self._plab())
        return wm


#: THE process-wide memory profiler (StepProfiler, the AOT warm path,
#: and the fleet scrape surface share it so the series stay one family).
memory_profiler = MemoryProfiler()
