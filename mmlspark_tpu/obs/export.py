"""Span-tree export: Chrome-trace/Perfetto JSON + the flight recorder.

Spans emit as flat JSON events (one per ``end_span``); this module turns
them back into openable artifacts:

- :func:`chrome_trace` — Chrome trace-event JSON (``chrome://tracing``
  and Perfetto both load it): one complete ``"X"`` event per span,
  ``pid`` = emitting process, ``tid`` = trace id, so each request's
  cross-process tree renders as one track per process.

- :class:`SpanCollector` — a bounded tracer sink retaining EVERY
  finished span (tests and short captures; not for always-on use).

- :class:`FlightRecorder` — the always-on ring buffer: collects spans
  per trace, and when the serving layer reports a finished request
  (:meth:`FlightRecorder.note_request`) keeps the full cross-process
  tree for the N slowest and the N most recent errored requests,
  dropping everything else. ``GET /debug/trace`` on both serving fronts
  serves :func:`debug_trace_payload` — the retained trees as one
  Chrome trace plus per-trace summaries, so a p99 outlier's trace_id
  (printed by the load generator) can be looked up minutes later.

Stdlib-only, backend-free, bounded everywhere: an always-on server must
never grow an unbounded span store.
"""

from __future__ import annotations

import collections
import heapq
import json
import threading

from .metrics import registry as _registry
from .tracing import Span, tracer as _tracer

# spans a single trace may retain (a runaway span loop inside one
# request must not evict every other trace's tree)
MAX_SPANS_PER_TRACE = 512


def _span_dict(span) -> dict:
    return span.to_dict() if isinstance(span, Span) else dict(span)


def _tid_of(trace_id: str) -> int:
    """Stable positive int track id from a hex-ish trace id."""
    try:
        return int(trace_id[-8:], 16) % (1 << 31) or 1
    except (ValueError, TypeError):
        return abs(hash(trace_id)) % (1 << 31) or 1


def chrome_trace(spans, *, extra_metadata: dict | None = None) -> dict:
    """Chrome trace-event JSON from finished spans (Span objects or
    their ``to_dict`` forms). Timestamps are the spans' wall-derived
    ``startWall`` in microseconds; each span is a complete ``X`` event."""
    events: list[dict] = []
    procs: dict[str, int] = {}
    for sp in spans:
        d = _span_dict(sp)
        proc = str(d.get("proc") or "?")
        pid = procs.setdefault(proc, len(procs) + 1)
        seconds = d.get("seconds") or 0.0
        event = {
            "ph": "X",
            "name": d.get("name", ""),
            "cat": "span",
            "ts": float(d.get("startWall") or 0.0) * 1e6,
            "dur": float(seconds) * 1e6,
            "pid": pid,
            "tid": _tid_of(str(d.get("traceId", ""))),
            "args": {
                "traceId": d.get("traceId"),
                "spanId": d.get("spanId"),
                "parentId": d.get("parentId"),
                **(d.get("attrs") or {}),
            },
        }
        if d.get("error"):
            event["args"]["error"] = d["error"]
        events.append(event)
    for proc, pid in procs.items():
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": f"proc {proc}"}})
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    if extra_metadata:
        out["metadata"] = dict(extra_metadata)
    return out


class SpanCollector:
    """Bounded collect-everything sink for tests and short captures:
    ``with SpanCollector() as spans: ...`` then inspect/export."""

    def __init__(self, maxlen: int = 65536, tracer=None):
        self._tracer = tracer if tracer is not None else _tracer
        self._lock = threading.Lock()
        self._spans = collections.deque(maxlen=int(maxlen))

    def __enter__(self) -> "SpanCollector":
        self._tracer.add_sink(self._on_span)
        return self

    def __exit__(self, *exc) -> None:
        self._tracer.remove_sink(self._on_span)

    def _on_span(self, span) -> None:
        with self._lock:
            self._spans.append(_span_dict(span))

    def ingest(self, span_dicts) -> None:
        """Fold remotely-collected spans (wire dicts) in."""
        with self._lock:
            for d in span_dicts:
                self._spans.append(dict(d))

    def spans(self) -> list[dict]:
        with self._lock:
            return list(self._spans)

    def by_trace(self) -> dict[str, list[dict]]:
        out: dict[str, list[dict]] = {}
        for d in self.spans():
            out.setdefault(str(d.get("traceId", "")), []).append(d)
        return out

    def names_by_trace(self) -> dict[str, set]:
        return {t: {d.get("name") for d in ds}
                for t, ds in self.by_trace().items()}


class FlightRecorder:
    """Always-on retention of the N slowest / errored requests' full
    cross-process span trees.

    Collection: :meth:`install` subscribes to the tracer, so every local
    span lands in a bounded pending bucket keyed by trace id; remote
    spans arrive through :meth:`ingest` (the mesh reply payload carries
    the worker's spans home). Retention: the serving layer calls
    :meth:`note_request` when a request finishes; errored requests and
    the slowest ``keep_slowest`` go to the kept store, everything else
    ages out of pending FIFO.
    """

    def __init__(self, keep_slowest: int = 32, keep_errored: int = 32,
                 max_pending: int = 1024, registry=None, tracer=None):
        reg = registry if registry is not None else _registry
        self.keep_slowest = int(keep_slowest)
        self.keep_errored = int(keep_errored)
        self.max_pending = int(max_pending)
        self._tracer = tracer if tracer is not None else _tracer
        self._lock = threading.Lock()
        self._installed = False
        #: trace_id -> list[span dict] (insertion-ordered, FIFO evicted)
        self._pending: collections.OrderedDict[str, list] = \
            collections.OrderedDict()
        #: kept trees: trace_id -> {"seconds","status","error","spans"}
        self._kept: dict[str, dict] = {}
        #: min-heap of (seconds, trace_id) over kept-for-slowness traces
        self._slow_heap: list[tuple[float, str]] = []
        #: errored trace ids, FIFO bounded
        self._errored: collections.deque = collections.deque()
        self._c_traces = reg.counter(
            "profile_flight_traces_total",
            "flight-recorder retention decisions, by outcome")

    # -- collection --------------------------------------------------------
    def install(self, tracer=None) -> "FlightRecorder":
        """Subscribe to the tracer (idempotent). The serving fronts call
        this from ``_init_shared_state``."""
        with self._lock:
            if self._installed:
                return self
            self._installed = True
        (tracer if tracer is not None else self._tracer) \
            .add_sink(self._on_span)
        return self

    def _on_span(self, span) -> None:
        self._add(_span_dict(span))

    def ingest(self, span_dicts) -> None:
        """Fold spans collected in ANOTHER process in (mesh replies
        carry the worker's spans; dedup by spanId per trace)."""
        for d in span_dicts or ():
            self._add(dict(d))

    def _add(self, d: dict) -> None:
        trace_id = str(d.get("traceId") or "")
        if not trace_id:
            return
        with self._lock:
            kept = self._kept.get(trace_id)
            if kept is not None:
                # late spans for a kept trace (a worker's reply payload
                # landing after note_request) complete the tree
                if len(kept["spans"]) < MAX_SPANS_PER_TRACE and \
                        not any(s.get("spanId") == d.get("spanId")
                                for s in kept["spans"]):
                    kept["spans"].append(d)
                return
            bucket = self._pending.get(trace_id)
            if bucket is None:
                bucket = self._pending[trace_id] = []
                while len(self._pending) > self.max_pending:
                    self._evict_one_pending_locked()
                    self._c_traces.inc(1, outcome="evicted")
            if len(bucket) < MAX_SPANS_PER_TRACE and \
                    not any(s.get("spanId") == d.get("spanId")
                            for s in bucket):
                bucket.append(d)

    def pending_spans(self, *, drain: bool = False,
                      max_spans: int = 256) -> list[dict]:
        """Flat copy of pending (not-yet-retained) spans, oldest trace
        first, bounded by ``max_spans``. With ``drain=True`` the copied
        spans are removed — a mesh worker's heartbeat flushes its local
        recorder home this way, so spans for a request that never
        replies (worker death) still reach the ingest-side recorder
        instead of rotting in the worker's pending ring."""
        out: list[dict] = []
        with self._lock:
            for tid in list(self._pending):
                if len(out) >= max_spans:
                    break
                bucket = self._pending[tid]
                take = bucket[:max_spans - len(out)]
                out.extend(dict(s) for s in take)
                if drain:
                    rest = bucket[len(take):]
                    if rest:
                        self._pending[tid] = rest
                    else:
                        del self._pending[tid]
        return out

    def mark_incomplete(self, trace_id: str,
                        reason: str = "worker lost") -> bool:
        """The process emitting part of this trace died mid-request
        (lease replay after worker death): promote whatever spans made
        it home into the kept store, flagged ``incomplete``, so
        ``/debug/trace`` shows a closed — not orphaned — tree. If the
        trace was already kept, just flags it. False when the trace is
        unknown on this recorder."""
        trace_id = str(trace_id or "")
        if not trace_id:
            return False
        with self._lock:
            kept = self._kept.get(trace_id)
            if kept is not None:
                kept["incomplete"] = True
                kept["note"] = reason
                return True
            spans = self._pending.pop(trace_id, None)
            if spans is None:
                return False
            self._kept[trace_id] = {
                "seconds": 0.0, "status": 0, "error": True,
                "incomplete": True, "note": reason, "spans": spans}
            self._errored.append(trace_id)
            if len(self._errored) > self.keep_errored:
                old = self._errored.popleft()
                self._kept.pop(old, None)
            self._c_traces.inc(1, outcome="kept_incomplete")
            return True

    def _evict_one_pending_locked(self) -> None:
        """Evict the oldest SINGLE-span pending trace first: the steady
        stream of lone root spans (an outbound ``http.send`` with no
        ambient parent opens a fresh one-span trace that will never see
        a ``note_request``) must not flush a multi-span request tree
        that is still in flight — exactly the slow request the recorder
        exists to keep. Falls back to plain FIFO when every pending
        trace is multi-span."""
        for tid, bucket in self._pending.items():
            if len(bucket) <= 1:
                del self._pending[tid]
                return
        self._pending.popitem(last=False)

    # -- retention ---------------------------------------------------------
    def note_request(self, trace_id: str, seconds: float, *,
                     status: int = 200, error: bool = False) -> None:
        """A request finished: decide whether its tree survives.
        Errored requests always keep (FIFO-bounded); others compete on
        ``seconds`` for the ``keep_slowest`` slots."""
        trace_id = str(trace_id or "")
        if not trace_id:
            return
        error = bool(error) or int(status) >= 500
        with self._lock:
            prior = self._kept.get(trace_id)
            if prior is not None:
                if prior.get("incomplete"):
                    # the replayed request completed elsewhere — record
                    # the real outcome, keep the incomplete flag
                    prior["seconds"] = float(seconds)
                    prior["status"] = int(status)
                return
            spans = self._pending.pop(trace_id, [])
            if error:
                self._kept[trace_id] = {
                    "seconds": float(seconds), "status": int(status),
                    "error": True, "spans": spans}
                self._errored.append(trace_id)
                if len(self._errored) > self.keep_errored:
                    old = self._errored.popleft()
                    self._kept.pop(old, None)
                self._c_traces.inc(1, outcome="kept_error")
                return
            if len(self._slow_heap) < self.keep_slowest:
                heapq.heappush(self._slow_heap,
                               (float(seconds), trace_id))
            elif self._slow_heap and \
                    float(seconds) > self._slow_heap[0][0]:
                _, evicted = heapq.heapreplace(
                    self._slow_heap, (float(seconds), trace_id))
                self._kept.pop(evicted, None)
                self._c_traces.inc(1, outcome="evicted")
            else:
                self._c_traces.inc(1, outcome="dropped")
                return
            self._kept[trace_id] = {
                "seconds": float(seconds), "status": int(status),
                "error": False, "spans": spans}
            self._c_traces.inc(1, outcome="kept_slow")

    # -- read surface ------------------------------------------------------
    def trees(self) -> list[dict]:
        """Kept trees, slowest first: ``{trace_id, seconds, status,
        error, spans}`` — ``spans`` are wire dicts."""
        with self._lock:
            items = [{"trace_id": t, "seconds": k["seconds"],
                      "status": k["status"], "error": k["error"],
                      "incomplete": bool(k.get("incomplete")),
                      "spans": [dict(s) for s in k["spans"]]}
                     for t, k in self._kept.items()]
        return sorted(items, key=lambda d: -d["seconds"])

    def tree(self, trace_id: str) -> dict | None:
        with self._lock:
            k = self._kept.get(str(trace_id))
            if k is None:
                return None
            return {"trace_id": str(trace_id), "seconds": k["seconds"],
                    "status": k["status"], "error": k["error"],
                    "incomplete": bool(k.get("incomplete")),
                    "spans": [dict(s) for s in k["spans"]]}

    def chrome(self) -> dict:
        """All retained trees as one Chrome trace."""
        trees = self.trees()
        spans = [s for t in trees for s in t["spans"]]
        return chrome_trace(spans, extra_metadata={
            "kept_traces": len(trees)})

    def clear(self) -> None:
        with self._lock:
            self._pending.clear()
            self._kept.clear()
            self._slow_heap.clear()
            self._errored.clear()


#: THE process-wide flight recorder (the serving fronts install + feed it).
flight_recorder = FlightRecorder()


def debug_trace_payload(recorder: FlightRecorder | None = None) -> bytes:
    """The ``GET /debug/trace`` body: retained-trace summaries plus the
    combined Chrome trace — save it as ``.json`` and open in Perfetto."""
    rec = recorder if recorder is not None else flight_recorder
    trees = rec.trees()
    payload = {
        "kept": len(trees),
        "traces": [{"trace_id": t["trace_id"],
                    "seconds": round(t["seconds"], 6),
                    "status": t["status"], "error": t["error"],
                    "incomplete": t.get("incomplete", False),
                    "spans": len(t["spans"])}
                   for t in trees],
        **rec.chrome(),
    }
    return json.dumps(payload).encode()
