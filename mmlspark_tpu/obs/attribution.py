"""Device cost-attribution plane: per-program analytic rooflines.

The real-silicon campaign needs to know, per compiled program, whether
it is compute- or memory-bound and how far measured MFU sits from the
analytic ceiling. This module owns both halves of that comparison:

- :class:`PeakSpec` — the per-platform peak table (flops + HBM
  bandwidth) that replaces the hardcoded ``DEFAULT_PEAK_FLOPS``
  constant everywhere a peak is divided by (StepProfiler MFU, bench
  MFU columns, the regression sentinel's synthetic steps). Resolution
  order: explicit argument > ``MMLSPARK_TPU_PEAK_FLOPS`` /
  ``MMLSPARK_TPU_PEAK_BYTES_PER_S`` env overrides > the detected TPU
  generation (``device_kind``) > the platform family default > the CPU
  fallback row.

- :class:`CostAttribution` — records each compiled program's analytic
  cost (XLA ``cost_analysis()`` flops / bytes accessed, normalized by
  ``parallel.compat.cost_analysis``) and exports the roofline gauges:

  - ``profile_analytic_flops{program}`` — flops per execution,
  - ``profile_analytic_bytes{program}`` — HBM bytes per execution,
  - ``profile_roofline_utilization{program,bound=compute|memory}`` —
    each resource's share of the roofline-critical time
    (``max(flops/peak_flops, bytes/peak_bw)``). The dominant resource
    reads 1.0 and names the program's placement; the other reads its
    arithmetic-intensity headroom. Both are always <= 1.0 by
    construction, so a matmul-bound program pins
    ``{bound="compute"} == 1.0`` on every platform.

Feeding happens at AOT build/warm time (``core/aot.py`` persists the
pair into each entry's ``meta.json`` and re-exports on warm load
without re-running analysis) and at LLM warm time (``serving/llm.py``).
The recorded pair also rides FeatureLog schema v6 rows
(``analytic_flops`` / ``analytic_bytes``) that the ridge cost model
trains on.

Import is stdlib-only and side-effect-free beyond registering the
gauges; jax is only touched behind the same no-init guards
``profile.device_platform`` uses.
"""

from __future__ import annotations

import os
import sys
import threading
from dataclasses import dataclass, replace

from .metrics import registry as _registry

#: env overrides — an operator pinning the peak for an unlisted part
#: (or a derated clock) wins over the table, whatever the platform.
ENV_PEAK_FLOPS = "MMLSPARK_TPU_PEAK_FLOPS"
ENV_PEAK_BYTES = "MMLSPARK_TPU_PEAK_BYTES_PER_S"


@dataclass(frozen=True)
class PeakSpec:
    """One platform's analytic ceilings: peak FLOP/s and HBM B/s."""

    platform: str
    peak_flops: float
    hbm_bytes_per_s: float

    def roofline_seconds(self, flops: float, bytes_: float) -> float:
        """Analytic lower bound on execution time: the slower of the
        compute and memory pipes (the classic roofline)."""
        return max(float(flops) / self.peak_flops,
                   float(bytes_) / self.hbm_bytes_per_s)


#: Per-platform peaks. TPU rows are bf16 per-chip peaks with the
#: published HBM bandwidths; the ``cpu`` row is the bench harness's
#: longstanding 1 Tflop/s reference point (testing/benchmarks.py used
#: it inline) with a DDR-class bandwidth, so CPU rooflines stay
#: comparable across runs rather than pretending to model the host.
PEAK_SPECS: dict[str, PeakSpec] = {
    "tpu-v5e": PeakSpec("tpu-v5e", 197e12, 819e9),
    "tpu-v4": PeakSpec("tpu-v4", 275e12, 1228e9),
    "cpu": PeakSpec("cpu", 1.0e12, 100e9),
}

#: family default: a TPU whose generation we cannot read resolves to
#: the fleet's current default part (v5e — the ROADMAP target slice)
_TPU_DEFAULT = "tpu-v5e"
_FALLBACK = "cpu"


def _tpu_generation() -> str | None:
    """``device_kind``-derived generation key, with the same
    never-initialize guard as ``profile.device_platform``: only ask a
    backend that already exists."""
    mod = sys.modules.get("jax")
    if mod is None:
        return None
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None or not getattr(xb, "_backends", None):
        return None
    try:
        kind = str(mod.devices()[0].device_kind).lower()
    except Exception:
        return None
    if "v5 lite" in kind or "v5e" in kind or "v5litepod" in kind:
        return "tpu-v5e"
    if "v4" in kind:
        return "tpu-v4"
    return None


def peak_spec(platform: str | None = None) -> PeakSpec:
    """Resolve the :class:`PeakSpec` for ``platform`` (default: the
    live ``device_platform()``), applying the documented resolution
    order. Never raises: anything unrecognized (including the
    jax-absent ``"none"``/``"uninitialized"`` states) lands on the CPU
    fallback row."""
    from .profile import device_platform
    key = (platform or device_platform() or "").strip().lower()
    spec = PEAK_SPECS.get(key)
    if spec is None and (key == "tpu" or key.startswith("tpu")):
        spec = PEAK_SPECS.get(_tpu_generation() or _TPU_DEFAULT) \
            or PEAK_SPECS[_TPU_DEFAULT]
    if spec is None:
        spec = PEAK_SPECS[_FALLBACK]
    flops_env = os.environ.get(ENV_PEAK_FLOPS)
    bytes_env = os.environ.get(ENV_PEAK_BYTES)
    try:
        if flops_env:
            spec = replace(spec, peak_flops=float(flops_env))
        if bytes_env:
            spec = replace(spec, hbm_bytes_per_s=float(bytes_env))
    except (TypeError, ValueError):
        pass  # a junk override must not take the metrics plane down
    return spec


class CostAttribution:
    """The per-program analytic-cost table + its gauge exports."""

    def __init__(self, registry=None):
        reg = registry if registry is not None else _registry
        self._lock = threading.Lock()
        self._costs: dict[str, dict] = {}
        self._g_flops = reg.gauge(
            "profile_analytic_flops",
            "XLA cost_analysis flops per execution, by compiled program")
        self._g_bytes = reg.gauge(
            "profile_analytic_bytes",
            "XLA cost_analysis HBM bytes accessed per execution, by "
            "compiled program")
        self._g_roofline = reg.gauge(
            "profile_roofline_utilization",
            "each resource's share of the roofline-critical time per "
            "program (the bound that reads 1.0 is the program's "
            "placement; the other is its headroom)")

    def record_program(self, program: str, flops: float, bytes_: float,
                       *, service: str = "",
                       platform: str | None = None) -> dict:
        """Record one compiled program's analytic cost and export its
        roofline placement against the resolved :class:`PeakSpec`.
        Returns the stored info dict (also what ``meta.json`` and the
        bench bank)."""
        spec = peak_spec(platform)
        flops = max(float(flops), 0.0)
        bytes_ = max(float(bytes_), 0.0)
        t_compute = flops / spec.peak_flops
        t_memory = bytes_ / spec.hbm_bytes_per_s
        critical = max(t_compute, t_memory, 1e-18)
        bound = "compute" if t_compute >= t_memory else "memory"
        self._g_flops.set(flops, program=program)
        self._g_bytes.set(bytes_, program=program)
        self._g_roofline.set(t_compute / critical, program=program,
                             bound="compute")
        self._g_roofline.set(t_memory / critical, program=program,
                             bound="memory")
        info = {
            "program": program,
            "service": service,
            "platform": spec.platform,
            "flops": flops,
            "bytes": bytes_,
            "bound": bound,
            "roofline_seconds": spec.roofline_seconds(flops, bytes_),
            "compute_seconds": t_compute,
            "memory_seconds": t_memory,
        }
        with self._lock:
            self._costs[program] = info
        return info

    def record_compiled(self, program: str, compiled, *,
                        service: str = "",
                        platform: str | None = None) -> dict | None:
        """``cost_analysis`` a ``jax.stages.Compiled`` (through the
        compat normalizer — misses are counted, never raised) and
        record it. Returns None when the backend yields nothing."""
        from ..parallel.compat import cost_analysis
        cost = cost_analysis(compiled)
        if cost is None:
            return None
        return self.record_program(program, cost["flops"],
                                   cost["bytes"], service=service,
                                   platform=platform)

    # -- read surface ------------------------------------------------------
    def program_cost(self, program: str) -> dict | None:
        with self._lock:
            info = self._costs.get(program)
        return dict(info) if info is not None else None

    def programs(self) -> dict[str, dict]:
        """Copy of the whole table (bench banking / debug payloads)."""
        with self._lock:
            return {k: dict(v) for k, v in self._costs.items()}

    def service_cost(self, service: str) -> tuple[float, float]:
        """Summed (flops, bytes) across the service's recorded
        programs — the FeatureLog v6 row values a served request
        carries. (0.0, 0.0) until something compiled for the service."""
        flops = bytes_ = 0.0
        with self._lock:
            for info in self._costs.values():
                if info.get("service") == service:
                    flops += info["flops"]
                    bytes_ += info["bytes"]
        return flops, bytes_

    def clear(self) -> None:
        with self._lock:
            self._costs.clear()


#: THE process-wide attribution table (AOT build/warm, LLM warm, and
#: the serving executor's feature rows all share it).
cost_attribution = CostAttribution()
