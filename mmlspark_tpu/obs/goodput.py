"""Fleet goodput ledger: useful chip-seconds vs itemized waste.

Serving efficiency on chips is goodput — useful work per chip-second
— and the registry already counts every waste source this ledger
folds; nothing here instruments the hot path. Each :meth:`tick` reads
one registry snapshot, takes deltas against the previous tick, prices
each waste source in estimated chip-seconds, and exports:

- ``goodput_waste_seconds_total{cause}`` — estimated wasted seconds by
  cause (monotone, federated fleet-wide like every ``goodput_``
  series),
- ``goodput_ratio`` — useful / (useful + waste) since the ledger's
  baseline tick,
- ``goodput_useful_seconds_total`` — the denominator's useful half.

Waste-cause taxonomy (what is read, and how it is priced):

===============  ====================================================
cause            source counters -> chip-second pricing
===============  ====================================================
spec_reject      ``gen_spec_rejected_total`` draft tokens the verifier
                 threw away x the measured seconds-per-committed-token
                 (``gen_decode_attn_seconds_sum`` / ``gen_tokens_total``)
eager_fallback   ``pipeline_fused_fallback_total`` calls that ran
                 eager x the measured mean profiled step
                 (``profile_step_seconds``), i.e. the fused run the
                 call was supposed to be
shed             ``sched_shed_total`` + ``sched_tenant_shed_total``
                 (reasons other than ``expired``) x a fixed admission
                 unit cost — work turned away at the door
expired          the ``expired`` reasons of the shed families plus
                 ``sched_continuous_expired_total`` x the same unit —
                 work queued, aged out, and thrown away
runtime_compile  ``profile_runtime_compiles_total`` x the measured
                 mean compile (``profile_compile_seconds``)
straggler        the stretch the slowest rank imposes on the whole
                 step: ``(1 - 1/score_max)`` of the tick's step
                 seconds when any ``fleet_straggler_score`` > 1
===============  ====================================================

Useful seconds are the profiled device families the executors already
record: ``profile_step_seconds_sum`` + ``gen_decode_attn_seconds_sum``.
Everything is an attribution model, not a measurement — the pricing
constants are explicit (:data:`DEFAULT_UNIT_COSTS`) and the payload
reports which were measured vs defaulted.

Import is stdlib-only; a jax-free process can construct and tick a
ledger (the no-JAX CI smoke does).
"""

from __future__ import annotations

import json
import threading
import time

from .fleet import parse_sample
from .metrics import registry as _registry

#: the waste-cause label values, in taxonomy order
WASTE_CAUSES = ("spec_reject", "eager_fallback", "shed", "expired",
                "runtime_compile", "straggler")

#: fallback chip-second prices used when no measured mean exists yet
#: (fresh process, cause never measured). Deliberately conservative.
DEFAULT_UNIT_COSTS = {
    "spec_reject": 1e-3,       # one committed-token's decode time
    "eager_fallback": 5e-3,    # one fused-segment execution
    "shed": 1e-3,              # admission + queue bookkeeping
    "expired": 1e-3,
    "runtime_compile": 5e-2,   # one trace+compile
}

#: never attribute more than this share of a tick's step seconds to a
#: straggler — MAD scores are unbounded and a single wild rank must
#: not zero the whole fleet's goodput
_STRAGGLER_CAP = 0.5


class GoodputLedger:
    """Delta-based goodput accounting over a metrics registry."""

    def __init__(self, registry=None, clock=time.monotonic,
                 unit_costs: dict | None = None):
        reg = registry if registry is not None else _registry
        self._reg = reg
        self._clock = clock
        self._unit_defaults = dict(DEFAULT_UNIT_COSTS)
        if unit_costs:
            self._unit_defaults.update(unit_costs)
        self._lock = threading.Lock()
        self._prev: dict | None = None
        self._waste = dict.fromkeys(WASTE_CAUSES, 0.0)
        self._useful = 0.0
        self._ticks = 0
        self._last_units: dict[str, float] = {}
        self._c_waste = reg.counter(
            "goodput_waste_seconds_total",
            "estimated chip-seconds wasted, by cause (see the ledger's "
            "taxonomy: spec_reject | eager_fallback | shed | expired | "
            "runtime_compile | straggler)")
        self._c_useful = reg.counter(
            "goodput_useful_seconds_total",
            "profiled useful device seconds the waste is measured "
            "against")
        self._g_ratio = reg.gauge(
            "goodput_ratio",
            "useful / (useful + estimated waste) chip-seconds since "
            "the ledger baseline (1.0 until anything is measured)")
        self._c_ticks = reg.counter(
            "goodput_ticks_total", "ledger delta evaluations")

    # -- snapshot folding --------------------------------------------------
    def _totals(self) -> dict[str, float]:
        """Fold one registry snapshot into the scalar totals the delta
        pass prices. Sums over label sets so pod-rank / per-service
        splits all count."""
        t = {
            "spec_rejected": 0.0, "fallbacks": 0.0, "shed": 0.0,
            "expired": 0.0, "runtime_compiles": 0.0,
            "compile_sum": 0.0, "compile_count": 0.0,
            "step_sum": 0.0, "decode_sum": 0.0,
            "tokens": 0.0, "straggler_max": 0.0,
        }
        for sample, value in self._reg.snapshot().items():
            name, labels = parse_sample(sample)
            if name == "gen_spec_rejected_total":
                t["spec_rejected"] += value
            elif name == "pipeline_fused_fallback_total":
                t["fallbacks"] += value
            elif name in ("sched_shed_total", "sched_tenant_shed_total"):
                key = "expired" \
                    if labels.get("reason") == "expired" else "shed"
                t[key] += value
            elif name == "sched_continuous_expired_total":
                t["expired"] += value
            elif name == "profile_runtime_compiles_total":
                t["runtime_compiles"] += value
            elif name == "profile_compile_seconds_sum":
                t["compile_sum"] += value
            elif name == "profile_compile_seconds_count":
                t["compile_count"] += value
            elif name == "profile_step_seconds_sum":
                t["step_sum"] += value
            elif name == "gen_decode_attn_seconds_sum":
                t["decode_sum"] += value
            elif name == "gen_tokens_total":
                t["tokens"] += value
            elif name == "fleet_straggler_score":
                t["straggler_max"] = max(t["straggler_max"], value)
        return t

    def _unit(self, cause: str, measured_sum: float,
              measured_count: float) -> float:
        """Measured mean when the denominator exists, else the default
        price; remembered per tick for the debug payload."""
        if measured_count > 0 and measured_sum > 0:
            unit = measured_sum / measured_count
        else:
            unit = self._unit_defaults[cause]
        self._last_units[cause] = unit
        return unit

    # -- the ledger --------------------------------------------------------
    def tick(self) -> dict:
        """Price the waste accrued since the previous tick and update
        the exported series. The first tick only establishes the
        baseline (ratio 1.0). Returns the debug payload."""
        with self._lock:
            totals = self._totals()
            prev, self._prev = self._prev, totals
            self._ticks += 1
            self._c_ticks.inc(1)
            if prev is None:
                return self._payload_locked()
            d = {k: max(totals[k] - prev.get(k, 0.0), 0.0)
                 for k in totals}
            waste = {
                "spec_reject": d["spec_rejected"] * self._unit(
                    "spec_reject", d["decode_sum"], d["tokens"]),
                "eager_fallback": d["fallbacks"] * self._unit(
                    "eager_fallback", 0.0, 0.0),
                "shed": d["shed"] * self._unit("shed", 0.0, 0.0),
                "expired": d["expired"] * self._unit(
                    "expired", 0.0, 0.0),
                "runtime_compile": d["runtime_compiles"] * self._unit(
                    "runtime_compile", d["compile_sum"],
                    d["compile_count"]),
            }
            useful = d["step_sum"] + d["decode_sum"]
            s = totals["straggler_max"]
            stretch = min(max(1.0 - 1.0 / s, 0.0), _STRAGGLER_CAP) \
                if s > 1.0 else 0.0
            waste["straggler"] = stretch * useful
            self._last_units["straggler"] = stretch
            for cause, sec in waste.items():
                if sec > 0:
                    self._c_waste.inc(sec, cause=cause)
                self._waste[cause] += sec
            if useful > 0:
                self._c_useful.inc(useful)
            self._useful += useful
            self._g_ratio.set(self._ratio_locked())
            return self._payload_locked()

    def _ratio_locked(self) -> float:
        total = self._useful + sum(self._waste.values())
        return self._useful / total if total > 0 else 1.0

    def _payload_locked(self) -> dict:
        return {
            "goodput_ratio": self._ratio_locked(),
            "useful_seconds": self._useful,
            "waste_seconds": dict(self._waste),
            "waste_total_seconds": sum(self._waste.values()),
            "ticks": self._ticks,
            "unit_costs": dict(self._last_units),
        }

    def payload(self) -> dict:
        """Tick, then report — the ``/debug/goodput`` surface is never
        staler than its own request."""
        return self.tick()

    def reset(self) -> None:
        """Drop the baseline and accumulated totals (the exported
        counters stay monotone; only the ratio restarts)."""
        with self._lock:
            self._prev = None
            self._waste = dict.fromkeys(WASTE_CAUSES, 0.0)
            self._useful = 0.0
            self._ticks = 0
            self._last_units.clear()


#: THE process-wide ledger (both serving fronts' /debug/goodput route
#: and the bench harness tick this one).
goodput_ledger = GoodputLedger()


def goodput_payload() -> bytes:
    """JSON body for ``GET /debug/goodput`` (ticks the singleton)."""
    return json.dumps(goodput_ledger.payload(), indent=1,
                      sort_keys=True).encode()
