"""RecommendationIndexer — user/item id indexing.

Reference ``recommendation/RecommendationIndexer.scala``: string user/item
columns → contiguous int indices (fit collects vocabularies), with inverse
mapping for recommendation output.
"""

from __future__ import annotations

import numpy as np

from ..core import ComplexParam, Estimator, Model, Param, \
    TypeConverters as TC


class RecommendationIndexer(Estimator):
    userInputCol = Param("userInputCol", "raw user column", TC.toString)
    userOutputCol = Param("userOutputCol", "indexed user column",
                          TC.toString, default="user")
    itemInputCol = Param("itemInputCol", "raw item column", TC.toString)
    itemOutputCol = Param("itemOutputCol", "indexed item column",
                          TC.toString, default="item")
    ratingCol = Param("ratingCol", "rating column", TC.toString,
                      default="rating")

    def _fit(self, df):
        users = sorted({v for v in df[self.getUserInputCol()].tolist()},
                       key=str)
        items = sorted({v for v in df[self.getItemInputCol()].tolist()},
                       key=str)
        model = RecommendationIndexerModel(userLevels=users,
                                           itemLevels=items)
        self._copy_params_to(model)
        return model


class RecommendationIndexerModel(Model):
    userInputCol = Param("userInputCol", "raw user column", TC.toString)
    userOutputCol = Param("userOutputCol", "indexed user column",
                          TC.toString, default="user")
    itemInputCol = Param("itemInputCol", "raw item column", TC.toString)
    itemOutputCol = Param("itemOutputCol", "indexed item column",
                          TC.toString, default="item")
    userLevels = ComplexParam("userLevels", "ordered raw user values")
    itemLevels = ComplexParam("itemLevels", "ordered raw item values")

    def _transform(self, df):
        u_map = {v: i for i, v in enumerate(self.get("userLevels"))}
        i_map = {v: i for i, v in enumerate(self.get("itemLevels"))}
        users = np.asarray([u_map[v] for v in
                            df[self.getUserInputCol()].tolist()], np.int64)
        items = np.asarray([i_map[v] for v in
                            df[self.getItemInputCol()].tolist()], np.int64)
        return (df.with_column(self.get("userOutputCol"), users)
                  .with_column(self.get("itemOutputCol"), items))

    def recover_user(self, idx: np.ndarray):
        levels = np.asarray(self.get("userLevels"), object)
        return levels[np.asarray(idx, np.int64)]

    def recover_item(self, idx: np.ndarray):
        levels = np.asarray(self.get("itemLevels"), object)
        return levels[np.asarray(idx, np.int64)]
