"""SAR — Smart Adaptive Recommendations.

Reference ``recommendation/SAR.scala:36-200+``: item-item co-occurrence
with jaccard/lift/cooccurrence similarities (:186-195), optionally
time-decayed user-item affinity (:86-128); ``SARModel.scala`` scores via
user-affinity × item-similarity and returns top-K unseen items.

TPU shape: co-occurrence = Aᵀ A (one matmul over the user-item matrix),
similarity normalization elementwise, recommendation = affinity @ sim +
top_k — the whole model is three MXU ops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import ComplexParam, DataFrame, Estimator, Model, Param, \
    TypeConverters as TC


@functools.partial(jax.jit, static_argnames=("similarity",))
def _item_similarity(counts: jnp.ndarray, similarity: str,
                     support_threshold: int):
    """counts: [I, I] co-occurrence (diag = item occurrence counts)."""
    occ = jnp.diag(counts)
    cooc = jnp.where(counts >= support_threshold, counts, 0.0)
    if similarity == "cooccurrence":
        sim = cooc
    elif similarity == "jaccard":
        denom = occ[:, None] + occ[None, :] - cooc
        sim = jnp.where(denom > 0, cooc / denom, 0.0)
    elif similarity == "lift":
        denom = occ[:, None] * occ[None, :]
        sim = jnp.where(denom > 0, cooc / denom, 0.0)
    else:
        raise ValueError(f"unknown similarity {similarity!r}")
    return sim


@functools.partial(jax.jit, static_argnames=("k",))
def _recommend(affinity, sim, seen_mask, k: int):
    scores = affinity @ sim                      # [U, I]
    scores = jnp.where(seen_mask, -jnp.inf, scores)
    return jax.lax.top_k(scores, k)


class SAR(Estimator):
    userCol = Param("userCol", "user id column (0-based int)", TC.toString,
                    default="user")
    itemCol = Param("itemCol", "item id column (0-based int)", TC.toString,
                    default="item")
    ratingCol = Param("ratingCol", "rating column ('' = implicit 1.0)",
                      TC.toString, default="rating")
    timeCol = Param("timeCol", "event-time column (unix seconds) for decay",
                    TC.toString, default="")
    similarityFunction = Param("similarityFunction",
                               "jaccard | lift | cooccurrence", TC.toString,
                               default="jaccard")
    supportThreshold = Param("supportThreshold",
                             "min co-occurrence count", TC.toInt, default=4)
    timeDecayCoeff = Param("timeDecayCoeff", "half-life in days", TC.toInt,
                           default=30)
    activityTimeFormat = Param("activityTimeFormat", "inert (numeric time "
                               "expected)", TC.toString,
                               default="yyyy/MM/dd'T'h:mm:ss")

    def _fit(self, df):
        users = np.asarray(df[self.get("userCol")], np.int64)
        items = np.asarray(df[self.get("itemCol")], np.int64)
        U, I = int(users.max()) + 1, int(items.max()) + 1

        rcol = self.get("ratingCol")
        ratings = (np.asarray(df[rcol], np.float32)
                   if rcol and rcol in df.columns
                   else np.ones(len(users), np.float32))

        # ---- time-decayed affinity (reference SAR.scala:86-128):
        # a(u,i) = Σ r · 2^(-(t_ref - t)/T)
        tcol = self.get("timeCol")
        if tcol and tcol in df.columns:
            t = np.asarray(df[tcol], np.float64)
            t_ref = t.max()
            half_life_s = self.get("timeDecayCoeff") * 86400.0
            decay = np.power(2.0, -(t_ref - t) / half_life_s)
            ratings = (ratings * decay).astype(np.float32)

        affinity = np.zeros((U, I), np.float32)
        np.add.at(affinity, (users, items), ratings)

        # ---- co-occurrence & similarity: binary occurrence matrix
        occurrence = np.zeros((U, I), np.float32)
        occurrence[users, items] = 1.0
        counts = jnp.asarray(occurrence).T @ jnp.asarray(occurrence)
        sim = _item_similarity(counts, self.get("similarityFunction"),
                               self.get("supportThreshold"))

        model = SARModel(userAffinity=affinity,
                         itemSimilarity=np.asarray(sim),
                         seenItems=occurrence.astype(bool))
        self._copy_params_to(model)
        return model


class SARModel(Model):
    userCol = Param("userCol", "user id column", TC.toString,
                    default="user")
    itemCol = Param("itemCol", "item id column", TC.toString,
                    default="item")
    userAffinity = ComplexParam("userAffinity", "[U, I] affinity matrix")
    itemSimilarity = ComplexParam("itemSimilarity", "[I, I] similarities")
    seenItems = ComplexParam("seenItems", "[U, I] bool seen mask")

    def recommend_for_all_users(self, num_items: int,
                                remove_seen: bool = True) -> DataFrame:
        aff = jnp.asarray(self.get("userAffinity"))
        sim = jnp.asarray(self.get("itemSimilarity"))
        seen = jnp.asarray(self.get("seenItems")) if remove_seen else \
            jnp.zeros(aff.shape, bool)
        scores, item_idx = _recommend(aff, sim, seen,
                                      min(num_items, aff.shape[1]))
        U = aff.shape[0]
        recs = np.empty(U, object)
        ratings = np.empty(U, object)
        s_np, i_np = np.asarray(scores), np.asarray(item_idx)
        for u in range(U):
            keep = np.isfinite(s_np[u])
            recs[u] = i_np[u][keep].tolist()
            ratings[u] = s_np[u][keep].tolist()
        return DataFrame({self.get("userCol"): np.arange(U),
                          "recommendations": recs, "ratings": ratings})

    def _transform(self, df):
        """Score (user, item) pairs: affinity row · similarity column."""
        users = np.asarray(df[self.get("userCol")], np.int64)
        items = np.asarray(df[self.get("itemCol")], np.int64)
        aff = self.get("userAffinity")
        sim = self.get("itemSimilarity")
        scores = np.einsum("ui,ij->uj", aff[users], sim)[
            np.arange(len(items)), items]
        return df.with_column("prediction", scores.astype(np.float32))
