"""Recommendation: SAR + ranking evaluation.

Reference ``recommendation/`` (SURVEY §2.10): ``SAR.scala`` (item-item
co-occurrence similarities + time-decayed user affinity), ``SARModel.scala``
(affinity × similarity top-K), ``RankingAdapter``/``RankingEvaluator``
(NDCG/MAP/recall@k), ``RankingTrainValidationSplit`` (per-user splits +
param sweep), ``RecommendationIndexer``.
"""

from .sar import SAR, SARModel
from .indexer import RecommendationIndexer, RecommendationIndexerModel
from .evaluator import RankingEvaluator, RankingAdapter
from .split import RankingTrainValidationSplit

__all__ = ["SAR", "SARModel", "RecommendationIndexer",
           "RecommendationIndexerModel", "RankingEvaluator",
           "RankingAdapter", "RankingTrainValidationSplit"]
