"""RankingTrainValidationSplit — per-user chronological/ratio splits +
parallel param sweep.

Reference ``recommendation/RankingTrainValidationSplit.scala:25-292``:
split each user's interactions into train/validation (by ratio, min
ratings enforced), sweep estimator param maps in a thread pool (:94-132),
pick the best by a ranking metric.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..core import ComplexParam, Estimator, Model, Param, \
    TypeConverters as TC
from .evaluator import RankingAdapter, RankingEvaluator


class RankingTrainValidationSplit(Estimator):
    estimator = ComplexParam("estimator", "recommender estimator (SAR)")
    paramMaps = ComplexParam("paramMaps",
                             "list of {param: value} dicts to sweep",
                             default=None, has_default=True)
    userCol = Param("userCol", "user column", TC.toString, default="user")
    itemCol = Param("itemCol", "item column", TC.toString, default="item")
    trainRatio = Param("trainRatio", "per-user train fraction", TC.toFloat,
                       default=0.75)
    minRatingsPerUser = Param("minRatingsPerUser",
                              "users below this are all-train", TC.toInt,
                              default=1)
    k = Param("k", "eval cutoff", TC.toInt, default=10)
    metricName = Param("metricName", "ndcgAt | map | recallAtK",
                       TC.toString, default="ndcgAt")
    parallelism = Param("parallelism", "concurrent fits", TC.toInt,
                        default=2)
    seed = Param("seed", "shuffle seed", TC.toInt, default=0)

    def _split(self, df):
        users = np.asarray(df[self.get("userCol")], np.int64)
        rng = np.random.default_rng(self.get("seed"))
        in_train = np.ones(len(users), bool)
        for u in np.unique(users):
            idx = np.where(users == u)[0]
            if len(idx) < self.get("minRatingsPerUser") or len(idx) < 2:
                continue
            n_val = max(1, int(round(len(idx)
                                     * (1 - self.get("trainRatio")))))
            n_val = min(n_val, len(idx) - 1)
            in_train[rng.choice(idx, size=n_val, replace=False)] = False
        return df.filter(in_train), df.filter(~in_train)

    def _fit(self, df):
        train_df, valid_df = self._split(df)
        base = self.get("estimator")
        param_maps = self.get("paramMaps") or [{}]

        def run(pm: dict) -> tuple[float, object]:
            est = base.copy()
            for name, value in pm.items():
                est.set(name, value)
            model = est.fit(train_df)
            adapter = RankingAdapter(
                userCol=self.get("userCol"), itemCol=self.get("itemCol"),
                k=self.get("k"), recommender=model)
            joined = adapter.transform(valid_df)
            metric = RankingEvaluator(
                k=self.get("k"),
                metric_name=self.get("metricName")).evaluate(joined)
            return metric, model

        with ThreadPoolExecutor(self.get("parallelism")) as pool:
            results = list(pool.map(run, param_maps))
        metrics = [m for m, _ in results]
        best_idx = int(np.argmax(metrics))
        model = RankingTrainValidationSplitModel(
            bestModel=results[best_idx][1],
            validationMetrics=metrics)
        self._copy_params_to(model)
        return model


class RankingTrainValidationSplitModel(Model):
    bestModel = ComplexParam("bestModel", "winning recommender")
    validationMetrics = ComplexParam("validationMetrics",
                                     "metric per param map")

    def _transform(self, df):
        return self.get("bestModel").transform(df)
