"""Ranking evaluation: NDCG@k, MAP@k, precision/recall@k.

Reference ``recommendation/RankingEvaluator`` + ``RankingAdapter`` —
converts scored interactions to per-user ranked lists and computes
top-k ranking metrics.
"""

from __future__ import annotations

import numpy as np

from ..core import DataFrame, Transformer, Param, TypeConverters as TC


def ndcg_at_k(recommended: list, relevant: set, k: int) -> float:
    dcg = sum(1.0 / np.log2(i + 2)
              for i, r in enumerate(recommended[:k]) if r in relevant)
    ideal = sum(1.0 / np.log2(i + 2)
                for i in range(min(len(relevant), k)))
    return dcg / ideal if ideal > 0 else 0.0


def map_at_k(recommended: list, relevant: set, k: int) -> float:
    hits, score = 0, 0.0
    for i, r in enumerate(recommended[:k]):
        if r in relevant:
            hits += 1
            score += hits / (i + 1)
    return score / min(len(relevant), k) if relevant else 0.0


def precision_at_k(recommended: list, relevant: set, k: int) -> float:
    return sum(r in relevant for r in recommended[:k]) / k


def recall_at_k(recommended: list, relevant: set, k: int) -> float:
    if not relevant:
        return 0.0
    return sum(r in relevant for r in recommended[:k]) / len(relevant)


_METRICS = {"ndcgAt": ndcg_at_k, "map": map_at_k,
            "precisionAtk": precision_at_k, "recallAtK": recall_at_k}


class RankingEvaluator:
    """Evaluate (recommendations, ground-truth) per user.

    ``evaluate(df)`` expects columns ``recommendations`` (list per user,
    as produced by ``SARModel.recommend_for_all_users``) and ``groundTruth``
    (list per user).
    """

    def __init__(self, k: int = 10, metric_name: str = "ndcgAt"):
        self.k = k
        self.metric_name = metric_name

    def evaluate(self, df: DataFrame) -> float:
        fn = _METRICS[self.metric_name]
        recs = df["recommendations"]
        truth = df["groundTruth"]
        vals = [fn(list(r), set(t), self.k) for r, t in zip(recs, truth)]
        return float(np.mean(vals)) if vals else 0.0


class RankingAdapter(Transformer):
    """Join model recommendations with held-out truth per user
    (reference ``RankingAdapter``: mode="allUsers" top-k)."""

    userCol = Param("userCol", "user column", TC.toString, default="user")
    itemCol = Param("itemCol", "item column", TC.toString, default="item")
    k = Param("k", "recommendations per user", TC.toInt, default=10)
    recommender = Param("recommender", "fitted SARModel (or compatible)")

    def _transform(self, df):
        model = self.get("recommender")
        recs = model.recommend_for_all_users(self.get("k"))
        truth: dict = {}
        users = np.asarray(df[self.get("userCol")], np.int64)
        items = np.asarray(df[self.get("itemCol")], np.int64)
        for u, i in zip(users, items):
            truth.setdefault(int(u), []).append(int(i))
        rec_users = np.asarray(recs[self.get("userCol")], np.int64)
        gt = np.empty(len(rec_users), object)
        gt[:] = [truth.get(int(u), []) for u in rec_users]
        return recs.with_column("groundTruth", gt)
