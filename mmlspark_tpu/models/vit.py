"""Vision Transformer (ViT-B/16 family) for the model zoo.

Zoo member beside the ResNets (reference catalogue:
``downloader/Schema.scala`` / ``ModelDownloader.scala`` — pretrained CNNs
fed to ``ImageFeaturizer``). A transformer is the TPU-natural image
backbone: everything is a large matmul on the MXU, no im2col, static
token count. Layout and forward semantics follow torchvision's
``vit_b_16`` (pre-LN blocks, cls token, learned position embeddings) so
public checkpoints convert weight-for-weight (``models/convert.py``).

Endpoints (the ``cutOutputLayers`` contract of ``ImageFeaturizer``):
``block1..depth`` (token tensors), ``pooled`` (final-LN cls token — the
transfer-learning feature), ``logits``.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn


class MHA(nn.Module):
    """Multi-head self-attention with explicit q/k/v/out Dense params
    (kernel [W, W] — torch ``in_proj_weight`` slices transpose straight
    in). Softmax runs in f32 regardless of compute dtype."""
    heads: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        N, T, W = x.shape
        hd = W // self.heads
        q = nn.Dense(W, dtype=self.dtype, name="q")(x)
        k = nn.Dense(W, dtype=self.dtype, name="k")(x)
        v = nn.Dense(W, dtype=self.dtype, name="v")(x)

        def split(a):
            return a.reshape(N, T, self.heads, hd).transpose(0, 2, 1, 3)

        q, k, v = split(q), split(k), split(v)
        logits = jnp.einsum("nhqd,nhkd->nhqk", q, k,
                            preferred_element_type=jnp.float32)
        attn = nn.softmax(logits / jnp.sqrt(hd).astype(jnp.float32),
                          axis=-1).astype(self.dtype)
        out = jnp.einsum("nhqk,nhkd->nhqd", attn, v)
        out = out.transpose(0, 2, 1, 3).reshape(N, T, W)
        return nn.Dense(W, dtype=self.dtype, name="out")(out)


class Block(nn.Module):
    """Pre-LN transformer block: x + MHA(LN(x)); x + MLP(LN(x))."""
    heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        W = x.shape[-1]
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_1")(x)
        x = x + MHA(self.heads, dtype=self.dtype,
                    name="attn")(h.astype(self.dtype))
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_2")(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype,
                     name="mlp_1")(h.astype(self.dtype))
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(W, dtype=self.dtype, name="mlp_2")(h)
        return x + h


class ViT(nn.Module):
    """Returns ``{"block1"..f"block{depth}", "pooled", "logits"}``."""
    patch: int = 16
    width: int = 768
    depth: int = 12
    heads: int = 12
    mlp_dim: int = 3072
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    # rematerialize blocks in the backward (jax.checkpoint) — the
    # fine-tune memory lever; param names unchanged, so converted
    # checkpoints load identically
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        endpoints = {}
        N = x.shape[0]
        x = x.astype(self.dtype)
        # patchify = one strided conv (a matmul on the MXU)
        x = nn.Conv(self.width, (self.patch, self.patch),
                    (self.patch, self.patch), padding="VALID",
                    dtype=self.dtype, name="conv_proj")(x)
        x = x.reshape(N, -1, self.width)               # [N, T, W]
        cls = self.param("class_token", nn.initializers.zeros,
                         (1, 1, self.width), jnp.float32)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (N, 1, self.width)).astype(self.dtype),
             x], axis=1)
        T = x.shape[1]
        pos = self.param("pos_embedding",
                         nn.initializers.normal(stddev=0.02),
                         (1, T, self.width), jnp.float32)
        x = x + pos.astype(self.dtype)
        block_cls = nn.remat(Block) if self.remat else Block
        from ..parallel.partition import constrain_activation
        for i in range(self.depth):
            # block-boundary activation sharding (batch over dp per the
            # registered spec) — identity with no mesh in scope
            x = constrain_activation(
                block_cls(self.heads, self.mlp_dim, dtype=self.dtype,
                          name=f"block{i}")(x), "ViT")
            endpoints[f"block{i + 1}"] = x
        x = nn.LayerNorm(dtype=jnp.float32, name="ln")(x)
        endpoints["pooled"] = x[:, 0].astype(jnp.float32)
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          name="head")(x[:, 0].astype(self.dtype))
        endpoints["logits"] = logits.astype(jnp.float32)
        return endpoints

    @property
    def layer_names(self) -> list[str]:
        return ([f"block{i + 1}" for i in range(self.depth)]
                + ["pooled", "logits"])


# Partition rules for the ViT family: the Megatron column→row pairing —
# q/k/v and mlp_1 shard their OUTPUT features ("column parallel"), out
# and mlp_2 shard their INPUT features ("row parallel") so the only
# cross-shard reduction per block is the one GSPMD inserts after each
# row-parallel matmul. Specs right-align (parallel/partition.py), so
# the same rules cover scan-stacked block params.
from ..parallel.partition import DtypePolicy, register_partition_rules

register_partition_rules("ViT", [
    (r"(class_token|pos_embedding)", ()),
    (r"conv_proj/kernel", ("tp",)),
    (r"conv_proj/bias", ("tp",)),
    (r"(ln_1|ln_2)/(scale|bias)", ()),
    (r"(^|/)ln/(scale|bias)", ()),
    (r"attn/(q|k|v)/kernel", (None, "tp")),
    (r"attn/(q|k|v)/bias", ("tp",)),
    (r"attn/out/kernel", ("tp", None)),
    (r"attn/out/bias", ()),
    (r"mlp_1/kernel", (None, "tp")),
    (r"mlp_1/bias", ("tp",)),
    (r"mlp_2/kernel", ("tp", None)),
    (r"mlp_2/bias", ()),
    (r"head/kernel", (None, "tp")),
    (r"head/bias", ()),
],
    # bf16 compute / fp32 storage+accum; batch-sharded activations at
    # block boundaries (the framework-wide chip defaults)
    dtype_policy=DtypePolicy(param_dtype="float32",
                             compute_dtype="bfloat16",
                             grad_accum_dtype="float32"),
    activation_spec=("dp",))


def ViT_B_16(num_classes=1000, dtype=jnp.bfloat16, remat=False):
    return ViT(num_classes=num_classes, dtype=dtype, remat=remat)


def ViT_L_16(num_classes=1000, dtype=jnp.bfloat16, remat=False):
    return ViT(width=1024, depth=24, heads=16, mlp_dim=4096,
               num_classes=num_classes, dtype=dtype, remat=remat)
