"""ResNet family in flax.linen, TPU-first.

Replaces the reference's downloaded CNTK ResNet50 graph (the default
``ImageFeaturizer`` backbone, ``downloader/Schema.scala`` layerNames). The
forward pass exposes a dict of named endpoints — pooled features, every
stage output, logits — so feature extraction at any depth is a lookup, the
moral equivalent of CNTK ``cutOutputLayers``.

TPU notes: NHWC layout (XLA's native conv layout on TPU), bfloat16 compute
with float32 params/BN statistics, channel dims kept multiples of 128 where
the architecture allows so conv GEMMs tile cleanly onto the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


# All spatial convs use explicit symmetric padding (the torchvision
# convention) rather than SAME: for stride-2 convs SAME pads
# asymmetrically, which would make converted torchvision checkpoints
# (models/convert.py) numerically diverge from their source model.
_PAD3 = ((1, 1), (1, 1))
_PAD7 = ((3, 3), (3, 3))


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), (self.strides, self.strides),
                 padding=_PAD3)(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), padding=_PAD3)(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            (self.strides, self.strides))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        residual = x
        y = nn.relu(norm()(conv(self.filters, (1, 1))(x)))
        y = conv(self.filters, (3, 3), (self.strides, self.strides),
                 padding=_PAD3)(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            (self.strides, self.strides))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Returns ``{"stage1".."stage4", "pooled", "logits"}`` endpoints.

    ``pooled`` (the global-average-pool vector) is the transfer-learning
    feature the reference extracts by cutting one layer off the CNTK graph
    (``image/ImageFeaturizer.scala:40-60``).
    """
    stage_sizes: Sequence[int]
    block: type = BottleneckBlock
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    # rematerialize blocks in the backward (jax.checkpoint) — the
    # fine-tune memory lever. Blocks get explicit names reproducing the
    # auto-name counter (``BottleneckBlock_0``…), because nn.remat would
    # otherwise auto-name them ``CheckpointBottleneckBlock_0`` and break
    # every converted checkpoint.
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        endpoints = {}
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), (2, 2), padding=_PAD7,
                    use_bias=False, dtype=self.dtype, name="conv_init")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding=((1, 1), (1, 1)))
        block_cls = nn.remat(self.block, static_argnums=(2,)) \
            if self.remat else self.block
        from ..parallel.partition import constrain_activation
        idx = 0
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = block_cls(self.width * 2 ** i, strides,
                              dtype=self.dtype,
                              name=f"{self.block.__name__}_{idx}")(
                    x, train)
                idx += 1
            # stage-boundary activation sharding (batch over dp per the
            # registered spec) — identity with no mesh in scope
            x = constrain_activation(x, "ResNet")
            endpoints[f"stage{i + 1}"] = x
        x = jnp.mean(x, axis=(1, 2))
        endpoints["pooled"] = x.astype(jnp.float32)
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          name="head")(x)
        endpoints["logits"] = logits.astype(jnp.float32)
        return endpoints

    @property
    def layer_names(self) -> list[str]:
        """Feature endpoints ordered shallow→deep, mirroring the reference's
        ``ModelSchema.layerNames`` contract (``downloader/Schema.scala``)."""
        return ([f"stage{i+1}" for i in range(len(self.stage_sizes))]
                + ["pooled", "logits"])


# Partition rules for the whole ResNet family (18/34/50/101 share the
# naming scheme). Specs are right-aligned (parallel/partition.py): a
# bare ("tp",) shards the LAST dim — a conv kernel's out-channels or a
# dense kernel's features — which is the only dim worth sharding in a
# CNN (channel counts are the 128-multiples; spatial dims are tiny).
# BatchNorm state (params AND batch_stats mean/var — the same rules
# match a full TrainState) replicates: per-channel vectors are noise
# next to one conv kernel, and replicated stats keep the EMA update
# collective-free.
from ..parallel.partition import DtypePolicy, register_partition_rules

register_partition_rules("ResNet", [
    (r"(bn_init|BatchNorm_\d+)/(scale|bias|mean|var)", ()),
    (r"conv_init/kernel", ("tp",)),
    (r"Conv_\d+/kernel", ("tp",)),
    (r"head/kernel", (None, "tp")),
    (r"head/bias", ()),
],
    # bf16 conv compute over fp32 params/BN stats; NHWC activations
    # batch-shard over dp at stage boundaries
    dtype_policy=DtypePolicy(param_dtype="float32",
                             compute_dtype="bfloat16",
                             grad_accum_dtype="float32"),
    activation_spec=("dp",))


def ResNet18(num_classes=1000, dtype=jnp.bfloat16, remat=False):
    return ResNet(stage_sizes=(2, 2, 2, 2), block=BasicBlock,
                  num_classes=num_classes, dtype=dtype, remat=remat)


def ResNet34(num_classes=1000, dtype=jnp.bfloat16, remat=False):
    return ResNet(stage_sizes=(3, 4, 6, 3), block=BasicBlock,
                  num_classes=num_classes, dtype=dtype, remat=remat)


def ResNet50(num_classes=1000, dtype=jnp.bfloat16, remat=False):
    return ResNet(stage_sizes=(3, 4, 6, 3), block=BottleneckBlock,
                  num_classes=num_classes, dtype=dtype, remat=remat)


def ResNet101(num_classes=1000, dtype=jnp.bfloat16, remat=False):
    return ResNet(stage_sizes=(3, 4, 23, 3), block=BottleneckBlock,
                  num_classes=num_classes, dtype=dtype, remat=remat)
