"""Mixture-of-Experts layer with expert parallelism over the ``ep`` axis.

No reference counterpart (SURVEY §2.14: EP absent there). Dense-dispatch
top-1 MoE: every device holds E/n local experts, receives the full token
batch (replicated), computes its experts' contributions for the tokens
routed to them, and a ``psum`` combines — router and combine are einsums
that XLA maps onto the MXU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


def init_moe_params(rng, num_experts: int, d_model: int, d_hidden: int):
    k1, k2, k3 = jax.random.split(rng, 3)
    scale = d_model ** -0.5
    return {
        "router": jax.random.normal(k1, (d_model, num_experts)) * scale,
        "w_in": jax.random.normal(
            k2, (num_experts, d_model, d_hidden)) * scale,
        "w_out": jax.random.normal(
            k3, (num_experts, d_hidden, d_model)) * (d_hidden ** -0.5),
    }


def moe_forward(params, x):
    """Single-device reference: x [T, D] → [T, D], top-1 routing."""
    logits = x @ params["router"]                     # [T, E]
    expert = jnp.argmax(logits, axis=-1)
    gate = jax.nn.softmax(logits, axis=-1)
    gate_top = jnp.take_along_axis(gate, expert[:, None], axis=1)[:, 0]
    dispatch = jax.nn.one_hot(expert, logits.shape[-1])   # [T, E]
    h = jnp.einsum("te,td,edh->teh", dispatch, x, params["w_in"])
    h = jax.nn.gelu(h)
    y = jnp.einsum("teh,ehd->td", h, params["w_out"])
    return y * gate_top[:, None]


def make_sharded_moe(mesh, *, axis: str = "ep"):
    """Expert-parallel forward: experts shard over ``axis``; tokens are
    replicated in, outputs psum-combined."""
    n = int(mesh.shape[axis])

    def local(params, x):
        # params' expert dims are local shards [E/n, ...]; the router
        # column block is this shard's experts
        shard = jax.lax.axis_index(axis)
        logits_local = x @ params["router"]           # [T, E/n]
        # global top-1 routing needs all logits: gather over the axis
        logits = jax.lax.all_gather(logits_local, axis, axis=1,
                                    tiled=True)       # [T, E]
        E = logits.shape[-1]
        e_per = E // n
        expert = jnp.argmax(logits, axis=-1)          # [T]
        gate = jax.nn.softmax(logits, axis=-1)
        gate_top = jnp.take_along_axis(gate, expert[:, None],
                                       axis=1)[:, 0]
        local_expert = expert - shard * e_per
        mine = (local_expert >= 0) & (local_expert < e_per)
        dispatch = jax.nn.one_hot(
            jnp.where(mine, local_expert, 0), e_per) \
            * mine[:, None]                           # [T, E/n]
        h = jnp.einsum("te,td,edh->teh", dispatch, x, params["w_in"])
        h = jax.nn.gelu(h)
        y = jnp.einsum("teh,ehd->td", h, params["w_out"])
        y = y * gate_top[:, None]
        return jax.lax.psum(y, axis)

    spec = {"router": P(None, axis), "w_in": P(axis),
            "w_out": P(axis)}
    return jax.shard_map(local, mesh=mesh, in_specs=(spec, P()),
                         out_specs=P(), check_vma=False)


def init_moe_blocks(rng, depth: int, d_model: int, num_experts: int,
                    d_hidden: int):
    """Per-block MoE parameter trees for ``make_moe_text_encoder``."""
    keys = jax.random.split(rng, depth)
    return [init_moe_params(k, num_experts, d_model, d_hidden)
            for k in keys]


def moe_text_encoder_forward(module, variables, moe_blocks, ids,
                             moe_apply=None):
    """The REAL TextEncoder with each block's dense feed-forward swapped
    for a top-1 MoE: embed → per block (attention residual, then
    x + MoE(ln_2 x)) → final LN + pool. ``moe_apply(params, tokens)``
    defaults to the single-device :func:`moe_forward`; pass a
    ``make_sharded_moe(mesh)`` for expert parallelism — the attention
    trunk and routing math are identical either way, which is what the
    sharded-vs-single equivalence tests assert."""
    from ..dl.text_encoder import EncoderBlock

    moe_apply = moe_apply or moe_forward
    block = EncoderBlock(module.heads, module.mlp_dim, module.width,
                         attention_fn=module.attention_fn,
                         dtype=module.dtype)
    x = module.apply(variables, ids, method="embed_ids")
    key_mask = ids != 0
    N, T = ids.shape
    W = module.width
    for i in range(module.depth):
        bvars = {"params": variables["params"][f"block{i}"]}
        x = block.apply(bvars, x, key_mask, method="attend")
        h = block.apply(bvars, x, method="pre_ffn_norm")
        y = moe_apply(moe_blocks[i],
                      h.reshape(N * T, W).astype(jnp.float32))
        x = x + y.reshape(N, T, W).astype(x.dtype)
    return module.apply(variables, x, ids, method="finalize")


def make_moe_text_encoder(mesh, module, variables, moe_blocks, *,
                          axis: str = "ep"):
    """Expert-parallel MoE text encoder: experts shard over ``axis``,
    attention stays replicated. Returns ``fn(ids) -> {"tokens",
    "pooled"}`` matching the single-device
    :func:`moe_text_encoder_forward` bit-for-bit up to psum ordering."""
    sharded = make_sharded_moe(mesh, axis=axis)

    def forward(ids):
        return moe_text_encoder_forward(module, variables, moe_blocks,
                                        ids, moe_apply=sharded)
    return forward
