"""Mixture-of-Experts layer with expert parallelism over the ``ep`` axis.

No reference counterpart (SURVEY §2.14: EP absent there). Dense-dispatch
top-1 MoE: every device holds E/n local experts, receives the full token
batch (replicated), computes its experts' contributions for the tokens
routed to them, and a ``psum`` combines — router and combine are einsums
that XLA maps onto the MXU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from ..parallel import collectives as _coll
from ..parallel.compat import shard_map as _shard_map


def init_moe_params(rng, num_experts: int, d_model: int, d_hidden: int):
    k1, k2, k3 = jax.random.split(rng, 3)
    scale = d_model ** -0.5
    return {
        "router": jax.random.normal(k1, (d_model, num_experts)) * scale,
        "w_in": jax.random.normal(
            k2, (num_experts, d_model, d_hidden)) * scale,
        "w_out": jax.random.normal(
            k3, (num_experts, d_hidden, d_model)) * (d_hidden ** -0.5),
    }


def load_balance_loss(logits, expert, valid=None):
    """Switch-Transformer auxiliary loss: ``E · Σ_e f_e · P_e`` where
    ``f_e`` is the fraction of tokens dispatched to expert e and
    ``P_e`` the mean router probability for e. Equals 1.0 at perfect
    uniformity; grows as routing collapses onto few experts. ``f`` is
    non-differentiable (argmax counts); gradients reach the router
    through ``P`` — the standard formulation.

    ``valid`` restricts both means to real tokens: pad positions embed
    identically, all route to one expert, and would otherwise dominate
    ``f`` on padded batches — the router would be trained by padding,
    not data."""
    E = logits.shape[-1]
    probs = jax.nn.softmax(logits, axis=-1)
    f = _expert_fraction(expert, E, valid)
    if valid is None:
        P = probs.mean(axis=0)
    else:
        v = valid.astype(jnp.float32)[:, None]
        P = (probs * v).sum(axis=0) / jnp.maximum(v.sum(), 1.0)
    return E * jnp.sum(f * P)


def _expert_fraction(expert, E: int, valid=None):
    """Fraction of (valid) tokens dispatched to each expert — shared by
    the balance loss and the aux output so their masking rules cannot
    diverge."""
    onehot = jax.nn.one_hot(expert, E)
    if valid is None:
        return onehot.mean(axis=0)
    v = valid.astype(jnp.float32)[:, None]
    return (onehot * v).sum(axis=0) / jnp.maximum(v.sum(), 1.0)


def _expert_positions(expert, E: int, valid=None):
    """Each token's arrival rank within its expert's queue (token
    order = batch order, the Switch first-come-first-served rule).
    ``valid`` excludes tokens (padding) from consuming queue slots —
    without it, a batch's pad positions all route to the same expert
    (identical embeddings) and can crowd real tokens past capacity."""
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)     # [T, E]
    if valid is not None:
        onehot = onehot * valid[:, None].astype(jnp.int32)
    ranks = jnp.cumsum(onehot, axis=0) - 1
    return jnp.take_along_axis(ranks, expert[:, None], axis=1)[:, 0]


def _capacity(T: int, E: int, capacity_factor: float) -> int:
    """Static per-expert token budget C = ceil(T/E · cf), clamped to T."""
    return max(1, min(T, int(np.ceil(T / E * capacity_factor))))


def _capacity_ffn(x, eid, pos, keep, w_in, w_out, C: int):
    """Sort-free capacity dispatch: kept tokens scatter into per-expert
    [E_local, C, D] buffers (unique slots by construction — ``pos`` is
    the within-expert rank), the experts run as ONE batched matmul pair
    (E_local·C·D·H FLOPs — independent of the global expert count),
    and results gather back to token order. Overflowed/foreign tokens
    contribute zero (their residual path passes through unchanged).
    Scatter/gather are differentiable, so training flows exactly like
    the dense formulation."""
    E_loc, D = w_in.shape[0], x.shape[1]
    slot = jnp.where(keep, eid * C + jnp.minimum(pos, C - 1), 0)
    contrib = jnp.where(keep[:, None], x, 0.0)
    buf = jnp.zeros((E_loc * C, D), x.dtype).at[slot].add(contrib)
    h = jax.nn.gelu(jnp.einsum(
        "ecd,edh->ech", buf.reshape(E_loc, C, D), w_in))
    y = jnp.einsum("ech,ehd->ecd", h, w_out)
    out = y.reshape(E_loc * C, -1)[slot]
    return jnp.where(keep[:, None], out, 0.0)


def moe_forward(params, x, *, return_aux: bool = False,
                capacity_factor: float | None = None, valid=None):
    """Single-device reference: x [T, D] → [T, D], top-1 routing.

    TRAINABLE end-to-end: experts get gradients through their outputs
    and the router through the chosen-expert probability multiplier
    (the Switch gating trick). ``return_aux=True`` additionally returns
    ``{"balance_loss", "expert_fraction"}`` — add ``balance_loss``
    (scaled ~1e-2) to the task loss to keep routing spread.

    ``capacity_factor=None`` (default) is the DENSE dispatch — every
    token through every expert, masked; exact, O(T·E·D·H), the
    equivalence oracle. A float switches to capacity dispatch:
    per-expert budget C = ceil(T/E · cf), tokens beyond it DROP (zero
    MoE contribution, residual unchanged), compute O(T·cf·D·H) —
    independent of E, the formulation that scales to real expert
    counts. With cf ≥ E the two are identical (no token can
    overflow). ``valid`` [T] bool marks real tokens: in capacity mode
    invalid (pad) tokens neither consume queue slots nor receive
    contributions; the dense path ignores it (pads are harmless there
    — their outputs die at the masked pool)."""
    logits = x @ params["router"]                     # [T, E]
    E = logits.shape[-1]
    expert = jnp.argmax(logits, axis=-1)
    gate = jax.nn.softmax(logits, axis=-1)
    gate_top = jnp.take_along_axis(gate, expert[:, None], axis=1)[:, 0]
    if capacity_factor is None:
        dispatch = jax.nn.one_hot(expert, E)          # [T, E]
        h = jnp.einsum("te,td,edh->teh", dispatch, x, params["w_in"])
        h = jax.nn.gelu(h)
        y = jnp.einsum("teh,ehd->td", h, params["w_out"])
    else:
        C = _capacity(x.shape[0], E, capacity_factor)
        pos = _expert_positions(expert, E, valid)
        keep = pos < C if valid is None else valid & (pos < C)
        y = _capacity_ffn(x, expert, pos, keep,
                          params["w_in"], params["w_out"], C)
    out = y * gate_top[:, None]
    if not return_aux:
        return out
    aux = {"balance_loss": load_balance_loss(logits, expert, valid),
           "expert_fraction": _expert_fraction(expert, E, valid)}
    return out, aux


def make_sharded_moe(mesh, *, axis: str = "ep",
                     return_aux: bool = False,
                     capacity_factor: float | None = None):
    """Expert-parallel forward: experts shard over ``axis``; tokens are
    replicated in, outputs psum-combined. Differentiable like the
    single-device reference (run under ``jit``); with ``return_aux``
    the replicated balance-loss aux rides out alongside.

    ``capacity_factor`` as in :func:`moe_forward`: None = dense-masked
    dispatch (exact; per-device compute O(T·E/n·D·H), scaling with the
    LOCAL expert count), a float = capacity dispatch (per-device
    compute O(T·cf/n·D·H) — independent of E, required at real expert
    widths). Routing/positions derive from the all-gathered logits, so
    every shard agrees on queue ranks and the result equals the
    single-device capacity path exactly."""
    n = int(mesh.shape[axis])

    def local(params, x, valid):
        # params' expert dims are local shards [E/n, ...]; the router
        # column block is this shard's experts
        shard = _coll.axis_index(axis)
        logits_local = x @ params["router"]           # [T, E/n]
        # global top-1 routing needs all logits: gather over the axis
        logits = _coll.allgather(logits_local, axis,
                                 gather_axis=1)       # [T, E]
        E = logits.shape[-1]
        e_per = E // n
        expert = jnp.argmax(logits, axis=-1)          # [T]
        gate = jax.nn.softmax(logits, axis=-1)
        gate_top = jnp.take_along_axis(gate, expert[:, None],
                                       axis=1)[:, 0]
        local_expert = expert - shard * e_per
        mine = (local_expert >= 0) & (local_expert < e_per)
        if capacity_factor is None:
            dispatch = jax.nn.one_hot(
                jnp.where(mine, local_expert, 0), e_per) \
                * mine[:, None]                       # [T, E/n]
            h = jnp.einsum("te,td,edh->teh", dispatch, x,
                           params["w_in"])
            h = jax.nn.gelu(h)
            y = jnp.einsum("teh,ehd->td", h, params["w_out"])
        else:
            C = _capacity(x.shape[0], E, capacity_factor)
            pos = _expert_positions(expert, E, valid)  # global ranks
            keep = mine & valid & (pos < C)
            y = _capacity_ffn(x, jnp.where(mine, local_expert, 0),
                              pos, keep, params["w_in"],
                              params["w_out"], C)
        y = y * gate_top[:, None]
        out = _coll.allreduce(y, axis)
        if not return_aux:
            return out
        # every shard holds the FULL gathered logits, so the aux is
        # computed identically everywhere — replicated by construction
        aux = {"balance_loss": load_balance_loss(logits, expert, valid),
               "expert_fraction": _expert_fraction(expert, E, valid)}
        return out, aux

    spec = {"router": P(None, axis), "w_in": P(axis),
            "w_out": P(axis)}
    out_specs = (P(), {"balance_loss": P(), "expert_fraction": P()}) \
        if return_aux else P()
    mapped = _shard_map(local, mesh=mesh, in_specs=(spec, P(), P()),
                           out_specs=out_specs, check_vma=False)

    def fn(params, x, valid=None):
        if valid is None:
            valid = jnp.ones(x.shape[0], bool)
        return mapped(params, x, valid)

    return fn


def init_moe_blocks(rng, depth: int, d_model: int, num_experts: int,
                    d_hidden: int):
    """Per-block MoE parameter trees for ``make_moe_text_encoder``."""
    keys = jax.random.split(rng, depth)
    return [init_moe_params(k, num_experts, d_model, d_hidden)
            for k in keys]


def moe_text_encoder_forward(module, variables, moe_blocks, ids,
                             moe_apply=None, *, with_aux: bool = False):
    """The REAL TextEncoder with each block's dense feed-forward swapped
    for a top-1 MoE: embed → per block (attention residual, then
    x + MoE(ln_2 x)) → final LN + pool. ``moe_apply(params, tokens)``
    defaults to the single-device :func:`moe_forward`; pass a
    ``make_sharded_moe(mesh)`` for expert parallelism — the attention
    trunk and routing math are identical either way, which is what the
    sharded-vs-single equivalence tests assert.

    ``with_aux=True``: ``moe_apply`` must be aux-returning (pass
    ``return_aux=True`` to either builder); the output dict gains
    ``balance_loss`` (mean over blocks — add it, scaled, to the task
    loss when TRAINING the MoE) and per-block ``expert_fraction``."""
    from ..dl.text_encoder import EncoderBlock

    moe_apply = moe_apply or functools.partial(moe_forward,
                                               return_aux=with_aux)
    block = EncoderBlock(module.heads, module.mlp_dim, module.width,
                         attention_fn=module.attention_fn,
                         dtype=module.dtype)
    x = module.apply(variables, ids, method="embed_ids")
    key_mask = ids != 0
    N, T = ids.shape
    W = module.width
    balance, fractions = [], []
    # pads must not consume capacity slots (capacity dispatch ranks
    # queues in flattened batch order; identical pad embeddings would
    # otherwise pile onto one expert ahead of real tokens)
    valid = key_mask.reshape(N * T)
    for i in range(module.depth):
        bvars = {"params": variables["params"][f"block{i}"]}
        x = block.apply(bvars, x, key_mask, method="attend")
        h = block.apply(bvars, x, method="pre_ffn_norm")
        y = moe_apply(moe_blocks[i],
                      h.reshape(N * T, W).astype(jnp.float32),
                      valid=valid)
        if with_aux:
            y, aux = y
            balance.append(aux["balance_loss"])
            fractions.append(aux["expert_fraction"])
        x = x + y.reshape(N, T, W).astype(x.dtype)
    out = module.apply(variables, x, ids, method="finalize")
    if with_aux:
        out["balance_loss"] = jnp.mean(jnp.stack(balance))
        out["expert_fraction"] = jnp.stack(fractions)
    return out


def make_moe_train_step(mesh, module, tx, *, axis: str = "ep",
                        balance_weight: float = 1e-2, loss_fn=None,
                        capacity_factor: float | None = 1.25):
    """Jitted expert-parallel TRAINING step for the MoE text encoder:
    (opt_state, variables, moe_blocks, ids, y) → updated (opt_state,
    variables, moe_blocks, loss, balance). Gradients flow to the
    attention trunk, the experts, AND the router (through the Switch
    gate multiplier); the load-balance aux (scaled by
    ``balance_weight``) keeps routing spread. Experts stay sharded over
    ``axis`` throughout — the optimizer update runs on the sharded
    leaves, so expert state never gathers.

    Training defaults to CAPACITY dispatch (``capacity_factor=1.25``,
    the Switch-Transformer setting): per-device expert compute is
    independent of the expert count, the formulation that scales;
    pass ``None`` for the exact dense-masked oracle."""
    import optax

    sharded = make_sharded_moe(mesh, axis=axis, return_aux=True,
                               capacity_factor=capacity_factor)
    loss_fn = loss_fn or (
        lambda pooled, t: jnp.mean((pooled.mean(-1) - t) ** 2))

    def loss_of(trainable, ids, y):
        variables, moe_blocks = trainable
        out = moe_text_encoder_forward(module, variables, moe_blocks,
                                       ids, moe_apply=sharded,
                                       with_aux=True)
        task = loss_fn(out["pooled"], y)
        return task + balance_weight * out["balance_loss"], \
            (task, out["balance_loss"])

    @jax.jit
    def step(opt_state, variables, moe_blocks, ids, y):
        (_, (task, balance)), grads = jax.value_and_grad(
            loss_of, has_aux=True)((variables, moe_blocks), ids, y)
        updates, opt_state = tx.update(grads, opt_state,
                                       (variables, moe_blocks))
        variables, moe_blocks = optax.apply_updates(
            (variables, moe_blocks), updates)
        return opt_state, variables, moe_blocks, task, balance

    return step


def make_moe_text_encoder(mesh, module, variables, moe_blocks, *,
                          axis: str = "ep",
                          capacity_factor: float | None = None):
    """Expert-parallel MoE text encoder: experts shard over ``axis``,
    attention stays replicated. Returns ``fn(ids) -> {"tokens",
    "pooled"}`` matching the single-device
    :func:`moe_text_encoder_forward` bit-for-bit up to psum ordering
    (pass the same ``capacity_factor`` to both for capacity mode)."""
    sharded = make_sharded_moe(mesh, axis=axis,
                               capacity_factor=capacity_factor)

    def forward(ids):
        return moe_text_encoder_forward(module, variables, moe_blocks,
                                        ids, moe_apply=sharded)
    return forward
