"""Checkpoint conversion: torchvision-layout ResNet weights → flax/orbax.

Fills the reference's pretrained-model supply chain
(``downloader/ModelDownloader.scala:37-60`` downloads hash-verified CNTK
graphs; ``downloader/Schema.scala`` carries the catalogue hash): here the
public pretrained source is a torchvision ``state_dict`` (``.pt``/``.pth``
pickle or an in-memory dict), converted once to an orbax checkpoint tree
under ``MMLSPARK_TPU_MODEL_DIR`` with a SHA-256 manifest that
``ModelDownloader`` verifies on every load.

Layout mapping (torchvision ResNet ↔ ``models/resnet.py``):

==========================  =====================================
torchvision                 flax (this package)
==========================  =====================================
conv1.weight                params/conv_init/kernel   (OIHW→HWIO)
bn1.{weight,bias}           params/bn_init/{scale,bias}
bn1.running_{mean,var}      batch_stats/bn_init/{mean,var}
layer<L>.<B>.conv<k>        params/<Block>_<i>/Conv_<k-1>/kernel
layer<L>.<B>.bn<k>          params/<Block>_<i>/BatchNorm_<k-1>/…
layer<L>.<B>.downsample.0   params/<Block>_<i>/Conv_<nc>/kernel
layer<L>.<B>.downsample.1   params/<Block>_<i>/BatchNorm_<nc>/…
fc.{weight,bias}            params/head/{kernel (T), bias}
==========================  =====================================

where ``i`` is the global block index (blocks auto-numbered across
stages by flax) and ``nc`` the per-block conv count (2 basic /
3 bottleneck). Strides sit on the 3×3 conv in both (torchvision's
"v1.5" ResNet), and ``resnet.py`` uses explicit symmetric padding so the
converted network is numerically identical to the torch source.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

_ARCHS = {
    # name -> (stage_sizes, block prefix, convs per block)
    "ResNet18": ((2, 2, 2, 2), "BasicBlock", 2),
    "ResNet34": ((3, 4, 6, 3), "BasicBlock", 2),
    "ResNet50": ((3, 4, 6, 3), "BottleneckBlock", 3),
    "ResNet101": ((3, 4, 23, 3), "BottleneckBlock", 3),
}


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, np.float32)


def torch_resnet_to_flax(state_dict: dict, model_name: str) -> dict:
    """torchvision ResNet ``state_dict`` → flax variables
    ``{"params": ..., "batch_stats": ...}`` for ``models.resnet``.

    Raises KeyError on missing weights (a truncated/mismatched checkpoint
    must fail loudly, like the reference's hash check).
    """
    if model_name not in _ARCHS:
        raise KeyError(f"no torchvision mapping for {model_name!r}; "
                       f"supported: {sorted(_ARCHS)}")
    stage_sizes, block_prefix, n_convs = _ARCHS[model_name]
    sd = dict(state_dict)
    params: dict = {}
    stats: dict = {}

    def conv(dst: dict, flax_name: str, torch_name: str):
        w = _np(sd.pop(torch_name + ".weight"))
        dst[flax_name] = {"kernel": w.transpose(2, 3, 1, 0)}  # OIHW→HWIO

    def bn(torch_name: str, flax_name: str, p: dict, s: dict):
        p[flax_name] = {"scale": _np(sd.pop(torch_name + ".weight")),
                        "bias": _np(sd.pop(torch_name + ".bias"))}
        s[flax_name] = {"mean": _np(sd.pop(torch_name + ".running_mean")),
                        "var": _np(sd.pop(torch_name + ".running_var"))}
        sd.pop(torch_name + ".num_batches_tracked", None)

    conv(params, "conv_init", "conv1")
    bn("bn1", "bn_init", params, stats)

    block_idx = 0
    for li, n_blocks in enumerate(stage_sizes):
        for bj in range(n_blocks):
            t = f"layer{li + 1}.{bj}"
            name = f"{block_prefix}_{block_idx}"
            bp: dict = {}
            bs: dict = {}
            for k in range(n_convs):
                conv(bp, f"Conv_{k}", f"{t}.conv{k + 1}")
                bn(f"{t}.bn{k + 1}", f"BatchNorm_{k}", bp, bs)
            if f"{t}.downsample.0.weight" in sd:
                conv(bp, f"Conv_{n_convs}", f"{t}.downsample.0")
                bn(f"{t}.downsample.1", f"BatchNorm_{n_convs}", bp, bs)
            params[name] = bp
            stats[name] = bs
            block_idx += 1

    params["head"] = {"kernel": _np(sd.pop("fc.weight")).T,
                      "bias": _np(sd.pop("fc.bias"))}
    if sd:
        leftover = sorted(sd)[:5]
        raise ValueError(
            f"{len(sd)} unconverted torch weights (first: {leftover}) — "
            "state_dict does not match the expected torchvision layout")
    return {"params": params, "batch_stats": stats}


# ------------------------------------------------------------- persistence
def _tree_sha256(tree) -> str:
    """Deterministic digest over a variables pytree (sorted key walk)."""
    h = hashlib.sha256()

    def walk(node, prefix):
        if isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], f"{prefix}/{k}")
        else:
            arr = np.asarray(node)
            h.update(prefix.encode())
            h.update(str(arr.shape).encode())
            h.update(arr.astype(np.float32).tobytes())

    walk(tree, "")
    return h.hexdigest()


def save_converted(variables: dict, model_name: str,
                   out_dir: str | None = None) -> str:
    """Write an orbax checkpoint + SHA-256 manifest under
    ``<out_dir>/<model_name>`` (out_dir defaults to
    ``MMLSPARK_TPU_MODEL_DIR``). Returns the checkpoint path."""
    out_dir = out_dir or os.environ.get("MMLSPARK_TPU_MODEL_DIR", "")
    if not out_dir:
        raise ValueError("no output dir: pass out_dir or set "
                         "MMLSPARK_TPU_MODEL_DIR")
    import orbax.checkpoint as ocp
    path = os.path.abspath(os.path.join(out_dir, model_name))
    with ocp.PyTreeCheckpointer() as ck:
        ck.save(path, variables, force=True)
    manifest = {"name": model_name, "sha256": _tree_sha256(variables)}
    with open(os.path.join(out_dir, f"{model_name}.manifest.json"),
              "w") as f:
        json.dump(manifest, f)
    return path


def verify_checkpoint(variables: dict, manifest_path: str) -> None:
    """Reference hash check (``ModelDownloader.scala:37-60``): raise on
    digest mismatch."""
    with open(manifest_path) as f:
        manifest = json.load(f)
    got = _tree_sha256(variables)
    if got != manifest["sha256"]:
        raise IOError(
            f"checkpoint hash mismatch for {manifest.get('name')}: "
            f"manifest {manifest['sha256'][:12]}…, computed {got[:12]}… — "
            "refusing corrupted/partial weights")


_VIT_ARCHS = {
    # name -> (width, depth)
    "ViT_B_16": (768, 12),
    "ViT_L_16": (1024, 24),
}


def torch_vit_to_flax(state_dict: dict, model_name: str) -> dict:
    """torchvision ViT ``state_dict`` (``vit_b_16`` layout) → flax
    variables ``{"params": ...}`` for ``models.vit``.

    Mapping: ``conv_proj`` → patchify conv (OIHW→HWIO);
    ``class_token``/``encoder.pos_embedding`` verbatim;
    per block ``encoder.layers.encoder_layer_i``:
    ``ln_1``/``ln_2`` → LayerNorm scale/bias, ``self_attention``'s fused
    ``in_proj_weight`` [3W, W] splits into q/k/v Dense kernels
    (transposed), ``out_proj`` → out Dense, ``mlp.0``/``mlp.3`` (or the
    older ``mlp.linear_1``/``linear_2``) → mlp_1/mlp_2;
    ``encoder.ln`` → final LayerNorm; ``heads.head`` → head Dense.
    Raises on missing or leftover weights, like the ResNet path.
    """
    if model_name not in _VIT_ARCHS:
        raise KeyError(f"no torchvision ViT mapping for {model_name!r}; "
                       f"supported: {sorted(_VIT_ARCHS)}")
    width, depth = _VIT_ARCHS[model_name]
    sd = dict(state_dict)
    params: dict = {}

    def dense(torch_name: str):
        return {"kernel": _np(sd.pop(torch_name + ".weight")).T,
                "bias": _np(sd.pop(torch_name + ".bias"))}

    def lnorm(torch_name: str):
        return {"scale": _np(sd.pop(torch_name + ".weight")),
                "bias": _np(sd.pop(torch_name + ".bias"))}

    w = _np(sd.pop("conv_proj.weight"))
    params["conv_proj"] = {"kernel": w.transpose(2, 3, 1, 0),
                           "bias": _np(sd.pop("conv_proj.bias"))}
    params["class_token"] = _np(sd.pop("class_token"))
    params["pos_embedding"] = _np(sd.pop("encoder.pos_embedding"))

    for i in range(depth):
        t = f"encoder.layers.encoder_layer_{i}"
        in_w = _np(sd.pop(t + ".self_attention.in_proj_weight"))
        in_b = _np(sd.pop(t + ".self_attention.in_proj_bias"))
        attn = {
            "q": {"kernel": in_w[:width].T, "bias": in_b[:width]},
            "k": {"kernel": in_w[width:2 * width].T,
                  "bias": in_b[width:2 * width]},
            "v": {"kernel": in_w[2 * width:].T, "bias": in_b[2 * width:]},
            "out": dense(t + ".self_attention.out_proj"),
        }
        mlp1_key = t + ".mlp.0" if t + ".mlp.0.weight" in sd \
            else t + ".mlp.linear_1"
        mlp2_key = t + ".mlp.3" if t + ".mlp.3.weight" in sd \
            else t + ".mlp.linear_2"
        params[f"block{i}"] = {
            "ln_1": lnorm(t + ".ln_1"), "attn": attn,
            "ln_2": lnorm(t + ".ln_2"),
            "mlp_1": dense(mlp1_key), "mlp_2": dense(mlp2_key),
        }
    params["ln"] = lnorm("encoder.ln")
    params["head"] = dense("heads.head")
    if sd:
        leftover = sorted(sd)[:5]
        raise ValueError(
            f"{len(sd)} unconverted torch weights (first: {leftover}) — "
            "state_dict does not match the expected torchvision layout")
    return {"params": params}


def torch_bert_to_flax(state_dict: dict, heads: int | None = None,
                       config=None) -> tuple[dict, dict]:
    """Foreign BERT-style ``state_dict`` (HF naming:
    ``embeddings.word_embeddings`` / ``encoder.layer.N.attention.self
    .query`` / …, with or without a leading ``bert.`` prefix) → flax
    variables for ``dl.bert.BertEncoder`` plus the inferred
    architecture kwargs.

    Every dimension is read from the weight shapes (vocab/width from
    the word embedding, depth from the layer indices, mlp_dim from the
    intermediate projection, max_len/type_vocab from their embeddings);
    ``heads`` is the one dimension a state_dict cannot carry — pass it
    explicitly, or pass ``config`` (the checkpoint's ``config.json``
    path or dict; its ``num_attention_heads`` is used). With neither,
    the ``width // 64`` BERT convention applies — WITH A WARNING,
    because a non-standard head count (e.g. MiniLM's 12 heads at width
    384) converts silently into different attention numerics than the
    source network. The pretraining head
    (``cls.*``) is dropped; any OTHER leftover key raises, like the
    vision converters (a truncated/mismatched checkpoint must fail
    loudly). Reference counterpart: ``downloader/ModelDownloader
    .scala:37-60`` (its featurizers run real downloaded weights).
    """
    sd = {}
    for k, v in state_dict.items():
        k = k[5:] if k.startswith("bert.") else k
        if k.startswith("cls."):       # masked-LM pretraining head
            continue
        sd[k] = v

    def dense(torch_name: str):
        return {"kernel": _np(sd.pop(torch_name + ".weight")).T,
                "bias": _np(sd.pop(torch_name + ".bias"))}

    def lnorm(torch_name: str):
        # older BERT exports use gamma/beta instead of weight/bias
        w = sd.pop(torch_name + ".weight", None)
        w = sd.pop(torch_name + ".gamma") if w is None else w
        b = sd.pop(torch_name + ".bias", None)
        b = sd.pop(torch_name + ".beta") if b is None else b
        return {"scale": _np(w), "bias": _np(b)}

    word = _np(sd.pop("embeddings.word_embeddings.weight"))
    pos = _np(sd.pop("embeddings.position_embeddings.weight"))
    typ = _np(sd.pop("embeddings.token_type_embeddings.weight"))
    sd.pop("embeddings.position_ids", None)   # a buffer, not a weight
    vocab, width = word.shape
    depth = 1 + max((int(k.split(".")[2]) for k in sd
                     if k.startswith("encoder.layer.")), default=-1)
    if depth <= 0:
        raise ValueError("state_dict has no encoder.layer.* weights — "
                         "not a BERT-style checkpoint")
    params: dict = {
        "word": {"embedding": word},
        "pos": {"embedding": pos},
        "type": {"embedding": typ},
        "embed_ln": lnorm("embeddings.LayerNorm"),
    }
    mlp_dim = None
    for i in range(depth):
        t = f"encoder.layer.{i}"
        blk = {
            "q": dense(t + ".attention.self.query"),
            "k": dense(t + ".attention.self.key"),
            "v": dense(t + ".attention.self.value"),
            "out": dense(t + ".attention.output.dense"),
            "ln_att": lnorm(t + ".attention.output.LayerNorm"),
            "mlp_1": dense(t + ".intermediate.dense"),
            "mlp_2": dense(t + ".output.dense"),
            "ln_ffn": lnorm(t + ".output.LayerNorm"),
        }
        mlp_dim = blk["mlp_1"]["kernel"].shape[1]
        params[f"block{i}"] = blk
    has_pooler = "pooler.dense.weight" in sd
    if has_pooler:
        params["pooler"] = dense("pooler.dense")
    if sd:
        leftover = sorted(sd)[:5]
        raise ValueError(
            f"{len(sd)} unconverted torch weights (first: {leftover}) — "
            "state_dict does not match the expected BERT layout")
    if heads is None and config is not None:
        if isinstance(config, (str, os.PathLike)):
            with open(config) as f:
                config = json.load(f)
        heads = config.get("num_attention_heads")
    if heads is None:
        import warnings
        heads = max(width // 64, 1)
        warnings.warn(
            f"head count not provided — assuming {heads} "
            f"(width {width} / 64, the BERT convention). A checkpoint "
            "with a different head count would convert into DIFFERENT "
            "attention numerics with no error; pass heads= or "
            "config=<config.json> to be exact.", stacklevel=2)
    arch = dict(vocab=int(vocab), width=int(width), depth=int(depth),
                heads=int(heads),
                mlp_dim=int(mlp_dim), max_len=int(pos.shape[0]),
                type_vocab=int(typ.shape[0]), pooler=has_pooler)
    if arch["width"] % arch["heads"] != 0:
        raise ValueError(f"heads={arch['heads']} must divide "
                         f"width={arch['width']}")
    return {"params": params}, arch


def bert_encoder_from_torch(state_dict: dict, heads: int | None = None,
                            config=None):
    """One-call ingestion: foreign BERT ``state_dict`` → ``(module,
    variables)`` ready for ``TextEncoderFeaturizer(model=...)`` or zoo
    publication via :func:`save_converted` +
    ``models.register_bert_encoder``."""
    from ..dl.bert import BertEncoder
    variables, arch = torch_bert_to_flax(state_dict, heads, config)
    return BertEncoder(**arch), variables


def torch_to_flax(state_dict: dict, model_name: str) -> dict:
    """Dispatch to the family converter by zoo model name."""
    if model_name in _VIT_ARCHS:
        return torch_vit_to_flax(state_dict, model_name)
    return torch_resnet_to_flax(state_dict, model_name)


def convert_torch_checkpoint(src, model_name: str,
                             out_dir: str | None = None) -> str:
    """One-call conversion: torch ``.pt``/``.pth`` path (or a state_dict)
    → verified orbax checkpoint. Returns the checkpoint path."""
    if isinstance(src, (str, os.PathLike)):
        import torch
        obj = torch.load(src, map_location="cpu", weights_only=True)
        state_dict = obj.get("state_dict", obj) if isinstance(obj, dict) \
            else obj
    else:
        state_dict = src
    variables = torch_to_flax(state_dict, model_name)
    return save_converted(variables, model_name, out_dir)


def _main(argv):
    """CLI: ``python -m mmlspark_tpu.models.convert <src.pt[h]> <name>
    [out_dir]`` — one-step torchvision→orbax conversion with manifest,
    e.g. ``... resnet50-0676ba61.pth ResNet50``. Point
    ``MMLSPARK_TPU_MODEL_DIR`` at the output to serve the weights."""
    if len(argv) < 2:
        print(_main.__doc__)
        return 2
    path = convert_torch_checkpoint(
        argv[0], argv[1], argv[2] if len(argv) > 2 else None)
    print(f"converted {argv[1]} -> {path}")
    return 0


if __name__ == "__main__":
    import sys
    raise SystemExit(_main(sys.argv[1:]))
