"""Model registry + downloader.

Reference: ``downloader/ModelDownloader.scala`` + ``downloader/Schema.scala``
— a catalogue of pretrained CNNs (``ModelSchema``: uri, hash, inputNode,
numLayers, layerNames) fetched from Azure blob with hash verification and
retry (``FaultToleranceUtils.retryWithTimeout``,
``ModelDownloader.scala:37-60``).

TPU-native version: the schema survives; weights come from a local path or
an orbax checkpoint. In a zero-egress build remote URIs are gated — models
not found locally are initialized from the flax init (random weights), which
keeps every downstream pipeline runnable and shape-correct; swap in real
checkpoints by pointing ``MMLSPARK_TPU_MODEL_DIR`` at a checkpoint tree.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Any, Callable

import jax
import numpy as np

from ..core.utils import retry_with_timeout


@dataclasses.dataclass
class ModelSchema:
    """Catalogue entry (reference ``downloader/Schema.scala``)."""
    name: str
    dataset: str = "ImageNet"
    model_type: str = "image"
    uri: str | None = None
    hash: str | None = None
    input_node: str = "image"
    num_layers: int = 0
    layer_names: tuple[str, ...] = ()
    input_size: int = 224
    num_classes: int = 1000
    builder: Callable[..., Any] | None = None


_REGISTRY: dict[str, ModelSchema] = {}


def register_model(schema: ModelSchema) -> ModelSchema:
    _REGISTRY[schema.name] = schema
    return schema


def _register_builtins():
    from .resnet import ResNet18, ResNet34, ResNet50, ResNet101
    for name, builder, layers in [
            ("ResNet18", ResNet18, 18), ("ResNet34", ResNet34, 34),
            ("ResNet50", ResNet50, 50), ("ResNet101", ResNet101, 101)]:
        register_model(ModelSchema(
            name=name, num_layers=layers, builder=builder,
            layer_names=("stage1", "stage2", "stage3", "stage4",
                         "pooled", "logits")))
    from .vit import ViT_B_16, ViT_L_16
    for name, builder, depth in [("ViT_B_16", ViT_B_16, 12),
                                 ("ViT_L_16", ViT_L_16, 24)]:
        register_model(ModelSchema(
            name=name, num_layers=depth, builder=builder,
            layer_names=tuple(f"block{i + 1}" for i in range(depth))
            + ("pooled", "logits")))
    # default text entry: the in-framework pretraining target
    # (dl/pretrain.py) — the text counterpart of the CNN catalogue
    register_text_encoder("TextEncoderBase", vocab=32768, width=256,
                          depth=4, heads=8, mlp_dim=1024)


class _TextEncoderBuilder:
    """Picklable text-encoder factory (a closure here would break
    ComplexParam persistence of any stage holding the LoadedModel —
    e.g. ``TextEncoderFeaturizer(model=...).save()``)."""

    def __init__(self, vocab: int, width: int, depth: int, heads: int,
                 mlp_dim: int):
        self.vocab, self.width, self.depth = vocab, width, depth
        self.heads, self.mlp_dim = heads, mlp_dim

    def __call__(self, **kwargs):
        from ..dl.text_encoder import TextEncoder
        return TextEncoder(vocab=self.vocab, width=self.width,
                           depth=self.depth, heads=self.heads,
                           mlp_dim=self.mlp_dim, **kwargs)


def register_text_encoder(name: str, *, vocab: int, width: int,
                          depth: int, heads: int,
                          mlp_dim: int | None = None,
                          seq_len: int = 128) -> ModelSchema:
    """Register a text-encoder catalogue entry. The reference catalogue
    is CNN-only (``downloader/Schema.scala``); text entries carry the
    encoder hyperparameters so a zoo checkpoint (e.g. from
    ``dl.pretrain.pretrain_masked_lm`` + ``models.convert
    .save_converted``) reloads into the exact architecture that
    produced it. ``seq_len`` only sizes the random-init dummy."""
    return register_model(ModelSchema(
        name=name, dataset="custom", model_type="text",
        num_layers=depth, input_node="tokens", input_size=seq_len,
        num_classes=0,
        builder=_TextEncoderBuilder(vocab, width, depth, heads,
                                    mlp_dim or 4 * width),
        layer_names=tuple(f"block{i}" for i in range(depth))
        + ("tokens", "pooled")))


_register_builtins()


class _BertEncoderBuilder:
    """Picklable BERT-encoder factory (mirrors ``_TextEncoderBuilder``
    — a closure would break ComplexParam persistence)."""

    def __init__(self, **arch):
        self.arch = dict(arch)

    def __call__(self, **kwargs):
        from ..dl.bert import BertEncoder
        return BertEncoder(**self.arch, **kwargs)


def register_bert_encoder(name: str, *, vocab: int, width: int,
                          depth: int, heads: int, mlp_dim: int,
                          max_len: int = 512, type_vocab: int = 2,
                          pooler: bool = True,
                          seq_len: int = 128) -> ModelSchema:
    """Register an ingested-BERT catalogue entry (the text counterpart
    of the reference's downloaded-CNTK-model entries,
    ``downloader/Schema.scala``): a foreign checkpoint converted by
    ``models.convert.torch_bert_to_flax`` + ``save_converted`` reloads
    into the exact BERT architecture that produced it."""
    return register_model(ModelSchema(
        name=name, dataset="custom", model_type="text",
        num_layers=depth, input_node="tokens",
        # clamp: the random-init dummy must fit the checkpoint's
        # learned position table or module.init raises
        input_size=min(seq_len, max_len),
        num_classes=0,
        builder=_BertEncoderBuilder(vocab=vocab, width=width,
                                    depth=depth, heads=heads,
                                    mlp_dim=mlp_dim, max_len=max_len,
                                    type_vocab=type_vocab,
                                    pooler=pooler),
        layer_names=tuple(f"block{i}" for i in range(depth))
        + ("tokens", "pooled", "cls")))


def get_model(name: str) -> ModelSchema:
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


@dataclasses.dataclass
class LoadedModel:
    """A model ready for inference: module + variables + schema."""
    schema: ModelSchema
    module: Any
    variables: dict

    @property
    def layer_names(self) -> list[str]:
        return list(self.schema.layer_names)


class ModelDownloader:
    """Resolve a catalogue model to weights (reference
    ``ModelDownloader.downloadByName``). Local checkpoint dir → orbax
    restore; otherwise deterministic random init (zero-egress fallback).
    """

    def __init__(self, local_dir: str | None = None):
        self.local_dir = local_dir or os.environ.get(
            "MMLSPARK_TPU_MODEL_DIR", "")

    def download_by_name(self, name: str, *, num_classes: int | None = None,
                         dtype=None, remat: bool | None = None,
                         allow_random_init: bool | None = None) -> LoadedModel:
        """Resolve ``name`` to a ready model.

        ``remat``: rematerialize blocks in the backward
        (``jax.checkpoint``) — the fine-tune memory lever; param names
        are unchanged, so checkpoints load identically.

        ``allow_random_init``: when no checkpoint is found locally, True
        falls back to deterministic random init (useful for shape checks
        and architecture tests); False raises; None (default) reads the
        ``MMLSPARK_TPU_ALLOW_RANDOM_INIT`` env toggle (default allow,
        with a warning). The reference fails loudly when its download
        cannot be verified (``ModelDownloader.scala:37-60``).
        """
        schema = get_model(name)
        kwargs = {}
        if num_classes is not None:
            kwargs["num_classes"] = num_classes
        if dtype is not None:
            kwargs["dtype"] = dtype
        if remat is not None:
            # the fine-tune memory lever (ResNet/ViT/TextEncoder remat
            # flags); param names are unchanged, so checkpoints load
            # identically whether or not blocks rematerialize
            kwargs["remat"] = remat
        module = schema.builder(**kwargs)
        variables = self._load_or_init(schema, module, allow_random_init)
        return LoadedModel(schema=schema, module=module, variables=variables)

    # -- weights ------------------------------------------------------------
    def _ckpt_path(self, schema: ModelSchema) -> str | None:
        if not self.local_dir:
            return None
        path = os.path.join(self.local_dir, schema.name)
        return path if os.path.isdir(path) else None

    def _load_or_init(self, schema: ModelSchema, module,
                      allow_random_init: bool | None = None) -> dict:
        path = self._ckpt_path(schema)
        if path:
            def restore():
                import orbax.checkpoint as ocp
                with ocp.PyTreeCheckpointer() as ck:
                    return ck.restore(path)
            # reference retries downloads with backoff; hash verification
            # is deterministic, so it runs once OUTSIDE the retry loop
            variables = retry_with_timeout(restore, backoffs_ms=(0, 100, 200))
            manifest = os.path.join(self.local_dir,
                                    f"{schema.name}.manifest.json")
            if os.path.exists(manifest):
                # reference verifies the downloaded artifact's hash
                # (ModelDownloader.scala:37-60); corrupted weights fail loud
                from .convert import verify_checkpoint
                verify_checkpoint(variables, manifest)
            return variables
        if allow_random_init is None:
            allow_random_init = os.environ.get(
                "MMLSPARK_TPU_ALLOW_RANDOM_INIT", "1") != "0"
            if allow_random_init:
                import warnings
                warnings.warn(
                    f"no checkpoint for {schema.name!r} under "
                    f"{self.local_dir or '<unset MMLSPARK_TPU_MODEL_DIR>'}; "
                    "initializing RANDOM weights (shape-correct, not "
                    "pretrained). Pass allow_random_init=True to silence, "
                    "or point MMLSPARK_TPU_MODEL_DIR at a checkpoint tree.",
                    stacklevel=3)
        if not allow_random_init:
            raise FileNotFoundError(
                f"no local checkpoint for model {schema.name!r} "
                f"(looked under {self.local_dir or '<unset>'}) and "
                "allow_random_init is False; convert weights with "
                "mmlspark_tpu.models.convert and set MMLSPARK_TPU_MODEL_DIR")
        rng = jax.random.PRNGKey(
            int(hashlib.md5(schema.name.encode()).hexdigest()[:8], 16))
        if schema.model_type == "text":
            dummy = np.zeros((1, schema.input_size), np.int32)
        else:
            dummy = np.zeros((1, schema.input_size, schema.input_size, 3),
                             np.float32)
        # init on host CPU when available: jitting module.init through a
        # remote-compile TPU tunnel is slow and can wedge; weights move to
        # device on first jitted apply (or an explicit device_put).
        # JAX_PLATFORMS may exclude cpu, in which case use the default.
        import contextlib
        try:
            ctx = jax.default_device(jax.local_devices(backend="cpu")[0])
        except RuntimeError:
            ctx = contextlib.nullcontext()
        with ctx:
            return jax.jit(module.init, static_argnums=2)(rng, dummy, False)
