"""Model registry + downloader.

Reference: ``downloader/ModelDownloader.scala`` + ``downloader/Schema.scala``
— a catalogue of pretrained CNNs (``ModelSchema``: uri, hash, inputNode,
numLayers, layerNames) fetched from Azure blob with hash verification and
retry (``FaultToleranceUtils.retryWithTimeout``,
``ModelDownloader.scala:37-60``).

TPU-native version: the schema survives; weights come from a local path or
an orbax checkpoint. In a zero-egress build remote URIs are gated — models
not found locally are initialized from the flax init (random weights), which
keeps every downstream pipeline runnable and shape-correct; swap in real
checkpoints by pointing ``MMLSPARK_TPU_MODEL_DIR`` at a checkpoint tree.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Any, Callable

import jax
import numpy as np

from ..core.utils import retry_with_timeout


@dataclasses.dataclass
class ModelSchema:
    """Catalogue entry (reference ``downloader/Schema.scala``)."""
    name: str
    dataset: str = "ImageNet"
    model_type: str = "image"
    uri: str | None = None
    hash: str | None = None
    input_node: str = "image"
    num_layers: int = 0
    layer_names: tuple[str, ...] = ()
    input_size: int = 224
    num_classes: int = 1000
    builder: Callable[..., Any] | None = None


_REGISTRY: dict[str, ModelSchema] = {}


def register_model(schema: ModelSchema) -> ModelSchema:
    _REGISTRY[schema.name] = schema
    return schema


def _register_builtins():
    from .resnet import ResNet18, ResNet34, ResNet50, ResNet101
    for name, builder, layers in [
            ("ResNet18", ResNet18, 18), ("ResNet34", ResNet34, 34),
            ("ResNet50", ResNet50, 50), ("ResNet101", ResNet101, 101)]:
        register_model(ModelSchema(
            name=name, num_layers=layers, builder=builder,
            layer_names=("stage1", "stage2", "stage3", "stage4",
                         "pooled", "logits")))


_register_builtins()


def get_model(name: str) -> ModelSchema:
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


@dataclasses.dataclass
class LoadedModel:
    """A model ready for inference: module + variables + schema."""
    schema: ModelSchema
    module: Any
    variables: dict

    @property
    def layer_names(self) -> list[str]:
        return list(self.schema.layer_names)


class ModelDownloader:
    """Resolve a catalogue model to weights (reference
    ``ModelDownloader.downloadByName``). Local checkpoint dir → orbax
    restore; otherwise deterministic random init (zero-egress fallback).
    """

    def __init__(self, local_dir: str | None = None):
        self.local_dir = local_dir or os.environ.get(
            "MMLSPARK_TPU_MODEL_DIR", "")

    def download_by_name(self, name: str, *, num_classes: int | None = None,
                         dtype=None) -> LoadedModel:
        schema = get_model(name)
        kwargs = {}
        if num_classes is not None:
            kwargs["num_classes"] = num_classes
        if dtype is not None:
            kwargs["dtype"] = dtype
        module = schema.builder(**kwargs)
        variables = self._load_or_init(schema, module)
        return LoadedModel(schema=schema, module=module, variables=variables)

    # -- weights ------------------------------------------------------------
    def _ckpt_path(self, schema: ModelSchema) -> str | None:
        if not self.local_dir:
            return None
        path = os.path.join(self.local_dir, schema.name)
        return path if os.path.isdir(path) else None

    def _load_or_init(self, schema: ModelSchema, module) -> dict:
        path = self._ckpt_path(schema)
        if path:
            def restore():
                import orbax.checkpoint as ocp
                with ocp.PyTreeCheckpointer() as ck:
                    return ck.restore(path)
            # reference retries downloads with backoff
            return retry_with_timeout(restore, retries=3)
        rng = jax.random.PRNGKey(
            int(hashlib.md5(schema.name.encode()).hexdigest()[:8], 16))
        dummy = np.zeros((1, schema.input_size, schema.input_size, 3),
                         np.float32)
        return jax.jit(module.init, static_argnums=2)(rng, dummy, False)
