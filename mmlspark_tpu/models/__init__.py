"""Model zoo: flax models with named intermediate layers.

Replaces the reference's CNTK model zoo — pretrained CNNs fetched by
``ModelDownloader`` (``downloader/ModelDownloader.scala``) and evaluated
through JNI (``cntk/CNTKModel.scala``). Here models are flax modules whose
forward pass returns every named layer, so ``ImageFeaturizer``'s
``cutOutputLayers`` (``image/ImageFeaturizer.scala:137-184``) is a dict
lookup rather than graph surgery.
"""

from .quantize import (quantization_fidelity, quantize_resnet,
                       quantize_text_encoder)
from .resnet import ResNet, ResNet18, ResNet34, ResNet50, ResNet101
from .zoo import (ModelSchema, ModelDownloader, get_model,
                  register_model, register_bert_encoder,
                  register_text_encoder)

__all__ = ["ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101",
           "ModelSchema", "ModelDownloader", "get_model",
           "register_model", "register_bert_encoder",
           "register_text_encoder", "quantize_resnet",
           "quantize_text_encoder", "quantization_fidelity"]
