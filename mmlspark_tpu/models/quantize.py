"""Post-training int8 quantization for the ResNet scoring path.

The v5e MXU runs int8 at twice the bf16 rate (394 TOPS vs 197 TFLOPS),
and inference-only feature extraction — the reference's north-star
``ImageFeaturizer`` workload (``image/ImageFeaturizer.scala:40-60``) —
is exactly the place to spend that: no gradients, BN statistics frozen,
and the pooled feature is robust to 8-bit weight error.

Scheme (standard w8a8-dynamic):
- BatchNorm FOLDS into the preceding conv (inference-only identity:
  ``w' = w·γ/√(σ²+ε)``, ``b' = β − μ·γ/√(σ²+ε)``), so the quantized
  graph has no normalization ops at all.
- Weights: per-OUTPUT-CHANNEL symmetric int8 (``s_c = max|w_c|/127``).
- Activations: per-TENSOR symmetric int8 with a DYNAMIC scale computed
  on device per batch (one max-reduction — cheap next to the conv).
- Accumulation in int32, dequantized as ``y·(s_x·s_c) + b`` in f32;
  residual adds, relu, and pooling stay in f32.

The quantized forward is a plain function over a folded/quantized
param pytree — not a flax module — so it jits to ONE program with no
framework overhead. Fidelity vs the f32 model is asserted by test
(cosine > 0.99 on the pooled features) and reported by the bench row
next to the speedup.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-5


def _fold(conv_params, bn_params, bn_stats):
    """Fold a BatchNorm into its preceding bias-free conv."""
    w = conv_params["kernel"].astype(jnp.float32)      # [kh,kw,ci,co]
    gamma = bn_params["scale"].astype(jnp.float32)
    beta = bn_params["bias"].astype(jnp.float32)
    mean = bn_stats["mean"].astype(jnp.float32)
    var = bn_stats["var"].astype(jnp.float32)
    inv = gamma / jnp.sqrt(var + _EPS)
    return w * inv[None, None, None, :], beta - mean * inv


def _quant_w(w):
    """Per-output-channel symmetric int8: (w_q int8, scale f32[co])."""
    s = jnp.max(jnp.abs(w), axis=(0, 1, 2)) / 127.0
    s = jnp.maximum(s, 1e-12)
    wq = jnp.clip(jnp.round(w / s[None, None, None, :]),
                  -127, 127).astype(jnp.int8)
    return wq, s


def _qconv(x, wq, s_w, b, *, strides, padding):
    """int8 conv with dynamic per-tensor activation scale; f32 out."""
    s_x = jnp.maximum(jnp.max(jnp.abs(x)) / 127.0, 1e-12)
    xq = jnp.clip(jnp.round(x / s_x), -127, 127).astype(jnp.int8)
    y = jax.lax.conv_general_dilated(
        xq, wq, strides, padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * (s_x * s_w)[None, None, None, :] \
        + b[None, None, None, :]


_PAD3 = ((1, 1), (1, 1))
_PAD7 = ((3, 3), (3, 3))
_PAD0 = ((0, 0), (0, 0))


def _block_layout(block_name: str, n_conv: int):
    """(strides, padding) per conv index for a basic/bottleneck block;
    the last conv (if beyond the mains) is the 1x1 downsample."""
    if block_name == "BasicBlock":
        mains = [(None, _PAD3), ((1, 1), _PAD3)]   # stride on conv 0
    else:
        mains = [((1, 1), _PAD0), (None, _PAD3), ((1, 1), _PAD0)]
    return mains, n_conv > len(mains)


def quantize_resnet(module, variables) -> tuple[Any, Any]:
    """Fold + quantize a fitted/converted ResNet; returns
    ``(q_forward, qparams)`` with ``q_forward(qparams, images_f32) ->
    pooled [N, C] f32`` (the ImageFeaturizer feature vector).

    ``module`` must be a ``models.resnet.ResNet``; any of the zoo's
    ResNet-18/34/50/101 work (both block types)."""
    params = variables["params"]
    if "batch_stats" not in variables:
        raise ValueError(
            "quantize_resnet folds BatchNorm from running statistics "
            "— pass the full variables dict (params + batch_stats), "
            "not a params-only tree")
    stats = variables["batch_stats"]
    block_name = module.block.__name__
    q: dict = {}
    w, b = _fold(params["conv_init"], params["bn_init"],
                 stats["bn_init"])
    q["conv_init"] = (*_quant_w(w), b)

    n_blocks = sum(module.stage_sizes)
    blocks = []
    for i in range(n_blocks):
        bp = params[f"{block_name}_{i}"]
        bs = stats[f"{block_name}_{i}"]
        convs = sorted(k for k in bp if k.startswith("Conv_"))
        qconvs = []
        for k in convs:
            j = k.split("_")[1]
            w, bias = _fold(bp[k], bp[f"BatchNorm_{j}"],
                            bs[f"BatchNorm_{j}"])
            qconvs.append((*_quant_w(w), bias))
        blocks.append(qconvs)
    q["blocks"] = blocks
    # the dense head stays OUT: the featurizer's endpoint of record is
    # the POOLED vector before it, and carrying unread head params
    # would cost ~8 MB of device transfer per ResNet-50 for nothing

    stage_sizes = tuple(module.stage_sizes)

    def q_forward(qp, x):
        x = jnp.asarray(x, jnp.float32)
        wq, sw, bias = qp["conv_init"]
        x = jax.nn.relu(_qconv(x, wq, sw, bias, strides=(2, 2),
                               padding=_PAD7))
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
            ((0, 0), (1, 1), (1, 1), (0, 0)))
        idx = 0
        for i, nb in enumerate(stage_sizes):
            for j in range(nb):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                qconvs = qp["blocks"][idx]
                mains, has_down = _block_layout(block_name,
                                                len(qconvs))
                residual = x
                y = x
                for ci, (st, pad) in enumerate(mains):
                    wq, sw, bias = qconvs[ci]
                    y = _qconv(y, wq, sw, bias,
                               strides=st or strides, padding=pad)
                    if ci < len(mains) - 1:
                        y = jax.nn.relu(y)
                if has_down:
                    wq, sw, bias = qconvs[-1]
                    residual = _qconv(residual, wq, sw, bias,
                                      strides=strides, padding=_PAD0)
                x = jax.nn.relu(y + residual)
                idx += 1
        return jnp.mean(x, axis=(1, 2))

    return q_forward, q


def quantization_fidelity(module, variables, q_forward, qparams,
                          images) -> float:
    """Mean cosine similarity between f32 and int8 pooled features —
    the number the bench row reports next to the speedup."""
    ref = module.apply(variables, jnp.asarray(images))["pooled"]
    got = q_forward(qparams, images)
    ref = np.asarray(ref, np.float64)
    got = np.asarray(got, np.float64)
    num = (ref * got).sum(-1)
    den = np.linalg.norm(ref, axis=-1) * np.linalg.norm(got, axis=-1)
    return float((num / np.maximum(den, 1e-12)).mean())
