"""Post-training int8 quantization for the ResNet scoring path.

The v5e MXU runs int8 at twice the bf16 rate (394 TOPS vs 197 TFLOPS),
and inference-only feature extraction — the reference's north-star
``ImageFeaturizer`` workload (``image/ImageFeaturizer.scala:40-60``) —
is exactly the place to spend that: no gradients, BN statistics frozen,
and the pooled feature is robust to 8-bit weight error.

Scheme (standard w8a8-dynamic):
- BatchNorm FOLDS into the preceding conv (inference-only identity:
  ``w' = w·γ/√(σ²+ε)``, ``b' = β − μ·γ/√(σ²+ε)``), so the quantized
  graph has no normalization ops at all.
- Weights: per-OUTPUT-CHANNEL symmetric int8 (``s_c = max|w_c|/127``).
- Activations: per-ROW symmetric int8 with a DYNAMIC scale computed on
  device (max over the non-batch axes — one reduction, cheap next to
  the conv). Per-row, NOT per-tensor: a whole-batch max would let one
  outlier row squeeze the int8 range of every other row, making a
  quantized row's features depend on its minibatch neighbors (and on
  miniBatchSize) — the f32 path is row-independent and the quantized
  path must match (ADVICE round-5).
- Accumulation in int32, dequantized as ``y·(s_x·s_c) + b`` in f32;
  residual adds, relu, and pooling stay in f32.

The quantized forward is a plain function over a folded/quantized
param pytree — not a flax module — so it jits to ONE program with no
framework overhead. Fidelity vs the f32 model is asserted by test
(cosine > 0.99 on the pooled features) and reported by the bench row
next to the speedup.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-5


def _fold(conv_params, bn_params, bn_stats):
    """Fold a BatchNorm into its preceding bias-free conv."""
    w = conv_params["kernel"].astype(jnp.float32)      # [kh,kw,ci,co]
    gamma = bn_params["scale"].astype(jnp.float32)
    beta = bn_params["bias"].astype(jnp.float32)
    mean = bn_stats["mean"].astype(jnp.float32)
    var = bn_stats["var"].astype(jnp.float32)
    inv = gamma / jnp.sqrt(var + _EPS)
    return w * inv[None, None, None, :], beta - mean * inv


def _quant_w(w):
    """Per-output-channel symmetric int8: (w_q int8, scale f32[co])."""
    s = jnp.max(jnp.abs(w), axis=(0, 1, 2)) / 127.0
    s = jnp.maximum(s, 1e-12)
    wq = jnp.clip(jnp.round(w / s[None, None, None, :]),
                  -127, 127).astype(jnp.int8)
    return wq, s


def _qconv(x, wq, s_w, b, *, strides, padding):
    """int8 conv with dynamic per-row activation scale; f32 out."""
    s_x = jnp.maximum(
        jnp.max(jnp.abs(x), axis=(1, 2, 3)) / 127.0, 1e-12)  # [N]
    xq = jnp.clip(jnp.round(x / s_x[:, None, None, None]),
                  -127, 127).astype(jnp.int8)
    y = jax.lax.conv_general_dilated(
        xq, wq, strides, padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) \
        * (s_x[:, None, None, None] * s_w[None, None, None, :]) \
        + b[None, None, None, :]


_PAD3 = ((1, 1), (1, 1))
_PAD7 = ((3, 3), (3, 3))
_PAD0 = ((0, 0), (0, 0))


def _block_layout(block_name: str, n_conv: int):
    """(strides, padding) per conv index for a basic/bottleneck block;
    the last conv (if beyond the mains) is the 1x1 downsample."""
    if block_name == "BasicBlock":
        mains = [(None, _PAD3), ((1, 1), _PAD3)]   # stride on conv 0
    else:
        mains = [((1, 1), _PAD0), (None, _PAD3), ((1, 1), _PAD0)]
    return mains, n_conv > len(mains)


def quantize_resnet(module, variables) -> tuple[Any, Any]:
    """Fold + quantize a fitted/converted ResNet; returns
    ``(q_forward, qparams)`` with ``q_forward(qparams, images_f32) ->
    pooled [N, C] f32`` (the ImageFeaturizer feature vector).

    ``module`` must be a ``models.resnet.ResNet``; any of the zoo's
    ResNet-18/34/50/101 work (both block types)."""
    params = variables["params"]
    if "batch_stats" not in variables:
        raise ValueError(
            "quantize_resnet folds BatchNorm from running statistics "
            "— pass the full variables dict (params + batch_stats), "
            "not a params-only tree")
    stats = variables["batch_stats"]
    block_name = module.block.__name__
    q: dict = {}
    w, b = _fold(params["conv_init"], params["bn_init"],
                 stats["bn_init"])
    q["conv_init"] = (*_quant_w(w), b)

    n_blocks = sum(module.stage_sizes)
    blocks = []
    for i in range(n_blocks):
        bp = params[f"{block_name}_{i}"]
        bs = stats[f"{block_name}_{i}"]
        convs = sorted(k for k in bp if k.startswith("Conv_"))
        qconvs = []
        for k in convs:
            j = k.split("_")[1]
            w, bias = _fold(bp[k], bp[f"BatchNorm_{j}"],
                            bs[f"BatchNorm_{j}"])
            qconvs.append((*_quant_w(w), bias))
        blocks.append(qconvs)
    q["blocks"] = blocks
    # the dense head stays OUT: the featurizer's endpoint of record is
    # the POOLED vector before it, and carrying unread head params
    # would cost ~8 MB of device transfer per ResNet-50 for nothing

    stage_sizes = tuple(module.stage_sizes)

    def q_forward(qp, x):
        x = jnp.asarray(x, jnp.float32)
        wq, sw, bias = qp["conv_init"]
        x = jax.nn.relu(_qconv(x, wq, sw, bias, strides=(2, 2),
                               padding=_PAD7))
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
            ((0, 0), (1, 1), (1, 1), (0, 0)))
        idx = 0
        for i, nb in enumerate(stage_sizes):
            for j in range(nb):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                qconvs = qp["blocks"][idx]
                mains, has_down = _block_layout(block_name,
                                                len(qconvs))
                residual = x
                y = x
                for ci, (st, pad) in enumerate(mains):
                    wq, sw, bias = qconvs[ci]
                    y = _qconv(y, wq, sw, bias,
                               strides=st or strides, padding=pad)
                    if ci < len(mains) - 1:
                        y = jax.nn.relu(y)
                if has_down:
                    wq, sw, bias = qconvs[-1]
                    residual = _qconv(residual, wq, sw, bias,
                                      strides=strides, padding=_PAD0)
                x = jax.nn.relu(y + residual)
                idx += 1
        return jnp.mean(x, axis=(1, 2))

    return q_forward, q


def cosine_fidelity(a, b) -> float:
    """Mean row-wise cosine similarity — the ONE copy of the fidelity
    arithmetic (tests and benches must not re-derive it)."""
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    num = (a * b).sum(-1)
    den = np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1)
    return float((num / np.maximum(den, 1e-12)).mean())


def quantization_fidelity(module, variables, q_forward, qparams,
                          images) -> float:
    """Mean cosine similarity between f32 and int8 pooled features —
    the number the bench row reports next to the speedup."""
    ref = module.apply(variables, jnp.asarray(images))["pooled"]
    return cosine_fidelity(ref, q_forward(qparams, images))


def _quant_dense_w(w):
    """Per-output-column symmetric int8 for a dense kernel [in, out]."""
    s = jnp.max(jnp.abs(w), axis=0) / 127.0
    s = jnp.maximum(s, 1e-12)
    wq = jnp.clip(jnp.round(w / s[None, :]), -127, 127).astype(jnp.int8)
    return wq, s


def _qdense(x, wq, s_w, b):
    """int8 matmul with dynamic per-row activation scale; f32 out.
    x [N, ..., in] f32/bf16 → [N, ..., out] f32 (scale is max over the
    non-batch axes, so row outputs are minibatch-independent)."""
    if x.ndim < 2:
        # 1-D input has no non-batch axes: the per-row max degenerates
        # to a per-element scale and every value quantizes to ±127 —
        # fail loudly instead
        raise ValueError("_qdense needs a batched input [N, ..., in]; "
                         f"got shape {x.shape}")
    row_axes = tuple(range(1, x.ndim))
    s_x = jnp.maximum(
        jnp.max(jnp.abs(x), axis=row_axes, keepdims=True) / 127.0,
        1e-12)  # [N, 1, ..., 1]
    xq = jnp.clip(jnp.round(x / s_x), -127, 127).astype(jnp.int8)
    y = jax.lax.dot_general(
        xq, wq, (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * (s_x * s_w) + b


def _ln(x, p):
    """LayerNorm in f32 (flax defaults: eps 1e-6, scale+bias)."""
    x = x.astype(jnp.float32)
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-6) * p["scale"] + p["bias"]


def quantize_text_encoder(module, variables):
    """w8a8-dynamic quantization of a ``dl.TextEncoder``'s dense
    layers (qkv / out / mlp — the bulk of encoder FLOPs); embedding,
    LayerNorms, softmax, and the attention contraction itself stay in
    f32/bf16. Returns ``(q_forward, qparams)`` with
    ``q_forward(qparams, ids) -> pooled [N, W] f32`` — the
    ``TextEncoderFeaturizer`` feature vector. Fidelity vs the f32
    forward is asserted by test (cos > 0.99).

    Supports DENSE attention (the default and the causal variant —
    causality is read off ``module.attention_fn``); a sharded or
    Pallas attention_fn raises rather than silently quantizing into a
    forward with different attention semantics."""
    import functools

    from ..dl.text_encoder import _dense_attention

    fn = module.attention_fn
    if fn is _dense_attention:
        causal = False
    elif isinstance(fn, functools.partial) \
            and fn.func is _dense_attention:
        causal = bool(fn.keywords.get("causal", False))
    else:
        raise ValueError(
            "quantize_text_encoder supports dense attention only "
            "(make_attention_fn('dense', ...)); got a custom/sharded "
            "attention_fn whose semantics the quantized forward "
            "cannot reproduce")
    params = variables["params"]
    q: dict = {"embed": params["embed"]["embedding"].astype(
        jnp.float32)}
    blocks = []
    for i in range(module.depth):
        bp = params[f"block{i}"]
        blocks.append({
            "ln_1": jax.tree.map(lambda a: a.astype(jnp.float32),
                                 bp["ln_1"]),
            "ln_2": jax.tree.map(lambda a: a.astype(jnp.float32),
                                 bp["ln_2"]),
            "qkv": (*_quant_dense_w(
                bp["qkv"]["kernel"].astype(jnp.float32)),
                bp["qkv"]["bias"].astype(jnp.float32)),
            "out": (*_quant_dense_w(
                bp["out"]["kernel"].astype(jnp.float32)),
                bp["out"]["bias"].astype(jnp.float32)),
            "mlp_1": (*_quant_dense_w(
                bp["mlp_1"]["kernel"].astype(jnp.float32)),
                bp["mlp_1"]["bias"].astype(jnp.float32)),
            "mlp_2": (*_quant_dense_w(
                bp["mlp_2"]["kernel"].astype(jnp.float32)),
                bp["mlp_2"]["bias"].astype(jnp.float32)),
        })
    q["blocks"] = blocks
    q["ln"] = jax.tree.map(lambda a: a.astype(jnp.float32),
                           params["ln"])

    heads, width = module.heads, module.width
    hd = width // heads

    def q_forward(qp, ids):
        N, T = ids.shape
        x = qp["embed"][ids]                          # [N, T, W] f32
        pos = jnp.arange(T)[:, None]
        dim = jnp.arange(width // 2)[None, :]
        ang = pos / (10000.0 ** (2 * dim / width))
        x = x + jnp.concatenate([jnp.sin(ang), jnp.cos(ang)],
                                axis=-1)[None]
        key_mask = ids != 0
        for bp in qp["blocks"]:
            h = _ln(x, bp["ln_1"])
            qkv = _qdense(h, *bp["qkv"])              # [N, T, 3W]
            qh, kh, vh = jnp.split(qkv, 3, axis=-1)

            def split(a):
                return a.reshape(N, T, heads, hd).transpose(0, 2, 1, 3)

            s = jnp.einsum("bhqd,bhkd->bhqk", split(qh), split(kh),
                           preferred_element_type=jnp.float32) \
                * hd ** -0.5
            if causal:
                tri = jnp.arange(T)[None, :] <= jnp.arange(T)[:, None]
                s = jnp.where(tri[None, None], s, -jnp.inf)
            s = s + jnp.where(key_mask, 0.0,
                              -jnp.inf)[:, None, None, :]
            p = jax.nn.softmax(s, axis=-1)
            p = jnp.where(jnp.isnan(p), 0.0, p)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, split(vh))
            o = o.transpose(0, 2, 1, 3).reshape(N, T, width)
            x = x + _qdense(o, *bp["out"])
            h = _ln(x, bp["ln_2"])
            h = _qdense(h, *bp["mlp_1"])
            h = jax.nn.gelu(h)
            x = x + _qdense(h, *bp["mlp_2"])
        x = _ln(x, qp["ln"])
        mask = key_mask.astype(jnp.float32)[..., None]
        return (x * mask).sum(1) / jnp.maximum(mask.sum(1), 1.0)

    return q_forward, q
