"""Mini-batching transformers — the serving/DL throughput trick.

Reference ``stages/MiniBatchTransformer.scala:15-225`` + ``Batchers.scala``:
batch rows into list-valued rows so downstream stages amortize per-call cost
(for us: one jitted XLA call per batch instead of per row), then
``FlattenBatch`` un-batches. ``DynamicBufferedBatcher`` adaptively sizes
batches from a producer queue — the key serving-latency mechanism.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator

import numpy as np

from ..core import DataFrame, Transformer, Param, TypeConverters as TC


def _batch_df(df: DataFrame, bounds: list[tuple[int, int]]) -> DataFrame:
    """Rows → one row per (start, end) batch; each cell becomes an array."""
    data = {}
    for col in df.columns:
        arr = df[col]
        cells = np.empty(len(bounds), dtype=object)
        cells[:] = [arr[a:b] for a, b in bounds]
        data[col] = cells
    out = DataFrame(data)
    out.num_partitions = df.num_partitions
    return out


class FixedMiniBatchTransformer(Transformer):
    batchSize = Param("batchSize", "rows per batch", TC.toInt, default=10)
    maxBufferSize = Param("maxBufferSize", "kept for API parity", TC.toInt,
                          default=1 << 20)

    def _transform(self, df):
        size = self.getBatchSize()
        n = df.num_rows
        bounds = [(i, min(i + size, n)) for i in range(0, n, size)]
        return _batch_df(df, bounds)


class DynamicMiniBatchTransformer(Transformer):
    """One batch per partition (the dynamic batcher consumes whatever is
    available — in columnar form, a partition is 'what's available')."""

    maxBatchSize = Param("maxBatchSize", "upper bound on batch size",
                         TC.toInt, default=1 << 30)

    def _transform(self, df):
        size = min(self.getMaxBatchSize(), max(df.num_rows, 1))
        n = df.num_rows
        bounds = [(i, min(i + size, n)) for i in range(0, n, size)] or []
        return _batch_df(df, bounds)


class TimeIntervalMiniBatchTransformer(Transformer):
    """Batch by arrival-time windows. On a materialized frame this groups by
    a timestamp column into ``millisToWait`` windows (reference streams rows;
    columnar equivalent uses the recorded arrival time)."""

    millisToWait = Param("millisToWait", "window length in ms", TC.toInt,
                         default=1000)
    timestampCol = Param("timestampCol",
                         "epoch-millis column; absent → single batch",
                         TC.toString)
    maxBatchSize = Param("maxBatchSize", "upper bound on batch size",
                         TC.toInt, default=1 << 30)

    def _transform(self, df):
        n = df.num_rows
        if not self.isSet("timestampCol"):
            bounds = [(0, n)] if n else []
            return _batch_df(df, bounds)
        ts = np.asarray(df[self.getTimestampCol()], dtype=np.int64)
        order = np.argsort(ts, kind="stable")
        sorted_df = df.take(order)
        ts = ts[order]
        window = self.getMillisToWait()
        max_size = self.getMaxBatchSize()
        bounds, start = [], 0
        for i in range(1, n + 1):
            if (i == n or ts[i] - ts[start] >= window
                    or i - start >= max_size):
                bounds.append((start, i))
                start = i
        return _batch_df(sorted_df, bounds)


class FlattenBatch(Transformer):
    """Inverse of the mini-batchers: list-valued rows → one row per element."""

    def _transform(self, df):
        cols = df.columns
        if not cols or df.num_rows == 0:
            return df
        lengths = None
        for c in cols:
            cells = df[c]
            if cells.dtype == object and len(cells) and \
                    hasattr(cells[0], "__len__"):
                lengths = np.asarray([len(v) for v in cells.tolist()])
                break
        if lengths is None:
            return df
        data = {}
        for c in cols:
            cells = df[c]
            if cells.dtype == object and hasattr(cells[0], "__len__") and \
                    not isinstance(cells[0], str):
                parts = [np.asarray(v) for v in cells.tolist()]
                if parts and parts[0].dtype != object and \
                        all(p.ndim == parts[0].ndim for p in parts):
                    data[c] = np.concatenate(parts, axis=0)
                else:
                    flat = np.empty(int(lengths.sum()), dtype=object)
                    k = 0
                    for v in cells.tolist():
                        for item in v:
                            flat[k] = item
                            k += 1
                    data[c] = flat
            else:
                data[c] = np.repeat(cells, lengths, axis=0)
        out = DataFrame(data)
        out.num_partitions = df.num_partitions
        return out


class DynamicBufferedBatcher:
    """Queue-based adaptive batcher (reference ``stages/Batchers.scala:1-152``).

    A producer thread fills a bounded queue; ``__iter__`` yields batches
    sized by the SAME close policy online serving uses
    (``sched.BatchPolicy`` — one batching brain for offline pipelines
    and the serving fronts): under light load batches are small (low
    latency), under heavy load they grow (high throughput), and with a
    ``linger`` budget the policy's padding-bucket / service-time logic
    decides whether waiting longer costs more than it gains. The default
    (``max_batch=None``, ``linger=0``) reproduces the reference's
    take-what-accumulated behavior exactly.
    """

    def __init__(self, it: Iterator, max_buffer_size: int = 1024,
                 max_batch: int | None = None, linger: float = 0.0,
                 policy=None):
        from ..sched import BatchPolicy

        self._it = it
        self._queue: queue.Queue = queue.Queue(maxsize=max_buffer_size)
        self._policy = policy or BatchPolicy(
            max_batch=max_batch or max_buffer_size, linger=linger)
        self._done = threading.Event()
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    def _produce(self):
        try:
            for item in self._it:
                self._queue.put(item)
        finally:
            self._done.set()

    def __iter__(self):
        from ..sched.policy import CLOSE, GROW
        while True:
            batch = []
            try:
                batch.append(self._queue.get(timeout=0.01))
            except queue.Empty:
                if self._done.is_set() and self._queue.empty():
                    return
                continue
            linger_end = time.monotonic() + self._policy.linger
            while True:
                action, wait_s, _reason = self._policy.decide(
                    len(batch), queue_empty=self._queue.empty(),
                    linger_remaining=linger_end - time.monotonic())
                if action == GROW:
                    try:
                        batch.append(self._queue.get_nowait())
                    except queue.Empty:
                        pass  # producer raced us; policy re-decides
                    continue
                if action == CLOSE:
                    break
                if self._done.is_set():
                    # producer exhausted: nothing can arrive, so paying
                    # the remaining linger would only delay the final
                    # partial batch
                    break
                try:  # WAIT: pay bounded latency to grow the batch
                    batch.append(self._queue.get(timeout=wait_s))
                except queue.Empty:
                    pass
            yield batch


class PartitionConsolidator(Transformer):
    """Funnel many partitions through one consolidated stream (reference
    ``stages/PartitionConsolidator.scala:21-143``) — used to respect
    per-process rate limits on HTTP services. Columnar equivalent: collapse
    to a single partition while preserving rows."""

    def _transform(self, df):
        return df.repartition(1)
