"""Mini-batching transformers — the serving/DL throughput trick.

Reference ``stages/MiniBatchTransformer.scala:15-225`` + ``Batchers.scala``:
batch rows into list-valued rows so downstream stages amortize per-call cost
(for us: one jitted XLA call per batch instead of per row), then
``FlattenBatch`` un-batches. ``DynamicBufferedBatcher`` adaptively sizes
batches from a producer queue — the key serving-latency mechanism.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator

import numpy as np

from ..core import DataFrame, Transformer, Param, TypeConverters as TC
from ..core.dataframe import (argsort_host, concat_host, jittable_dtype,
                              object_column, repeat_rows, to_host)
from ..core.lazyjnp import jnp


def _batch_df(df: DataFrame, bounds: list[tuple[int, int]]) -> DataFrame:
    """Rows → one row per (start, end) batch; each cell becomes an array.
    Cells are views of the source columns (slicing, no scratch buffer);
    the object column wrapper is the one host allocation."""
    data = {}
    for col in df.columns:
        arr = df[col]
        data[col] = object_column([arr[a:b] for a, b in bounds])
    out = DataFrame(data)
    out.num_partitions = df.num_partitions
    return out


def _uniform_batch_trace(cols: dict, size: int) -> dict:
    """The jnp mini-batch path: [n, ...] → [n/size, size, ...] (or one
    [1, n, ...] batch when size >= n). Static shapes — n is concrete at
    trace time, so the reshape is a free layout change XLA folds away;
    this replaces the per-column host scratch buffer entirely."""
    out = {}
    for c, v in cols.items():
        n = v.shape[0]
        if size >= n:
            out[c] = v[None]
        else:
            out[c] = v.reshape((n // size, size) + v.shape[1:])
    return out


class FixedMiniBatchTransformer(Transformer):
    batchSize = Param("batchSize", "rows per batch", TC.toInt, default=10)
    maxBufferSize = Param("maxBufferSize", "kept for API parity", TC.toInt,
                          default=1 << 20)

    _trace_changes_rows = True

    def _transform(self, df):
        size = self.getBatchSize()
        n = df.num_rows
        bounds = [(i, min(i + size, n)) for i in range(0, n, size)]
        return _batch_df(df, bounds)

    def _trace_ok(self, schema, n_rows):
        if not n_rows:
            return False
        size = self.getBatchSize()
        return size >= n_rows or n_rows % size == 0

    def _trace(self, cols):
        return _uniform_batch_trace(cols, self.getBatchSize())


class DynamicMiniBatchTransformer(Transformer):
    """One batch per partition (the dynamic batcher consumes whatever is
    available — in columnar form, a partition is 'what's available').

    This stage sits in every served batch pipeline, so its traced form
    matters most: one batch of everything available is a pure
    ``[n, ...] → [1, n, ...]`` expand — zero host work, fully fusable
    (the ``numpy.empty`` scratch buffer is gone; the eager path slices
    views and only wraps them in an object column)."""

    maxBatchSize = Param("maxBatchSize", "upper bound on batch size",
                         TC.toInt, default=1 << 30)

    _trace_changes_rows = True

    def _transform(self, df):
        size = min(self.getMaxBatchSize(), max(df.num_rows, 1))
        n = df.num_rows
        bounds = [(i, min(i + size, n)) for i in range(0, n, size)] or []
        return _batch_df(df, bounds)

    def _trace_ok(self, schema, n_rows):
        if not n_rows:
            return False
        size = min(self.getMaxBatchSize(), max(n_rows, 1))
        return size >= n_rows or n_rows % size == 0

    def _trace(self, cols):
        n = max((v.shape[0] for v in cols.values()), default=1)
        return _uniform_batch_trace(
            cols, min(self.getMaxBatchSize(), max(n, 1)))


class TimeIntervalMiniBatchTransformer(Transformer):
    """Batch by arrival-time windows. On a materialized frame this groups by
    a timestamp column into ``millisToWait`` windows (reference streams rows;
    columnar equivalent uses the recorded arrival time)."""

    millisToWait = Param("millisToWait", "window length in ms", TC.toInt,
                         default=1000)
    timestampCol = Param("timestampCol",
                         "epoch-millis column; absent → single batch",
                         TC.toString)
    maxBatchSize = Param("maxBatchSize", "upper bound on batch size",
                         TC.toInt, default=1 << 30)

    _trace_changes_rows = True

    def _transform(self, df):
        n = df.num_rows
        if not self.isSet("timestampCol"):
            bounds = [(0, n)] if n else []
            return _batch_df(df, bounds)
        ts = df[self.getTimestampCol()].astype(np.int64)
        # stable host argsort: epoch-millis are int64 and must sort
        # exactly (argsort_host's docstring has the 2**31-wrap story);
        # the windowing loop below relies on stability
        order = argsort_host(ts)
        sorted_df = df.take(order)
        ts = ts[order]
        window = self.getMillisToWait()
        max_size = self.getMaxBatchSize()
        bounds, start = [], 0
        for i in range(1, n + 1):
            if (i == n or ts[i] - ts[start] >= window
                    or i - start >= max_size):
                bounds.append((start, i))
                start = i
        return _batch_df(sorted_df, bounds)

    def _trace_ok(self, schema, n_rows):
        # window boundaries are data-dependent; only the no-timestamp
        # single-batch form has static shapes
        return bool(n_rows) and not self.isSet("timestampCol")

    def _trace(self, cols):
        return {c: v[None] for c, v in cols.items()}


class FlattenBatch(Transformer):
    """Inverse of the mini-batchers: list-valued rows → one row per element."""

    _trace_changes_rows = True

    def _transform(self, df):
        cols = df.columns
        if not cols or df.num_rows == 0:
            return df
        lengths = None
        for c in cols:
            cells = df[c]
            if cells.dtype == object and len(cells) and \
                    hasattr(cells[0], "__len__"):
                lengths = [len(v) for v in cells]
                break
        if lengths is None:
            return df
        data = {}
        for c in cols:
            cells = df[c]
            if cells.dtype == object and hasattr(cells[0], "__len__") and \
                    not isinstance(cells[0], str):
                parts = [to_host(v) for v in cells]
                if parts and parts[0].dtype != object and \
                        all(p.ndim == parts[0].ndim for p in parts):
                    # numeric cells: concatenate on host in the cells'
                    # own dtype — int64 epoch millis from the
                    # time-interval batcher must not round through the
                    # device's 32-bit lattice on the eager path
                    data[c] = concat_host(parts)
                else:
                    data[c] = object_column(
                        item for v in cells for item in v)
            else:
                data[c] = repeat_rows(cells, lengths)
        out = DataFrame(data)
        out.num_partitions = df.num_partitions
        return out

    def _trace_ok(self, schema, n_rows):
        # the traced form merges the two leading axes of every column:
        # all columns must be batched (trailing shape present)
        return bool(schema) and all(
            jittable_dtype(dt) and len(shape) >= 1
            for dt, shape in schema.values())

    def _trace(self, cols):
        return {c: v.reshape((-1,) + v.shape[2:]) for c, v in cols.items()}


class DynamicBufferedBatcher:
    """Queue-based adaptive batcher (reference ``stages/Batchers.scala:1-152``).

    A producer thread fills a bounded queue; ``__iter__`` yields batches
    sized by the SAME close policy online serving uses
    (``sched.BatchPolicy`` — one batching brain for offline pipelines
    and the serving fronts): under light load batches are small (low
    latency), under heavy load they grow (high throughput), and with a
    ``linger`` budget the policy's padding-bucket / service-time logic
    decides whether waiting longer costs more than it gains. The default
    (``max_batch=None``, ``linger=0``) reproduces the reference's
    take-what-accumulated behavior exactly.
    """

    def __init__(self, it: Iterator, max_buffer_size: int = 1024,
                 max_batch: int | None = None, linger: float = 0.0,
                 policy=None):
        from ..sched import BatchPolicy

        self._it = it
        self._queue: queue.Queue = queue.Queue(maxsize=max_buffer_size)
        self._policy = policy or BatchPolicy(
            max_batch=max_batch or max_buffer_size, linger=linger)
        self._done = threading.Event()
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    def _produce(self):
        try:
            for item in self._it:
                self._queue.put(item)
        finally:
            self._done.set()

    def __iter__(self):
        from ..sched.policy import CLOSE, GROW
        while True:
            batch = []
            try:
                batch.append(self._queue.get(timeout=0.01))
            except queue.Empty:
                if self._done.is_set() and self._queue.empty():
                    return
                continue
            linger_end = time.monotonic() + self._policy.linger
            while True:
                action, wait_s, _reason = self._policy.decide(
                    len(batch), queue_empty=self._queue.empty(),
                    linger_remaining=linger_end - time.monotonic())
                if action == GROW:
                    try:
                        batch.append(self._queue.get_nowait())
                    except queue.Empty:
                        pass  # producer raced us; policy re-decides
                    continue
                if action == CLOSE:
                    break
                if self._done.is_set():
                    # producer exhausted: nothing can arrive, so paying
                    # the remaining linger would only delay the final
                    # partial batch
                    break
                try:  # WAIT: pay bounded latency to grow the batch
                    batch.append(self._queue.get(timeout=wait_s))
                except queue.Empty:
                    pass
            yield batch


class PartitionConsolidator(Transformer):
    """Funnel many partitions through one consolidated stream (reference
    ``stages/PartitionConsolidator.scala:21-143``) — used to respect
    per-process rate limits on HTTP services. Columnar equivalent: collapse
    to a single partition while preserving rows."""

    def _transform(self, df):
        return df.repartition(1)

    def _trace(self, cols):
        return cols  # partition collapse is host metadata

    def _post_host(self, df):
        return df.repartition(1)
