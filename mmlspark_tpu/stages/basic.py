"""Generic DataFrame plumbing transformers.

Reference ``stages/`` (SURVEY §2.9): the ~20 utility transformers every
pipeline uses — column selection/renaming, UDFs, lambdas, repartitioning,
caching, timing.
"""

from __future__ import annotations

from ..core import Transformer, Param, TypeConverters as TC, UDFParam
from ..core.contracts import HasInputCol, HasInputCols, HasOutputCol
from ..core.dataframe import jittable_dtype, object_column


class DropColumns(Transformer):
    cols = Param("cols", "columns to drop", TC.toListString, default=[],
                 has_default=True)

    def _transform(self, df):
        present = [c for c in self.getCols() if c in df.columns]
        return df.drop(*present) if present else df

    def _trace_ok(self, schema, n_rows):
        # a dropped host-carried column would survive the segment
        return all(jittable_dtype(schema[c][0])
                   for c in self.getCols() if c in schema)

    def _trace(self, cols):
        drop = set(self.getCols())
        return {c: v for c, v in cols.items() if c not in drop}


class SelectColumns(Transformer):
    cols = Param("cols", "columns to keep", TC.toListString)

    def _transform(self, df):
        return df.select(*self.getCols())

    def _trace_ok(self, schema, n_rows):
        # selecting implicitly drops the rest — every column must be in
        # the traced dict for the effect to be complete
        return all(jittable_dtype(dt) for dt, _ in schema.values()) \
            and all(c in schema for c in self.getCols())

    def _trace(self, cols):
        return {c: cols[c] for c in self.getCols()}


class RenameColumn(Transformer, HasInputCol, HasOutputCol):
    def _transform(self, df):
        return df.with_column_renamed(self.getInputCol(), self.getOutputCol())

    def _trace_ok(self, schema, n_rows):
        ic = self.getInputCol()
        return ic in schema and jittable_dtype(schema[ic][0])

    def _trace(self, cols):
        old, new = self.getInputCol(), self.getOutputCol()
        return {(new if c == old else c): v for c, v in cols.items()}


class UDFTransformer(Transformer, HasInputCol, HasInputCols, HasOutputCol):
    """Apply a user function to one or more columns (reference
    ``stages/UDFTransformer.scala``). The function receives numpy arrays
    (whole-column, not per-row — columnar by design).

    ``jitSafe=True`` declares the function a pure ``jax.numpy``
    computation with static output shapes, letting the pipeline
    compiler fuse this stage into an XLA segment (the udf then receives
    tracers; a host-op inside it will fail the trace and fall back
    eagerly, loudly). This is how model-inference stages ride the fused
    serving path."""

    udf = UDFParam("udf", "function(column_array...) -> column_array")
    jitSafe = Param("jitSafe",
                    "udf is pure jax.numpy with static shapes (enables "
                    "whole-pipeline fusion)", TC.toBoolean, default=False,
                    has_default=True)

    def _transform(self, df):
        fn = self.get("udf")
        if self.isSet("inputCols"):
            args = [df[c] for c in self.getInputCols()]
        else:
            args = [df[self.getInputCol()]]
        return df.with_column(self.getOutputCol(), fn(*args))

    def _in_cols(self):
        return self.getInputCols() if self.isSet("inputCols") \
            else [self.getInputCol()]

    def _trace_ok(self, schema, n_rows):
        return self.get("jitSafe") and all(
            c in schema and jittable_dtype(schema[c][0])
            for c in self._in_cols())

    def _trace(self, cols):
        out = dict(cols)
        out[self.getOutputCol()] = self.get("udf")(
            *[cols[c] for c in self._in_cols()])
        return out


class Lambda(Transformer):
    """Arbitrary DataFrame → DataFrame function (reference
    ``stages/Lambda.scala``)."""

    transformFunc = UDFParam("transformFunc", "df -> df function")

    def _transform(self, df):
        return self.get("transformFunc")(df)


class MultiColumnAdapter(Transformer, HasInputCols):
    """Apply a single-column stage across many columns (reference
    ``stages/MultiColumnAdapter.scala``)."""

    from ..core.param import StageParam as _SP
    baseStage = _SP("baseStage", "single-column stage to replicate")
    outputCols = Param("outputCols", "output column names", TC.toListString)

    def _transform(self, df):
        base = self.get("baseStage")
        cur = df
        for in_col, out_col in zip(self.getInputCols(), self.getOutputCols()):
            stage = base.copy({"inputCol": in_col, "outputCol": out_col})
            cur = stage.transform(cur)
        return cur


class Repartition(Transformer):
    n = Param("n", "target partition count", TC.toInt)
    disable = Param("disable", "no-op passthrough", TC.toBoolean,
                    default=False)

    def _transform(self, df):
        if self.getDisable():
            return df
        return df.repartition(self.getN())

    def _trace(self, cols):
        return cols  # partition count is host metadata, not array data

    def _post_host(self, df):
        return df if self.getDisable() else df.repartition(self.getN())


class Cacher(Transformer):
    disable = Param("disable", "no-op passthrough", TC.toBoolean,
                    default=False)

    def _transform(self, df):
        return df if self.getDisable() else df.cache()

    def _trace(self, cols):
        return cols  # cache() is a host-side no-op on materialized data


class Explode(Transformer, HasInputCol, HasOutputCol):
    """Explode a list column into one row per element (reference
    ``stages/Explode.scala``).

    Output length is the SUM of per-row list lengths — data-dependent,
    so no static-shape ``_trace`` exists and the pipeline compiler
    splits fused segments around it (its host plumbing is free of
    numpy scratch work, but dynamic shapes cannot lower to XLA)."""

    def _transform(self, df):
        col = df[self.getInputCol()]
        idx: list[int] = []
        exploded: list = []
        for i, v in enumerate(col):
            for item in v:
                idx.append(i)
                exploded.append(item)
        out = df.take(idx)
        return out.with_column(self.getOutputCol(),
                               object_column(exploded))


class Timer(Transformer):
    """Wrap a stage and log its wall time (reference ``stages/Timer.scala``).

    The measured duration is recorded on ``lastDuration`` and logged
    through the telemetry channel. Measurement runs through the obs
    :class:`~mmlspark_tpu.obs.profile.StepProfiler`, so a timed stage
    also lands in the ``profile_step_seconds`` host-dispatch vs
    device-execute split and emits dispatch/device child spans under
    the ambient trace — one timing surface, not a private stopwatch.

    DELIBERATE semantic point: Timer now syncs the wrapped stage's
    output (``block_until_ready``) before stopping the clock. The old
    stopwatch measured only dispatch, which for a device-backed stage
    under JAX's async dispatch reported near-zero — the one number a
    user wrapping a stage in Timer explicitly asked NOT to get. The
    sync costs the measured stage its dispatch overlap; that is what
    measuring completion means. Un-timed pipelines are untouched
    (``PipelineModel`` profiles only behind an explicit opt-in).
    """

    from ..core.param import StageParam as _SP
    stage = _SP("stage", "stage to time")
    logToScala = Param("logToScala", "kept for API parity; logs to telemetry",
                       TC.toBoolean, default=True)

    lastDuration: float | None = None

    def _transform(self, df):
        from ..core import Estimator
        from ..obs.profile import step_profiler
        inner = self.get("stage")
        with step_profiler.step(type(inner).__name__) as h:
            if isinstance(inner, Estimator):
                fitted = inner.fit(df)
                out = fitted.transform(df)
            else:
                out = inner.transform(df)
            h.done(out)
        self.lastDuration = h.seconds
        self._log_event("timer", stage=type(inner).__name__,
                        seconds=self.lastDuration)
        return out
