"""Generic DataFrame plumbing transformers.

Reference ``stages/`` (SURVEY §2.9): the ~20 utility transformers every
pipeline uses — column selection/renaming, UDFs, lambdas, repartitioning,
caching, timing.
"""

from __future__ import annotations

import numpy as np

from ..core import Transformer, Param, TypeConverters as TC, UDFParam
from ..core.contracts import HasInputCol, HasInputCols, HasOutputCol


class DropColumns(Transformer):
    cols = Param("cols", "columns to drop", TC.toListString, default=[],
                 has_default=True)

    def _transform(self, df):
        present = [c for c in self.getCols() if c in df.columns]
        return df.drop(*present) if present else df


class SelectColumns(Transformer):
    cols = Param("cols", "columns to keep", TC.toListString)

    def _transform(self, df):
        return df.select(*self.getCols())


class RenameColumn(Transformer, HasInputCol, HasOutputCol):
    def _transform(self, df):
        return df.with_column_renamed(self.getInputCol(), self.getOutputCol())


class UDFTransformer(Transformer, HasInputCol, HasInputCols, HasOutputCol):
    """Apply a user function to one or more columns (reference
    ``stages/UDFTransformer.scala``). The function receives numpy arrays
    (whole-column, not per-row — columnar by design)."""

    udf = UDFParam("udf", "function(column_array...) -> column_array")

    def _transform(self, df):
        fn = self.get("udf")
        if self.isSet("inputCols"):
            args = [df[c] for c in self.getInputCols()]
        else:
            args = [df[self.getInputCol()]]
        return df.with_column(self.getOutputCol(), fn(*args))


class Lambda(Transformer):
    """Arbitrary DataFrame → DataFrame function (reference
    ``stages/Lambda.scala``)."""

    transformFunc = UDFParam("transformFunc", "df -> df function")

    def _transform(self, df):
        return self.get("transformFunc")(df)


class MultiColumnAdapter(Transformer, HasInputCols):
    """Apply a single-column stage across many columns (reference
    ``stages/MultiColumnAdapter.scala``)."""

    from ..core.param import StageParam as _SP
    baseStage = _SP("baseStage", "single-column stage to replicate")
    outputCols = Param("outputCols", "output column names", TC.toListString)

    def _transform(self, df):
        base = self.get("baseStage")
        cur = df
        for in_col, out_col in zip(self.getInputCols(), self.getOutputCols()):
            stage = base.copy({"inputCol": in_col, "outputCol": out_col})
            cur = stage.transform(cur)
        return cur


class Repartition(Transformer):
    n = Param("n", "target partition count", TC.toInt)
    disable = Param("disable", "no-op passthrough", TC.toBoolean,
                    default=False)

    def _transform(self, df):
        if self.getDisable():
            return df
        return df.repartition(self.getN())


class Cacher(Transformer):
    disable = Param("disable", "no-op passthrough", TC.toBoolean,
                    default=False)

    def _transform(self, df):
        return df if self.getDisable() else df.cache()


class Explode(Transformer, HasInputCol, HasOutputCol):
    """Explode a list column into one row per element (reference
    ``stages/Explode.scala``)."""

    def _transform(self, df):
        col = df[self.getInputCol()]
        lengths = np.asarray([len(v) for v in col.tolist()])
        idx = np.repeat(np.arange(df.num_rows), lengths)
        exploded = np.empty(int(lengths.sum()), dtype=object)
        k = 0
        for v in col.tolist():
            for item in v:
                exploded[k] = item
                k += 1
        out = df.take(idx)
        return out.with_column(self.getOutputCol(), exploded)


class Timer(Transformer):
    """Wrap a stage and log its wall time (reference ``stages/Timer.scala``).

    The measured duration is recorded on ``lastDuration`` and logged
    through the telemetry channel. Measurement runs through the obs
    :class:`~mmlspark_tpu.obs.profile.StepProfiler`, so a timed stage
    also lands in the ``profile_step_seconds`` host-dispatch vs
    device-execute split and emits dispatch/device child spans under
    the ambient trace — one timing surface, not a private stopwatch.

    DELIBERATE semantic point: Timer now syncs the wrapped stage's
    output (``block_until_ready``) before stopping the clock. The old
    stopwatch measured only dispatch, which for a device-backed stage
    under JAX's async dispatch reported near-zero — the one number a
    user wrapping a stage in Timer explicitly asked NOT to get. The
    sync costs the measured stage its dispatch overlap; that is what
    measuring completion means. Un-timed pipelines are untouched
    (``PipelineModel`` profiles only behind an explicit opt-in).
    """

    from ..core.param import StageParam as _SP
    stage = _SP("stage", "stage to time")
    logToScala = Param("logToScala", "kept for API parity; logs to telemetry",
                       TC.toBoolean, default=True)

    lastDuration: float | None = None

    def _transform(self, df):
        from ..core import Estimator
        from ..obs.profile import step_profiler
        inner = self.get("stage")
        with step_profiler.step(type(inner).__name__) as h:
            if isinstance(inner, Estimator):
                fitted = inner.fit(df)
                out = fitted.transform(df)
            else:
                out = inner.transform(df)
            h.done(out)
        self.lastDuration = h.seconds
        self._log_event("timer", stage=type(inner).__name__,
                        seconds=self.lastDuration)
        return out
