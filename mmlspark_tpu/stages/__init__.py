from .basic import (DropColumns, SelectColumns, RenameColumn, UDFTransformer,
                    Lambda, MultiColumnAdapter, Repartition, Cacher, Explode,
                    Timer)
from .batching import (FixedMiniBatchTransformer, DynamicMiniBatchTransformer,
                       TimeIntervalMiniBatchTransformer, FlattenBatch,
                       DynamicBufferedBatcher, PartitionConsolidator)
from .misc import (SummarizeData, ClassBalancer, ClassBalancerModel,
                   StratifiedRepartition, EnsembleByKey, TextPreprocessor,
                   UnicodeNormalize)

__all__ = [
    "DropColumns", "SelectColumns", "RenameColumn", "UDFTransformer",
    "Lambda", "MultiColumnAdapter", "Repartition", "Cacher", "Explode",
    "Timer",
    "FixedMiniBatchTransformer", "DynamicMiniBatchTransformer",
    "TimeIntervalMiniBatchTransformer", "FlattenBatch",
    "DynamicBufferedBatcher", "PartitionConsolidator",
    "SummarizeData", "ClassBalancer", "ClassBalancerModel",
    "StratifiedRepartition", "EnsembleByKey", "TextPreprocessor",
    "UnicodeNormalize",
]
