"""Data-shaping and profiling stages.

Reference ``stages/``: SummarizeData, ClassBalancer, StratifiedRepartition,
EnsembleByKey, TextPreprocessor, UnicodeNormalize (SURVEY §2.9).

Numeric compute here runs through ``jax.numpy`` (eagerly outside a
pipeline, traced inside a fused segment where a ``_trace`` form exists);
string normalization stays plain Python — those loops are genuinely
host work and keep their stages out of fused segments at runtime.
"""

from __future__ import annotations

import re
import unicodedata

import numpy as np

from ..core import DataFrame, Estimator, Model, Transformer, Param, \
    TypeConverters as TC
from ..core.contracts import HasInputCol, HasLabelCol, HasOutputCol, HasSeed
from ..core.dataframe import (f32_exact, jittable_dtype, quantile_host,
                              to_host, to_host_list, unique_host)
from ..core.lazyjnp import jnp, jrandom


class SummarizeData(Transformer):
    """Counts / quantiles / missing-value profile per column (reference
    ``stages/SummarizeData.scala:1-238``)."""

    counts = Param("counts", "include counts block", TC.toBoolean, default=True)
    basic = Param("basic", "include basic stats block", TC.toBoolean,
                  default=True)
    sample = Param("sample", "include quantiles block", TC.toBoolean,
                   default=True)
    percentiles = Param("percentiles", "quantiles to compute", TC.toListFloat,
                        default=[0.005, 0.01, 0.05, 0.25, 0.5, 0.75, 0.95,
                                 0.99, 0.995])
    errorThreshold = Param("errorThreshold",
                           "quantile error (parity; exact here)", TC.toFloat,
                           default=0.0)

    def _transform(self, df):
        rows = []
        for col in df.columns:
            arr = df[col]
            row = {"Feature": col}
            numeric = arr.dtype.kind in "iuf" and arr.ndim == 1
            hostlike = arr.dtype == object or arr.dtype.kind in "MmUS"
            valid = None
            if numeric:
                # profiling output, not device math: stats stay on host
                # in the column's own dtype so float64 columns don't
                # merge distinct values (or degrade mean/quantiles)
                # through the device's 32-bit lattice
                x = to_host(arr)
                nan = x != x
                valid = x[~nan]
            if self.getCounts():
                row["Count"] = float(len(arr))
                if hostlike:
                    row["Unique Value Count"] = float(
                        len({str(v) for v in arr}))
                    row["Missing Value Count"] = float(
                        sum(v is None for v in arr)) \
                        if arr.dtype == object else 0.0
                elif numeric:
                    row["Unique Value Count"] = float(
                        unique_host(valid).size)
                    row["Missing Value Count"] = float(nan.sum())
                else:
                    row["Unique Value Count"] = float(
                        unique_host(to_host(arr)).size)
                    row["Missing Value Count"] = 0.0
            if self.getBasic():
                if numeric and valid.size:
                    row.update({
                        "Mean": float(valid.mean()),
                        "Std": float(valid.std(ddof=1))
                        if valid.size > 1 else np.nan,
                        "Min": float(valid.min()),
                        "Max": float(valid.max())})
                else:
                    row.update({"Mean": np.nan, "Std": np.nan,
                                "Min": np.nan, "Max": np.nan})
            if self.getSample():
                for p in self.getPercentiles():
                    row[f"Quantile_{p}"] = quantile_host(valid, p) \
                        if numeric and valid.size else np.nan
            rows.append(row)
        return DataFrame.from_rows(rows)


class ClassBalancer(Estimator, HasInputCol):
    """Compute per-class weights inversely proportional to frequency
    (reference ``stages/ClassBalancer.scala``)."""

    outputCol = Param("outputCol", "weight column", TC.toString,
                      default="weight")
    broadcastJoin = Param("broadcastJoin", "parity flag", TC.toBoolean,
                          default=True)

    def _fit(self, df):
        col = df[self.getInputCol()]
        if col.dtype == object:
            counts: dict[str, int] = {}
            for v in col:
                counts[str(v)] = counts.get(str(v), 0) + 1
        else:
            # EXACT host uniqueness: weight keys are str(value) and
            # _transform looks up str() of the exact column values — a
            # device round-trip would store float32-rounded keys that
            # the lookup then misses (unique_host's docstring)
            values, cnts = unique_host(col, return_counts=True)
            counts = {str(v): int(c)
                      for v, c in zip(to_host_list(values),
                                      to_host_list(cnts))}
        top = max(counts.values())
        model = ClassBalancerModel().setWeights(
            {k: float(top) / c for k, c in counts.items()})
        self._copy_params_to(model)
        return model


class ClassBalancerModel(Model, HasInputCol):
    weights = Param("weights", "class → weight", TC.toDict)
    outputCol = Param("outputCol", "weight column", TC.toString,
                      default="weight")

    def _transform(self, df):
        w = self.getWeights()
        col = df[self.getInputCol()]
        # look up str() of the same Python values fit stored: str(numpy
        # float32 scalar) is the SHORT repr ('0.1') while fit's keys
        # came from to_host_list (Python floats → '0.10000000149…')
        vals = col if col.dtype == object else to_host_list(col)
        return df.with_column(self.getOutputCol(),
                              [w[str(v)] for v in vals])

    def _trace_ok(self, schema, n_rows):
        ic = self.getInputCol()
        if ic not in schema or not jittable_dtype(schema[ic][0]):
            return False
        try:
            keys = [float(k) for k in self.getWeights()]
        except (TypeError, ValueError):
            return False  # non-numeric class labels: host dict lookup
        # keys that don't survive a float32 round-trip would collide
        # with a neighbor (ints ≥ 2**24) or miss in the traced
        # searchsorted — stay on the exact host lookup
        return all(f32_exact(k) for k in keys)

    def _trace(self, cols):
        items = sorted((float(k), float(v))
                       for k, v in self.getWeights().items())
        keys = jnp.asarray([k for k, _ in items])
        vals = jnp.asarray([v for _, v in items])
        x = cols[self.getInputCol()]
        idx = jnp.clip(jnp.searchsorted(keys, x), 0, len(items) - 1)
        out = dict(cols)
        # a traced computation cannot raise on an unseen label the way
        # the eager dict lookup does (KeyError) — gate on an exact key
        # match and emit NaN instead of silently borrowing the nearest
        # class's weight; NaN poisons downstream losses loudly
        out[self.getOutputCol()] = jnp.where(keys[idx] == x, vals[idx],
                                             jnp.nan)
        return out


class StratifiedRepartition(Transformer, HasLabelCol, HasSeed):
    """Rebalance rows across partitions so every partition sees every label
    (reference ``stages/StratifiedRepartition.scala:1-82``). Matters here for
    the same reason as the reference: distributed GBDT shards must all hold
    examples of each class or their histogram collectives degrade."""

    mode = Param("mode", "equal | original | mixed", TC.toString,
                 default="mixed")

    def _transform(self, df):
        labels = df[self.getLabelCol()]
        groups: dict[str, list[int]] = {}
        for i, v in enumerate(labels):
            groups.setdefault(str(v), []).append(i)
        key = jrandom.PRNGKey(self.getSeed())
        pools = []
        for k in sorted(groups):
            key, sub = jrandom.split(key)
            pools.append(list(to_host_list(
                jrandom.permutation(sub, jnp.asarray(groups[k])))))
        order: list[int] = []
        # Round-robin interleave per label so contiguous block
        # partitioning gives each partition a balanced label mix.
        while any(pools):
            for pool in pools:
                if pool:
                    order.append(pool.pop())
        return df.take(order)


class EnsembleByKey(Transformer):
    """Group rows by key columns and average vector/score columns (reference
    ``stages/EnsembleByKey.scala``)."""

    keys = Param("keys", "grouping key columns", TC.toListString)
    cols = Param("cols", "columns to aggregate", TC.toListString)
    strategy = Param("strategy", "mean (only supported, as in reference)",
                     TC.toString, default="mean")
    collapseGroup = Param("collapseGroup", "one row per group", TC.toBoolean,
                          default=True)

    def _transform(self, df):
        keys, cols = self.getKeys(), self.getCols()
        key_tuples = list(zip(*[list(df[k]) for k in keys]))
        groups: dict = {}
        for i, kt in enumerate(key_tuples):
            groups.setdefault(kt, []).append(i)
        rows = []
        for kt, idxs in groups.items():
            row = dict(zip(keys, kt))
            for c in cols:
                arr = df[c]
                if arr.dtype == object:
                    vals = jnp.stack(
                        [jnp.asarray(to_host(arr[i]), dtype=jnp.float32)
                         for i in idxs])
                else:
                    vals = jnp.asarray(arr[idxs], dtype=jnp.float32)
                mean = vals.mean(axis=0)
                row[f"mean({c})"] = float(mean) if mean.ndim == 0 \
                    else to_host(mean)
            rows.append(row)
        return DataFrame.from_rows(rows)


class TextPreprocessor(Transformer, HasInputCol, HasOutputCol):
    """Trie-based string normalization map (reference
    ``stages/TextPreprocessor.scala``). Pure host string work, by
    nature — never enters a fused segment."""

    map = Param("map", "substring → replacement", TC.toDict, default={},
                has_default=True)
    normFunc = Param("normFunc", "lower | upper | identity", TC.toString,
                     default="identity")

    def _transform(self, df):
        mapping = self.get("map")
        norm = {"lower": str.lower, "upper": str.upper,
                "identity": lambda s: s}[self.getNormFunc()]
        pattern = None
        if mapping:
            pattern = re.compile("|".join(
                re.escape(k) for k in sorted(mapping, key=len, reverse=True)))
        col = df[self.getInputCol()]
        out = []
        for v in col:
            s = norm(v) if v is not None else v
            if s is not None and pattern is not None:
                s = pattern.sub(lambda m: mapping[m.group(0)], s)
            out.append(s)
        return df.with_column(self.getOutputCol(), out)


class UnicodeNormalize(Transformer, HasInputCol, HasOutputCol):
    """Unicode NFC/NFKC/... normalization (reference
    ``stages/UnicodeNormalize.scala``). Host string work, like
    TextPreprocessor."""

    form = Param("form", "NFC | NFD | NFKC | NFKD", TC.toString,
                 default="NFKC")
    lower = Param("lower", "lowercase after normalizing", TC.toBoolean,
                  default=True)

    def _transform(self, df):
        form, lower = self.getForm(), self.getLower()
        col = df[self.getInputCol()]
        out = []
        for v in col:
            if v is None:
                out.append(None)
            else:
                s = unicodedata.normalize(form, v)
                out.append(s.lower() if lower else s)
        return df.with_column(self.getOutputCol(), out)
