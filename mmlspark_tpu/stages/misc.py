"""Data-shaping and profiling stages.

Reference ``stages/``: SummarizeData, ClassBalancer, StratifiedRepartition,
EnsembleByKey, TextPreprocessor, UnicodeNormalize (SURVEY §2.9).
"""

from __future__ import annotations

import re
import unicodedata

import numpy as np

from ..core import DataFrame, Estimator, Model, Transformer, Param, \
    TypeConverters as TC
from ..core.contracts import HasInputCol, HasLabelCol, HasOutputCol, HasSeed


class SummarizeData(Transformer):
    """Counts / quantiles / missing-value profile per column (reference
    ``stages/SummarizeData.scala:1-238``)."""

    counts = Param("counts", "include counts block", TC.toBoolean, default=True)
    basic = Param("basic", "include basic stats block", TC.toBoolean,
                  default=True)
    sample = Param("sample", "include quantiles block", TC.toBoolean,
                   default=True)
    percentiles = Param("percentiles", "quantiles to compute", TC.toListFloat,
                        default=[0.005, 0.01, 0.05, 0.25, 0.5, 0.75, 0.95,
                                 0.99, 0.995])
    errorThreshold = Param("errorThreshold",
                           "quantile error (parity; exact here)", TC.toFloat,
                           default=0.0)

    def _transform(self, df):
        rows = []
        for col in df.columns:
            arr = df[col]
            row = {"Feature": col}
            if self.getCounts():
                row["Count"] = float(len(arr))
                row["Unique Value Count"] = float(len(set(map(str, arr.tolist())))) \
                    if arr.dtype == object else float(np.unique(arr[~_nan(arr)]).size)
                row["Missing Value Count"] = float(_nan(arr).sum()) if \
                    arr.dtype != object else float(sum(v is None for v in arr))
            numeric = arr.dtype.kind in "iuf" and arr.ndim == 1
            if self.getBasic():
                if numeric:
                    vals = arr[~_nan(arr)].astype(np.float64)
                    row.update({"Mean": float(vals.mean()) if vals.size else np.nan,
                                "Std": float(vals.std(ddof=1)) if vals.size > 1 else np.nan,
                                "Min": float(vals.min()) if vals.size else np.nan,
                                "Max": float(vals.max()) if vals.size else np.nan})
                else:
                    row.update({"Mean": np.nan, "Std": np.nan,
                                "Min": np.nan, "Max": np.nan})
            if self.getSample():
                vals = arr[~_nan(arr)].astype(np.float64) if numeric else \
                    np.empty(0)
                for p in self.getPercentiles():
                    row[f"Quantile_{p}"] = float(np.quantile(vals, p)) \
                        if vals.size else np.nan
            rows.append(row)
        return DataFrame.from_rows(rows)


def _nan(arr):
    if arr.dtype.kind == "f":
        return np.isnan(arr)
    return np.zeros(len(arr), dtype=bool)


class ClassBalancer(Estimator, HasInputCol):
    """Compute per-class weights inversely proportional to frequency
    (reference ``stages/ClassBalancer.scala``)."""

    outputCol = Param("outputCol", "weight column", TC.toString,
                      default="weight")
    broadcastJoin = Param("broadcastJoin", "parity flag", TC.toBoolean,
                          default=True)

    def _fit(self, df):
        col = df[self.getInputCol()]
        values, counts = np.unique(col, return_counts=True)
        weights = counts.max() / counts.astype(np.float64)
        model = ClassBalancerModel().setWeights(
            {str(v): float(w) for v, w in zip(values.tolist(), weights)})
        self._copy_params_to(model)
        return model


class ClassBalancerModel(Model, HasInputCol):
    weights = Param("weights", "class → weight", TC.toDict)
    outputCol = Param("outputCol", "weight column", TC.toString,
                      default="weight")

    def _transform(self, df):
        w = self.getWeights()
        col = df[self.getInputCol()]
        out = np.asarray([w[str(v)] for v in col.tolist()], dtype=np.float64)
        return df.with_column(self.getOutputCol(), out)


class StratifiedRepartition(Transformer, HasLabelCol, HasSeed):
    """Rebalance rows across partitions so every partition sees every label
    (reference ``stages/StratifiedRepartition.scala:1-82``). Matters here for
    the same reason as the reference: distributed GBDT shards must all hold
    examples of each class or their histogram collectives degrade."""

    mode = Param("mode", "equal | original | mixed", TC.toString,
                 default="mixed")

    def _transform(self, df):
        labels = df[self.getLabelCol()]
        rng = np.random.default_rng(self.getSeed())
        order = []
        # Round-robin interleave per label so contiguous block partitioning
        # gives each partition a balanced label mix.
        by_label = {}
        for v in np.unique(labels):
            idx = np.flatnonzero(labels == v)
            rng.shuffle(idx)
            by_label[v] = list(idx)
        pools = list(by_label.values())
        while any(pools):
            for pool in pools:
                if pool:
                    order.append(pool.pop())
        return df.take(np.asarray(order, dtype=np.int64))


class EnsembleByKey(Transformer):
    """Group rows by key columns and average vector/score columns (reference
    ``stages/EnsembleByKey.scala``)."""

    keys = Param("keys", "grouping key columns", TC.toListString)
    cols = Param("cols", "columns to aggregate", TC.toListString)
    strategy = Param("strategy", "mean (only supported, as in reference)",
                     TC.toString, default="mean")
    collapseGroup = Param("collapseGroup", "one row per group", TC.toBoolean,
                          default=True)

    def _transform(self, df):
        keys, cols = self.getKeys(), self.getCols()
        key_arrays = [df[k] for k in keys]
        key_tuples = list(zip(*[a.tolist() for a in key_arrays]))
        groups: dict = {}
        for i, kt in enumerate(key_tuples):
            groups.setdefault(kt, []).append(i)
        rows = []
        for kt, idxs in groups.items():
            row = dict(zip(keys, kt))
            for c in cols:
                arr = df[c]
                vals = np.stack([np.asarray(arr[i], dtype=np.float64)
                                 for i in idxs]) if arr.dtype == object else \
                    np.asarray(arr[idxs], dtype=np.float64)
                row[f"mean({c})"] = vals.mean(axis=0)
            rows.append(row)
        return DataFrame.from_rows(rows)


class TextPreprocessor(Transformer, HasInputCol, HasOutputCol):
    """Trie-based string normalization map (reference
    ``stages/TextPreprocessor.scala``)."""

    map = Param("map", "substring → replacement", TC.toDict, default={},
                has_default=True)
    normFunc = Param("normFunc", "lower | upper | identity", TC.toString,
                     default="identity")

    def _transform(self, df):
        mapping = self.get("map")
        norm = {"lower": str.lower, "upper": str.upper,
                "identity": lambda s: s}[self.getNormFunc()]
        pattern = None
        if mapping:
            pattern = re.compile("|".join(
                re.escape(k) for k in sorted(mapping, key=len, reverse=True)))
        col = df[self.getInputCol()]
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(col.tolist()):
            s = norm(v) if v is not None else v
            if s is not None and pattern is not None:
                s = pattern.sub(lambda m: mapping[m.group(0)], s)
            out[i] = s
        return df.with_column(self.getOutputCol(), out)


class UnicodeNormalize(Transformer, HasInputCol, HasOutputCol):
    """Unicode NFC/NFKC/... normalization (reference
    ``stages/UnicodeNormalize.scala``)."""

    form = Param("form", "NFC | NFD | NFKC | NFKD", TC.toString,
                 default="NFKC")
    lower = Param("lower", "lowercase after normalizing", TC.toBoolean,
                  default=True)

    def _transform(self, df):
        form, lower = self.getForm(), self.getLower()
        col = df[self.getInputCol()]
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(col.tolist()):
            if v is None:
                out[i] = None
            else:
                s = unicodedata.normalize(form, v)
                out[i] = s.lower() if lower else s
        return df.with_column(self.getOutputCol(), out)
