"""Feature-slot selection by nonzero count.

Reference ``featurize/CountSelector.scala``: drop feature-vector slots that
are zero for every row (dead features inflate histogram work on device).

The fitted model is a static gather over the kept slot indices — pure
jax.numpy, fused into whole-pipeline XLA segments via ``_trace``.
"""

from __future__ import annotations

from ..core import Estimator, Model, Param
from ..core.contracts import HasInputCol, HasOutputCol
from ..core.dataframe import jittable_dtype, to_host_list
from ..core.lazyjnp import jnp
from ..core.utils import as_2d_features


class CountSelector(Estimator, HasInputCol, HasOutputCol):
    def _fit(self, df):
        x = jnp.asarray(as_2d_features(df, self.getInputCol()))
        keep = to_host_list(jnp.flatnonzero(jnp.any(x != 0, axis=0)))
        model = CountSelectorModel().setIndices([int(i) for i in keep])
        self._copy_params_to(model)
        return model


class CountSelectorModel(Model, HasInputCol, HasOutputCol):
    indices = Param("indices", "kept feature-slot indices")

    def _transform(self, df):
        x = jnp.asarray(as_2d_features(df, self.getInputCol()))
        idx = jnp.asarray(self.getIndices(), dtype=jnp.int32)
        return df.with_column(self.getOutputCol(), x[:, idx])

    def _trace_ok(self, schema, n_rows):
        ic = self.getInputCol()
        if ic not in schema:
            return False
        dtype, shape = schema[ic]
        return jittable_dtype(dtype) and len(shape) == 1

    def _trace(self, cols):
        idx = jnp.asarray(self.getIndices(), dtype=jnp.int32)
        out = dict(cols)
        out[self.getOutputCol()] = cols[self.getInputCol()][:, idx]
        return out
