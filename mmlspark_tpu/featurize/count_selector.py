"""Feature-slot selection by nonzero count.

Reference ``featurize/CountSelector.scala``: drop feature-vector slots that
are zero for every row (dead features inflate histogram work on device).
"""

from __future__ import annotations

import numpy as np

from ..core import Estimator, Model, Param
from ..core.contracts import HasInputCol, HasOutputCol
from ..core.utils import as_2d_features


class CountSelector(Estimator, HasInputCol, HasOutputCol):
    def _fit(self, df):
        x = as_2d_features(df, self.getInputCol())
        keep = np.flatnonzero((x != 0).any(axis=0)).tolist()
        model = CountSelectorModel().setIndices(keep)
        self._copy_params_to(model)
        return model


class CountSelectorModel(Model, HasInputCol, HasOutputCol):
    indices = Param("indices", "kept feature-slot indices")

    def _transform(self, df):
        x = as_2d_features(df, self.getInputCol())
        idx = np.asarray(self.getIndices(), dtype=np.int64)
        return df.with_column(self.getOutputCol(), x[:, idx])
