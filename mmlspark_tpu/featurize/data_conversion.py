"""Column type conversion.

Reference ``featurize/DataConversion.scala``: cast a set of columns to a
target type (boolean/byte/short/integer/long/float/double/string/date).

Numeric targets are pure dtype casts (traceable — ``_trace`` maps them
onto the nearest jax dtype inside a fused segment; the eager path keeps
exact numpy dtypes, e.g. real float64, which XLA's f32-default world
cannot represent). String/date targets are host conversions.
"""

from __future__ import annotations

import numpy as np

from ..core import Transformer, Param, TypeConverters as TC
from ..core.contracts import HasInputCols
from ..core.dataframe import jittable_dtype, object_column

_CONVERSIONS = {
    "boolean": np.bool_,
    "byte": np.int8,
    "short": np.int16,
    "integer": np.int32,
    "long": np.int64,
    "float": np.float32,
    "double": np.float64,
    "string": object,
    "date": "datetime64[s]",
}

# targets a traced segment can produce (dtype casts XLA supports; jax
# demotes 64-bit to 32-bit without x64, so long/double stay eager-exact
# but trace-approximate — close enough for fused inference paths)
_TRACEABLE_TARGETS = ("boolean", "byte", "short", "integer", "long",
                      "float", "double")


class DataConversion(Transformer, HasInputCols):
    convertTo = Param("convertTo", "target type: " + "|".join(_CONVERSIONS),
                      TC.toString)
    dateTimeFormat = Param("dateTimeFormat", "format for date parsing",
                           TC.toString, default="%Y-%m-%d %H:%M:%S")

    def _transform(self, df):
        target = self.getConvertTo()
        if target not in _CONVERSIONS:
            raise ValueError(f"unknown convertTo {target!r}; "
                             f"expected one of {sorted(_CONVERSIONS)}")
        cur = df
        for col in self.getInputCols():
            arr = df[col]
            if target == "string":
                out = object_column(None if v is None else str(v)
                                    for v in arr)
            elif target == "date":
                import pandas as pd
                out = pd.to_datetime(
                    pd.Series(list(arr)),
                    format=self.getDateTimeFormat()).to_numpy()
            else:
                if arr.dtype == object:
                    arr = arr.astype(np.float64)
                out = arr.astype(_CONVERSIONS[target])
            cur = cur.with_column(col, out)
        return cur

    def _trace_ok(self, schema, n_rows):
        return self.getConvertTo() in _TRACEABLE_TARGETS and all(
            c in schema and jittable_dtype(schema[c][0])
            for c in self.getInputCols())

    def _trace(self, cols):
        target = _CONVERSIONS[self.getConvertTo()]
        out = dict(cols)
        for col in self.getInputCols():
            out[col] = cols[col].astype(target)
        return out
