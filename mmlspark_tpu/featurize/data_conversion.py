"""Column type conversion.

Reference ``featurize/DataConversion.scala``: cast a set of columns to a
target type (boolean/byte/short/integer/long/float/double/string/date).
"""

from __future__ import annotations

import numpy as np

from ..core import Transformer, Param, TypeConverters as TC
from ..core.contracts import HasInputCols

_CONVERSIONS = {
    "boolean": np.bool_,
    "byte": np.int8,
    "short": np.int16,
    "integer": np.int32,
    "long": np.int64,
    "float": np.float32,
    "double": np.float64,
    "string": object,
    "date": "datetime64[s]",
}


class DataConversion(Transformer, HasInputCols):
    convertTo = Param("convertTo", "target type: " + "|".join(_CONVERSIONS),
                      TC.toString)
    dateTimeFormat = Param("dateTimeFormat", "format for date parsing",
                           TC.toString, default="%Y-%m-%d %H:%M:%S")

    def _transform(self, df):
        target = self.getConvertTo()
        if target not in _CONVERSIONS:
            raise ValueError(f"unknown convertTo {target!r}; "
                             f"expected one of {sorted(_CONVERSIONS)}")
        cur = df
        for col in self.getInputCols():
            arr = df[col]
            if target == "string":
                out = np.asarray([None if v is None else str(v)
                                  for v in arr.tolist()], dtype=object)
            elif target == "date":
                import pandas as pd
                out = pd.to_datetime(
                    pd.Series(arr.tolist()),
                    format=self.getDateTimeFormat()).to_numpy()
            else:
                if arr.dtype == object:
                    arr = np.asarray(arr.tolist(), dtype=np.float64)
                out = arr.astype(_CONVERSIONS[target])
            cur = cur.with_column(col, out)
        return cur
