"""Categorical value indexing.

Reference ``featurize/ValueIndexer.scala`` / ``IndexToValue.scala`` +
categorical metadata (``core/schema/Categoricals.scala``): map arbitrary
category values to dense integer indices (and back), recording the level
order on the model so downstream stages (one-hot, label decoding) agree.
"""

from __future__ import annotations

import numpy as np

from ..core import Estimator, Model, Param, TypeConverters as TC
from ..core.contracts import HasInputCol, HasOutputCol


class ValueIndexer(Estimator, HasInputCol, HasOutputCol):
    """Fit: collect distinct values (sorted); transform: value → index."""

    def _fit(self, df):
        col = df[self.getInputCol()]
        if col.dtype == object:
            levels = sorted({v for v in col.tolist() if v is not None},
                            key=lambda v: str(v))
        else:
            levels = np.unique(col[~_isnan(col)]).tolist()
        model = ValueIndexerModel().setLevels(list(levels))
        self._copy_params_to(model)
        return model


class ValueIndexerModel(Model, HasInputCol, HasOutputCol):
    levels = Param("levels", "ordered category levels")
    unknownIndex = Param("unknownIndex",
                         "index assigned to unseen values (-1 = error)",
                         TC.toInt, default=-1)

    def _transform(self, df):
        levels = self.getLevels()
        lookup = {v: i for i, v in enumerate(levels)}
        col = df[self.getInputCol()]
        unknown = self.getUnknownIndex()
        out = np.empty(len(col), dtype=np.int64)
        for i, v in enumerate(col.tolist()):
            if v in lookup:
                out[i] = lookup[v]
            elif unknown >= 0:
                out[i] = unknown
            else:
                raise ValueError(f"unseen value {v!r} in column "
                                 f"{self.getInputCol()!r}")
        return df.with_column(self.getOutputCol(), out)


class IndexToValue(Model, HasInputCol, HasOutputCol):
    """Inverse mapping: index column → original values."""

    levels = Param("levels", "ordered category levels")

    def _transform(self, df):
        levels = self.getLevels()
        idx = df[self.getInputCol()].astype(np.int64)
        values = np.empty(len(idx), dtype=object)
        for i, j in enumerate(idx):
            values[i] = levels[j]
        arr = np.asarray(values)
        try:
            arr = arr.astype(type(levels[0])) if levels else arr
        except (ValueError, TypeError):
            pass
        return df.with_column(self.getOutputCol(), arr)


def _isnan(arr: np.ndarray) -> np.ndarray:
    if arr.dtype.kind == "f":
        return np.isnan(arr)
    return np.zeros(arr.shape[0], dtype=bool)
