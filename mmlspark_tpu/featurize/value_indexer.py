"""Categorical value indexing.

Reference ``featurize/ValueIndexer.scala`` / ``IndexToValue.scala`` +
categorical metadata (``core/schema/Categoricals.scala``): map arbitrary
category values to dense integer indices (and back), recording the level
order on the model so downstream stages (one-hot, label decoding) agree.

Numeric level sets index through a ``searchsorted`` gather — pure
jax.numpy, traceable into fused segments. String levels stay a host
dict lookup (genuinely host-bound, like the tokenizers).
"""

from __future__ import annotations

from ..core import Estimator, Model, Param, TypeConverters as TC
from ..core.contracts import HasInputCol, HasOutputCol
from ..core.dataframe import (jittable_dtype, object_column, to_host,
                              to_host_list, unique_host)
from ..core.lazyjnp import jnp


def _numeric_levels(levels) -> bool:
    try:
        if not all(isinstance(v, (int, float)) and not isinstance(v, bool)
                   for v in levels):
            return False
    except TypeError:
        return False
    # int levels beyond the device's 32-bit lattice cannot build the
    # traced lookup table (jnp.asarray raises OverflowError at trace
    # time); the fit path keeps them int64-exact, so gate the traced
    # form off and let the host dict lookup handle them
    return all(-2 ** 31 <= v < 2 ** 31 for v in levels
               if isinstance(v, int))


class ValueIndexer(Estimator, HasInputCol, HasOutputCol):
    """Fit: collect distinct values (sorted); transform: value → index."""

    def _fit(self, df):
        col = df[self.getInputCol()]
        if col.dtype == object:
            levels = sorted({v for v in col if v is not None},
                            key=lambda v: str(v))
        else:
            # fit-time uniqueness stays on host and EXACT: the fitted
            # levels must equal the values transform will look up
            # (unique_host's docstring has the 32-bit demotion story)
            levels = to_host_list(unique_host(col, drop_nan=True))
        model = ValueIndexerModel().setLevels(list(levels))
        self._copy_params_to(model)
        return model


class ValueIndexerModel(Model, HasInputCol, HasOutputCol):
    levels = Param("levels", "ordered category levels")
    unknownIndex = Param("unknownIndex",
                         "index assigned to unseen values (-1 = error)",
                         TC.toInt, default=-1)

    def _transform(self, df):
        levels = self.getLevels()
        lookup = {v: i for i, v in enumerate(levels)}
        col = df[self.getInputCol()]
        unknown = self.getUnknownIndex()
        out = []
        for v in col:
            if v in lookup:
                out.append(lookup[v])
            elif unknown >= 0:
                out.append(unknown)
            else:
                raise ValueError(f"unseen value {v!r} in column "
                                 f"{self.getInputCol()!r}")
        # this is the HOST lookup path (string levels can never fuse):
        # stay on host — no device round-trip for a dict lookup. int32
        # matches the traced form's output dtype
        return df.with_column(self.getOutputCol(),
                              to_host(out).astype("int32"))

    def _trace_ok(self, schema, n_rows):
        ic = self.getInputCol()
        # the traced form cannot raise on unseen values: it needs a
        # well-defined unknownIndex and numeric, sorted-comparable levels
        return ic in schema and jittable_dtype(schema[ic][0]) \
            and self.getUnknownIndex() >= 0 \
            and _numeric_levels(self.getLevels())

    def _trace(self, cols):
        levels = jnp.asarray(sorted(self.getLevels()))
        x = cols[self.getInputCol()]
        idx = jnp.clip(jnp.searchsorted(levels, x), 0, levels.size - 1)
        hit = levels[idx] == x
        # map the sorted position back to the DECLARED level order
        order = jnp.asarray(
            [self.getLevels().index(v)
             for v in sorted(self.getLevels())], dtype=jnp.int32)
        out = dict(cols)
        out[self.getOutputCol()] = jnp.where(
            hit, order[idx], self.getUnknownIndex()).astype(jnp.int32)
        return out


class IndexToValue(Model, HasInputCol, HasOutputCol):
    """Inverse mapping: index column → original values."""

    levels = Param("levels", "ordered category levels")

    def _transform(self, df):
        levels = self.getLevels()
        idx = df[self.getInputCol()].astype(int)
        values = object_column(levels[int(j)] for j in idx)
        try:
            arr = values.astype(type(levels[0])) if levels else values
        except (ValueError, TypeError):
            arr = values
        return df.with_column(self.getOutputCol(), arr)

    def _trace_ok(self, schema, n_rows):
        ic = self.getInputCol()
        return ic in schema and jittable_dtype(schema[ic][0]) \
            and _numeric_levels(self.getLevels())

    def _trace(self, cols):
        levels = jnp.asarray(self.getLevels())
        out = dict(cols)
        out[self.getOutputCol()] = levels[
            cols[self.getInputCol()].astype(jnp.int32)]
        return out
