"""Vector assembly and one-hot encoding stages.

Reference: the SparkML ``VectorAssembler``/``OneHotEncoder`` surface the
ecosystem leans on (tested at
``core/schema/VerifyFastVectorAssembler.scala`` and
``core/ml/OneHotEncoderSpec.scala``; ``Featurize`` composes the same
operations internally, ``featurize/Featurize.scala:36``). Standalone
stages so user pipelines can assemble/encode without the full
auto-featurizer.

Both are pure data movement (concatenate, compare-and-select) over
jax.numpy, so both carry ``_trace`` forms and fuse into whole-pipeline
XLA segments — with two static-shape caveats: ``handleInvalid="skip"``
makes the assembler's output length data-dependent (host-bound), and
"error" modes must raise on bad data, which a traced program cannot
(only "keep" modes fuse).
"""

from __future__ import annotations

import numpy as np

from ..core import Estimator, Model, Transformer, Param, \
    TypeConverters as TC
from ..core.contracts import HasInputCol, HasInputCols, HasOutputCol
from ..core.dataframe import jittable_dtype, to_host
from ..core.lazyjnp import jnp


def _as_matrix(arr, n: int, col: str):
    """One column → [n, w] float32 (scalars become w=1)."""
    if arr.dtype == object:
        try:
            return jnp.stack([jnp.asarray(to_host(v), jnp.float32).ravel()
                              for v in arr])
        except (ValueError, TypeError) as e:
            raise ValueError(
                f"column {col!r} has ragged/non-numeric vector rows: "
                f"{e}") from e
    return _matrixify(jnp.asarray(arr, jnp.float32), n)


def _matrixify(x, n: int):
    """[n] or [n, ...] → [n, w] (the traced-path reshape; no host)."""
    if x.ndim == 1:
        return x.reshape(n, 1)
    return x.reshape(n, -1)


class VectorAssembler(Transformer, HasInputCols, HasOutputCol):
    """Concatenate numeric scalar/vector columns into one vector column.

    ``handleInvalid``: "error" raises on NaN, "keep" propagates NaN,
    "skip" drops invalid rows (the SparkML contract).
    """

    handleInvalid = Param("handleInvalid", "error|keep|skip on NaN rows",
                          TC.toString, default="error", has_default=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(outputCol="features")

    def _transform(self, df):
        n = df.num_rows
        blocks = [_as_matrix(df[c], n, c) for c in self.getInputCols()]
        mat = jnp.concatenate(blocks, axis=1) if blocks else \
            jnp.zeros((n, 0), jnp.float32)
        bad = jnp.isnan(mat).any(axis=1)
        mode = self.get("handleInvalid")
        if mode not in ("error", "keep", "skip"):
            raise ValueError(
                f"handleInvalid={mode!r} is not one of error|keep|skip")
        if bool(bad.any()):
            if mode == "error":
                raise ValueError(
                    f"{int(bad.sum())} rows contain NaN; set "
                    "handleInvalid='keep' or 'skip'")
            if mode == "skip":
                keep = to_host(~bad)
                df = df.take(keep.nonzero()[0])
                mat = mat[keep]
        return df.with_column(self.getOutputCol(), mat)

    def _trace_ok(self, schema, n_rows):
        # "skip" drops rows (data-dependent length); "error" must raise
        # on NaN — neither has a static traced form
        if self.get("handleInvalid") != "keep":
            return False
        return all(c in schema and jittable_dtype(schema[c][0])
                   and len(schema[c][1]) <= 1
                   for c in self.getInputCols())

    def _trace(self, cols):
        first = cols[self.getInputCols()[0]] if self.getInputCols() \
            else next(iter(cols.values()))
        n = first.shape[0]
        blocks = [_matrixify(cols[c].astype(jnp.float32), n)
                  for c in self.getInputCols()]
        out = dict(cols)
        out[self.getOutputCol()] = jnp.concatenate(blocks, axis=1) \
            if blocks else jnp.zeros((n, 0), jnp.float32)
        return out


class OneHotEncoder(Estimator, HasInputCol, HasOutputCol):
    """Category indices → one-hot vectors (SparkML semantics:
    ``dropLast=True`` encodes the last category as the all-zeros
    vector, keeping the encoding linearly independent)."""

    dropLast = Param("dropLast", "last category encodes as all-zeros",
                     TC.toBoolean, default=True, has_default=True)
    handleInvalid = Param("handleInvalid",
                          "error|keep for out-of-range indices at "
                          "transform ('keep' adds a catch-all slot)",
                          TC.toString, default="error", has_default=True)

    def _fit(self, df):
        raw = df[self.getInputCol()]
        if raw.dtype.kind not in "iuf":
            raise TypeError("OneHotEncoder expects numeric category "
                            f"indices, got dtype {raw.dtype}")
        idx = jnp.asarray(raw)
        if idx.size and bool((idx < 0).any()):
            raise ValueError("category indices must be non-negative")
        size = int(idx.max()) + 1 if idx.size else 0
        model = OneHotEncoderModel().set("categorySize", size)
        self._copy_params_to(model)
        return model


class OneHotEncoderModel(Model, HasInputCol, HasOutputCol):
    categorySize = Param("categorySize", "number of fitted categories",
                         TC.toInt)
    dropLast = Param("dropLast", "last category encodes as all-zeros",
                     TC.toBoolean, default=True, has_default=True)
    handleInvalid = Param("handleInvalid",
                          "error|keep for out-of-range indices",
                          TC.toString, default="error", has_default=True)

    def _widths(self) -> tuple[int, int]:
        size = self.get("categorySize")
        keep_invalid = self.get("handleInvalid") == "keep"
        width = size + (1 if keep_invalid else 0)
        out_width = width - (1 if self.get("dropLast") else 0)
        return size, max(out_width, 0)

    def _encode(self, idx, out_width: int):
        """[n] int indices → [n, out_width] one-hot (pure jnp; the
        shared body of the eager and traced paths). Out-of-range
        indices must already be mapped to the catch-all slot by the
        caller (``jnp.where(oob, size, idx)``)."""
        return (idx[:, None] == jnp.arange(out_width)[None, :]) \
            .astype(jnp.float32)

    def _transform(self, df):
        size, out_width = self._widths()
        keep_invalid = self.get("handleInvalid") == "keep"
        idx = jnp.asarray(to_host(df[self.getInputCol()]).astype(np.int64))
        oob = (idx < 0) | (idx >= size)
        if bool(oob.any()):
            if not keep_invalid:
                raise ValueError(
                    f"{int(oob.sum())} indices outside the fitted "
                    f"[0, {size}) range; set handleInvalid='keep'")
            idx = jnp.where(oob, size, idx)  # catch-all slot
        return df.with_column(self.getOutputCol(),
                              self._encode(idx, out_width))

    def _trace_ok(self, schema, n_rows):
        ic = self.getInputCol()
        # "error" must raise on out-of-range — host-bound by contract
        return ic in schema and jittable_dtype(schema[ic][0]) \
            and self.get("handleInvalid") == "keep" \
            and len(schema[ic][1]) == 0

    def _trace(self, cols):
        size, out_width = self._widths()
        idx = cols[self.getInputCol()].astype(jnp.int32)
        idx = jnp.where((idx < 0) | (idx >= size), size, idx)
        out = dict(cols)
        out[self.getOutputCol()] = self._encode(idx, out_width)
        return out
