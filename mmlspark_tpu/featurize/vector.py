"""Vector assembly and one-hot encoding stages.

Reference: the SparkML ``VectorAssembler``/``OneHotEncoder`` surface the
ecosystem leans on (tested at
``core/schema/VerifyFastVectorAssembler.scala`` and
``core/ml/OneHotEncoderSpec.scala``; ``Featurize`` composes the same
operations internally, ``featurize/Featurize.scala:36``). Standalone
stages so user pipelines can assemble/encode without the full
auto-featurizer — the TPU design keeps them host-side numpy: both are
data-plumbing (concatenation, indexing), not compute.
"""

from __future__ import annotations

import numpy as np

from ..core import Estimator, Model, Transformer, Param, \
    TypeConverters as TC
from ..core.contracts import HasInputCol, HasInputCols, HasOutputCol


def _as_matrix(arr, n: int, col: str) -> np.ndarray:
    """One column → [n, w] float32 (scalars become w=1)."""
    if arr.dtype == object:
        try:
            return np.stack([np.asarray(v, np.float32).ravel()
                             for v in arr])
        except ValueError as e:
            raise ValueError(
                f"column {col!r} has ragged/non-numeric vector rows: "
                f"{e}") from e
    if arr.ndim == 1:
        return np.asarray(arr, np.float32).reshape(n, 1)
    return np.asarray(arr, np.float32).reshape(n, -1)


class VectorAssembler(Transformer, HasInputCols, HasOutputCol):
    """Concatenate numeric scalar/vector columns into one vector column.

    ``handleInvalid``: "error" raises on NaN, "keep" propagates NaN,
    "skip" drops invalid rows (the SparkML contract).
    """

    handleInvalid = Param("handleInvalid", "error|keep|skip on NaN rows",
                          TC.toString, default="error", has_default=True)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._setDefault(outputCol="features")

    def _transform(self, df):
        n = df.num_rows
        blocks = [_as_matrix(df[c], n, c) for c in self.getInputCols()]
        mat = np.concatenate(blocks, axis=1) if blocks else \
            np.zeros((n, 0), np.float32)
        bad = np.isnan(mat).any(axis=1)
        mode = self.get("handleInvalid")
        if mode not in ("error", "keep", "skip"):
            raise ValueError(
                f"handleInvalid={mode!r} is not one of error|keep|skip")
        if bad.any():
            if mode == "error":
                raise ValueError(
                    f"{int(bad.sum())} rows contain NaN; set "
                    "handleInvalid='keep' or 'skip'")
            if mode == "skip":
                df = df.take(np.flatnonzero(~bad))
                mat = mat[~bad]
        return df.with_column(self.getOutputCol(), mat)


class OneHotEncoder(Estimator, HasInputCol, HasOutputCol):
    """Category indices → one-hot vectors (SparkML semantics:
    ``dropLast=True`` encodes the last category as the all-zeros
    vector, keeping the encoding linearly independent)."""

    dropLast = Param("dropLast", "last category encodes as all-zeros",
                     TC.toBoolean, default=True, has_default=True)
    handleInvalid = Param("handleInvalid",
                          "error|keep for out-of-range indices at "
                          "transform ('keep' adds a catch-all slot)",
                          TC.toString, default="error", has_default=True)

    def _fit(self, df):
        idx = np.asarray(df[self.getInputCol()])
        if idx.dtype.kind not in "iuf":
            raise TypeError("OneHotEncoder expects numeric category "
                            f"indices, got dtype {idx.dtype}")
        if idx.size and (idx < 0).any():
            raise ValueError("category indices must be non-negative")
        size = int(idx.max()) + 1 if idx.size else 0
        model = OneHotEncoderModel().set("categorySize", size)
        self._copy_params_to(model)
        return model


class OneHotEncoderModel(Model, HasInputCol, HasOutputCol):
    categorySize = Param("categorySize", "number of fitted categories",
                         TC.toInt)
    dropLast = Param("dropLast", "last category encodes as all-zeros",
                     TC.toBoolean, default=True, has_default=True)
    handleInvalid = Param("handleInvalid",
                          "error|keep for out-of-range indices",
                          TC.toString, default="error", has_default=True)

    def _transform(self, df):
        size = self.get("categorySize")
        drop = self.get("dropLast")
        keep_invalid = self.get("handleInvalid") == "keep"
        idx = np.asarray(df[self.getInputCol()]).astype(np.int64)
        width = size + (1 if keep_invalid else 0)
        oob = (idx < 0) | (idx >= size)
        if oob.any():
            if not keep_invalid:
                raise ValueError(
                    f"{int(oob.sum())} indices outside the fitted "
                    f"[0, {size}) range; set handleInvalid='keep'")
            idx = np.where(oob, size, idx)  # catch-all slot
        out_width = width - (1 if drop else 0)
        mat = np.zeros((len(idx), max(out_width, 0)), np.float32)
        valid = idx < out_width
        mat[np.flatnonzero(valid), idx[valid]] = 1.0
        return df.with_column(self.getOutputCol(), mat)
